"""Custom C++ op runtime (reference: paddle/fluid/extension/ extension.h +
python/paddle/utils/cpp_extension — user-compiled ops loaded at runtime).

TPU-native: custom ops are XLA FFI handlers. `load()` compiles the user's
.cc against jaxlib's bundled XLA FFI headers into a shared library, dlopens
it, registers every requested handler with jax.ffi, and returns a module-ish
object whose attributes invoke the op through jax.ffi.ffi_call — fully
jit-compatible (the handler becomes a custom-call in the XLA program).

Handlers run on the registering platform (cpu by default; a TPU build would
register a device handler the same way). Like the reference, autograd
support requires the author to define and compose a grad op explicitly.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import tempfile
from typing import Optional, Sequence

import jax
import numpy as np

from ..core.tensor import Tensor, apply


class CustomOp:
    """One registered FFI handler, callable on Tensors/arrays."""

    def __init__(self, name: str):
        self.name = name

    def __call__(self, *args, out_shape=None, out_dtype=None, **attrs):
        from ..tensor.creation import _t
        if out_shape is None:
            out_shape = _t(args[0]).shape
        if out_dtype is None:
            out_dtype = _t(args[0]).dtype

        def f(*arrays):
            call = jax.ffi.ffi_call(
                self.name,
                jax.ShapeDtypeStruct(tuple(out_shape), out_dtype))
            return call(*arrays, **attrs)

        return apply(f, *[_t(a) for a in args])


class CustomOpLibrary:
    def __init__(self, lib_path: str, handlers: Sequence[str]):
        self._lib = ctypes.CDLL(lib_path)
        self.lib_path = lib_path
        for name in handlers:
            fn = getattr(self._lib, name)
            jax.ffi.register_ffi_target(
                name, jax.ffi.pycapsule(fn), platform="cpu")
            setattr(self, name, CustomOp(name))


def load(name: str, sources: Sequence[str], handlers: Sequence[str],
         extra_cxx_flags: Optional[Sequence[str]] = None,
         build_directory: Optional[str] = None,
         verbose: bool = False) -> CustomOpLibrary:
    """Compile + load custom FFI ops (cpp_extension.load analog).

    sources: .cc files defining XLA_FFI_DEFINE_HANDLER_SYMBOL handlers.
    handlers: exported handler symbol names to register.
    """
    build_dir = build_directory or os.path.join(
        tempfile.gettempdir(), f"paddle_tpu_ext_{name}")
    os.makedirs(build_dir, exist_ok=True)
    out = os.path.join(build_dir, f"lib{name}.so")
    cmd = ["g++", "-O2", "-std=c++17", "-shared", "-fPIC",
           f"-I{jax.ffi.include_dir()}", "-o", out] + list(sources) + \
        list(extra_cxx_flags or [])
    if verbose:
        print("[cpp_extension]", " ".join(cmd))
    r = subprocess.run(cmd, capture_output=True, text=True)
    if r.returncode != 0:
        raise RuntimeError(
            f"custom op build failed:\n{r.stderr[-2000:]}")
    return CustomOpLibrary(out, handlers)
