"""Bounded LRU cache for jitted executables (ISSUE 7 satellite).

Every place the runtime builds a jax.jit program per static shape/knob
combination (one-shot `generate()`'s prefill+decode loop; historically the
LLM engine's per-pow2-bucket prefill zoo, now gone) shares this one
policy: hold at most `cap` executables, evict least-recently-used, and
WARN when the caller's shapes churn — a cache that keeps evicting is a
cache that keeps recompiling, and on TPU each recompile is seconds of
dead time that should be fixed at the call site (bucket the shapes) rather
than hidden by a bigger cap.
"""
from __future__ import annotations

import logging
from collections import OrderedDict
from typing import Callable, Hashable

_log = logging.getLogger("paddle_tpu.jit_cache")

# evictions within one `churn_window` builds that trigger the warning
_CHURN_FRACTION = 0.5

# miss listeners: cb(cache_name, key, build_seconds), called after every
# cache-miss build across ALL JitLRUCache instances. The recompile
# sentinel (obs.goodput) registers here on backends without
# jax.monitoring compile events. List copy on mutation so iteration
# never races registration; the hit path pays one truthiness check.
_MISS_LISTENERS: list = []


def add_miss_listener(cb):
    global _MISS_LISTENERS
    _MISS_LISTENERS = _MISS_LISTENERS + [cb]


def remove_miss_listener(cb):
    global _MISS_LISTENERS
    # equality, not identity: bound methods are re-created per access
    _MISS_LISTENERS = [c for c in _MISS_LISTENERS if c != cb]


# listeners that already got their one WARNING for raising (by id of the
# registered callable); later raises from the same listener log at debug
# so a persistently-broken observer cannot flood the build path's logs
_WARNED_LISTENERS: set = set()


def _notify_miss(name: str, key, seconds: float):
    """Fan a miss out to every listener, isolating each: a listener that
    raises must never poison the build, drop the executable, or starve
    the listeners after it (ISSUE 12 satellite)."""
    for cb in _MISS_LISTENERS:
        try:
            cb(name, key, seconds)
        except Exception:
            if id(cb) not in _WARNED_LISTENERS:
                _WARNED_LISTENERS.add(id(cb))
                _log.warning(
                    "%s jit-cache miss listener %r raised; executable "
                    "kept, listener isolated (further raises from it "
                    "log at debug)", name, cb, exc_info=True)
            else:
                _log.debug("%s miss listener raised", name,
                           exc_info=True)


class JitLRUCache:
    """OrderedDict-backed LRU of compiled callables.

    get_or_build(key, build) returns the cached executable for `key`,
    building (and possibly evicting) on miss. `evictions` is a lifetime
    counter the tests pin; the churn warning fires (once per window) when
    at least half the last `churn_window` builds caused an eviction."""

    def __init__(self, cap: int, name: str = "jit", churn_window: int = 8):
        if cap < 1:
            raise ValueError(f"cap must be >= 1, got {cap}")
        self.cap = int(cap)
        self.name = name
        self.churn_window = int(churn_window)
        self._cache: "OrderedDict[Hashable, object]" = OrderedDict()
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self._recent_evictions = 0
        self._recent_builds = 0

    def __len__(self) -> int:
        return len(self._cache)

    def __contains__(self, key: Hashable) -> bool:
        return key in self._cache

    def get_or_build(self, key: Hashable, build: Callable[[], object]):
        if key in self._cache:
            self.hits += 1
            self._cache.move_to_end(key)
            return self._cache[key]
        self.misses += 1
        if _MISS_LISTENERS:
            import time
            t0 = time.monotonic()
            fn = build()
            dt = time.monotonic() - t0
            _notify_miss(self.name, key, dt)
        else:
            fn = build()
        self._cache[key] = fn
        self._recent_builds += 1
        while len(self._cache) > self.cap:
            evicted_key, _ = self._cache.popitem(last=False)
            self.evictions += 1
            self._recent_evictions += 1
            _log.debug("%s cache evicted %r (cap %d)", self.name,
                       evicted_key, self.cap)
        if self._recent_builds >= self.churn_window:
            if (self._recent_evictions
                    >= self._recent_builds * _CHURN_FRACTION):
                _log.warning(
                    "%s jit cache churning: %d of the last %d builds "
                    "evicted a compiled executable (cap %d). Callers are "
                    "cycling more static shapes than the cache holds — "
                    "bucket the shapes or raise the cap",
                    self.name, self._recent_evictions, self._recent_builds,
                    self.cap)
            self._recent_builds = 0
            self._recent_evictions = 0
        return fn

    def stats(self) -> dict:
        return {"size": len(self._cache), "cap": self.cap,
                "hits": self.hits, "misses": self.misses,
                "evictions": self.evictions}
