from . import cpp_extension  # noqa: F401


def set_printoptions(precision=None, threshold=None, edgeitems=None,
                     sci_mode=None, linewidth=None):
    """paddle.set_printoptions — forwards to numpy's print options (tensor
    reprs render via numpy)."""
    import numpy as np
    kwargs = {}
    if precision is not None:
        kwargs["precision"] = precision
    if threshold is not None:
        kwargs["threshold"] = threshold
    if edgeitems is not None:
        kwargs["edgeitems"] = edgeitems
    if linewidth is not None:
        kwargs["linewidth"] = linewidth
    if sci_mode is not None:
        kwargs["suppress"] = not sci_mode
    np.set_printoptions(**kwargs)


def deprecated(update_to="", since="", reason="", level=0):
    """paddle.utils.deprecated decorator: warns once per call site."""
    import functools
    import warnings

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            msg = (f"API {fn.__module__}.{fn.__name__} is deprecated "
                   f"since {since or 'this release'}"
                   + (f", use {update_to} instead" if update_to else "")
                   + (f" ({reason})" if reason else ""))
            warnings.warn(msg, DeprecationWarning, stacklevel=2)
            return fn(*args, **kwargs)
        return wrapper

    return deco


def run_check():
    """paddle.utils.run_check: prove the install works end-to-end — a tiny
    matmul + grad on the default backend, printed like the reference's
    install_check."""
    import jax
    import numpy as np
    import paddle_tpu as paddle
    x = paddle.to_tensor(np.ones((4, 4), np.float32))
    x.stop_gradient = False
    y = (x @ x).sum()
    y.backward()
    assert np.isfinite(float(y.item()))
    print(f"PaddlePaddle(TPU-native) works on {jax.default_backend()}! "
          f"devices={jax.device_count()}")


def require_version(min_version, max_version=None):
    """paddle.utils.require_version: assert the installed version is in
    [min_version, max_version]."""
    import paddle_tpu as paddle

    def key(v):
        return tuple(int(p) for p in str(v).split(".")[:3] if p.isdigit())

    cur = key(paddle.__version__)
    if key(min_version) > cur:
        raise RuntimeError(
            f"requires paddle >= {min_version}, got {paddle.__version__}")
    if max_version is not None and key(max_version) < cur:
        raise RuntimeError(
            f"requires paddle <= {max_version}, got {paddle.__version__}")


def try_import(module_name, err_msg=None):
    """paddle.utils.try_import: import or raise a friendly error."""
    import importlib
    try:
        return importlib.import_module(module_name)
    except ImportError as e:
        raise ImportError(
            err_msg or f"{module_name} is required but not installed "
                       f"(pip install {module_name})") from e
