from . import cpp_extension  # noqa: F401


def set_printoptions(precision=None, threshold=None, edgeitems=None,
                     sci_mode=None, linewidth=None):
    """paddle.set_printoptions — forwards to numpy's print options (tensor
    reprs render via numpy)."""
    import numpy as np
    kwargs = {}
    if precision is not None:
        kwargs["precision"] = precision
    if threshold is not None:
        kwargs["threshold"] = threshold
    if edgeitems is not None:
        kwargs["edgeitems"] = edgeitems
    if linewidth is not None:
        kwargs["linewidth"] = linewidth
    if sci_mode is not None:
        kwargs["suppress"] = not sci_mode
    np.set_printoptions(**kwargs)
