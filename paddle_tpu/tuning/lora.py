"""LoRA adapter definition/injection for gpt/llama fine-tuning.

Low-rank deltas ``scaling * (x @ A^T @ B^T)`` are injected on the attention and
MLP projections of each decoder layer.  The base weights are frozen at
injection time, so ``Layer.functional_state()`` returns a params tree holding
*only* the adapter leaves — ``AsyncCheckpointManager`` then snapshots just the
tiny adapter tree during fine-tuning, and the same tree is what gets published
as a certified ``AdapterWeightSet`` for serving.

The canonical adapter tree (what ``adapter_state_dict`` emits and the serving
``AdapterBank`` consumes) is::

    {"0": {"qkv_proj": {"A": [r, in], "B": [out, r]}, ...}, "1": {...}, ...}

keyed by decoder-layer index then target-site name.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from ..core.tensor import apply
from ..nn import initializer as I
from ..nn.layer.layers import Layer
from ..ops.lora import lora_matmul

GPT_TARGETS = ("qkv_proj", "out_proj", "linear1", "linear2")
LLAMA_TARGETS = ("q_proj", "k_proj", "v_proj", "o_proj",
                 "gate_proj", "up_proj", "down_proj")


@dataclass
class LoRAConfig:
    rank: int = 8
    alpha: float = 16.0
    targets: Optional[Tuple[str, ...]] = None  # None = all sites for the arch
    init_std: float = 0.02

    def __post_init__(self):
        if self.rank < 1:
            raise ValueError(f"LoRA rank must be >= 1, got {self.rank}")
        if self.alpha <= 0:
            raise ValueError(f"LoRA alpha must be > 0, got {self.alpha}")
        if self.targets is not None:
            self.targets = tuple(self.targets)


class LoRALinear(Layer):
    """Wraps a linear projection with a trainable low-rank residual.

    ``lora_B`` starts at zero so the wrapped module is exactly the base
    projection until training moves it.
    """

    def __init__(self, base, rank, alpha, init_std=0.02):
        super().__init__()
        self.base = base
        w = _base_weight(base)
        in_f, out_f = int(w.shape[0]), int(w.shape[1])
        self.rank, self.alpha = int(rank), float(alpha)
        self.scaling = self.alpha / self.rank
        self.lora_A = self.create_parameter(
            [self.rank, in_f], default_initializer=I.Normal(0.0, init_std))
        self.lora_B = self.create_parameter(
            [out_f, self.rank], default_initializer=I.Constant(0.0))

    def forward(self, x):
        y = self.base(x)
        scaling = self.scaling

        def _delta(xv, Av, Bv):
            return (lora_matmul(xv, Av, Bv) * scaling).astype(xv.dtype)

        return y + apply(_delta, x, self.lora_A, self.lora_B)


def _base_weight(module):
    base = module.base if isinstance(module, LoRALinear) else module
    if not hasattr(base, "weight"):
        raise TypeError(f"LoRA target {type(base).__name__} has no weight")
    return base.weight


def _decoder_layers(model):
    """-> (list of decoder layers, arch name 'gpt'|'llama')."""
    if hasattr(model, "gpt"):
        return list(model.gpt.layers), "gpt"
    if hasattr(model, "llama"):
        return list(model.llama.layers), "llama"
    if hasattr(model, "layers"):
        layers = list(model.layers)
        if layers and hasattr(layers[0].self_attn, "qkv_proj"):
            return layers, "gpt"
        return layers, "llama"
    raise TypeError(f"cannot locate decoder layers on {type(model).__name__}")


def default_lora_targets(model) -> Tuple[str, ...]:
    _, arch = _decoder_layers(model)
    return GPT_TARGETS if arch == "gpt" else LLAMA_TARGETS


def _site_owner(layer, name, arch):
    """Resolve the module owning a target projection within a decoder layer."""
    if arch == "gpt":
        owner = layer.self_attn if name in ("qkv_proj", "out_proj") else layer
    else:
        owner = (layer.self_attn
                 if name in ("q_proj", "k_proj", "v_proj", "o_proj")
                 else layer.mlp)
    if not hasattr(owner, name):
        raise ValueError(f"unknown LoRA target {name!r} for arch {arch!r}")
    return owner


def target_sites(model, targets=None):
    """Per-decoder-layer dims of each target site.

    -> (list over layers of {site: (in_dim, out_dim)}, arch).  Raises if
    layers disagree on a site's dims (the stacked serving bank requires a
    homogeneous stack).
    """
    layers, arch = _decoder_layers(model)
    targets = tuple(targets) if targets else (
        GPT_TARGETS if arch == "gpt" else LLAMA_TARGETS)
    sites: List[Dict[str, Tuple[int, int]]] = []
    for layer in layers:
        dims = {}
        for name in targets:
            w = _base_weight(getattr(_site_owner(layer, name, arch), name))
            dims[name] = (int(w.shape[0]), int(w.shape[1]))
        sites.append(dims)
    for dims in sites[1:]:
        if dims != sites[0]:
            raise ValueError("LoRA target dims differ across decoder layers; "
                             "a stacked adapter bank requires homogeneous "
                             f"layers, got {dims} vs {sites[0]}")
    return sites, arch


def adapter_signature(model, rank, alpha=None, targets=None) -> dict:
    """JSON-serializable signature binding an adapter to its base model.

    Shipped inside the `AdapterWeightSet` manifest and compared (typed
    refusal) against the serving bank before a row load.
    """
    sites, arch = target_sites(model, targets)
    return {
        "arch": arch,
        "num_layers": len(sites),
        "rank": int(rank),
        "alpha": None if alpha is None else float(alpha),
        "targets": sorted(sites[0].keys()),
        "dims": {name: [int(i), int(o)] for name, (i, o) in
                 sorted(sites[0].items())},
    }


def inject_lora(model, config: LoRAConfig):
    """Freeze every existing parameter and wrap the target projections.

    Returns the (mutated) model.  After injection ``functional_state()``
    yields a params tree of only ``lora_A``/``lora_B`` leaves; everything
    else rides the buffers tree.
    """
    layers, arch = _decoder_layers(model)
    targets = config.targets or (GPT_TARGETS if arch == "gpt"
                                 else LLAMA_TARGETS)
    for _, p in model.named_parameters():
        p.trainable = False
        p.stop_gradient = True
    for layer in layers:
        for name in targets:
            owner = _site_owner(layer, name, arch)
            current = getattr(owner, name)
            if isinstance(current, LoRALinear):
                raise ValueError(f"LoRA already injected at {name!r}")
            setattr(owner, name, LoRALinear(current, config.rank,
                                            config.alpha, config.init_std))
    return model


def _iter_adapted_sites(model):
    layers, arch = _decoder_layers(model)
    for i, layer in enumerate(layers):
        for name in (GPT_TARGETS if arch == "gpt" else LLAMA_TARGETS):
            try:
                owner = _site_owner(layer, name, arch)
            except ValueError:
                continue
            module = getattr(owner, name, None)
            if isinstance(module, LoRALinear):
                yield i, name, module


def lora_parameters(model):
    """The trainable adapter parameters (feed these to the optimizer)."""
    out = []
    for _, _, module in _iter_adapted_sites(model):
        out.extend([module.lora_A, module.lora_B])
    return out


def adapter_state_dict(model) -> Dict[str, Dict[str, Dict[str, np.ndarray]]]:
    """Extract the canonical adapter tree (host numpy, float32)."""
    tree: Dict[str, Dict[str, Dict[str, np.ndarray]]] = {}
    for i, name, module in _iter_adapted_sites(model):
        tree.setdefault(str(i), {})[name] = {
            "A": np.asarray(module.lora_A.data, dtype=np.float32),
            "B": np.asarray(module.lora_B.data, dtype=np.float32),
        }
    if not tree:
        raise ValueError("model has no injected LoRA adapters")
    return tree


def load_adapter_state(model, tree):
    """Load a canonical adapter tree back into an injected model."""
    seen = 0
    for i, name, module in _iter_adapted_sites(model):
        entry = tree.get(str(i), {}).get(name)
        if entry is None:
            raise ValueError(f"adapter tree missing layer {i} site {name!r}")
        A = jnp.asarray(entry["A"], dtype=module.lora_A.data.dtype)
        B = jnp.asarray(entry["B"], dtype=module.lora_B.data.dtype)
        if A.shape != module.lora_A.data.shape or \
                B.shape != module.lora_B.data.shape:
            raise ValueError(
                f"adapter shape mismatch at layer {i} site {name!r}: "
                f"{A.shape}/{B.shape} vs "
                f"{module.lora_A.data.shape}/{module.lora_B.data.shape}")
        module.lora_A.data = A
        module.lora_B.data = B
        seen += 1
    if not seen:
        raise ValueError("model has no injected LoRA adapters")
    return model


def merge_adapter_delta(model):
    """Fold each adapter delta into its base weight (serving without a bank).

    After merging, the LoRA residual is zeroed so the wrapped module keeps
    producing the merged output.
    """
    for _, _, module in _iter_adapted_sites(model):
        w = module.base.weight
        dW = module.scaling * jnp.einsum(
            "ri,or->io", module.lora_A.data.astype(jnp.float32),
            module.lora_B.data.astype(jnp.float32))
        w.data = (w.data.astype(jnp.float32) + dW).astype(w.data.dtype)
        module.lora_B.data = jnp.zeros_like(module.lora_B.data)
    return model
