"""Parameter-efficient fine-tuning (LoRA) for the fine-tune-and-serve loop."""
from .lora import (LoRAConfig, LoRALinear, adapter_signature,  # noqa: F401
                   adapter_state_dict, default_lora_targets, inject_lora,
                   load_adapter_state, lora_parameters, merge_adapter_delta,
                   target_sites)
