"""paddle.linalg namespace (reference: python/paddle/linalg.py — a re-export
of the tensor.linalg operator set under the stable `paddle.linalg.*` names).
"""
from .tensor.linalg import (bincount, bmm, cholesky, cholesky_solve,  # noqa
                            cond, corrcoef, cross, det, dist, dot, eig, eigh,
                            eigvals, eigvalsh, histogram, inv, lu, matmul,
                            matrix_power, matrix_rank, mm, multi_dot, norm,
                            pinv, qr, slogdet, solve, svd, t,
                            triangular_solve)

__all__ = ["cholesky", "cholesky_solve", "cond", "corrcoef", "cross", "det",
           "dist", "dot", "eig", "eigh", "eigvals", "eigvalsh", "inv", "lu",
           "matmul", "matrix_power", "matrix_rank", "multi_dot", "norm",
           "pinv", "qr", "slogdet", "solve", "svd", "triangular_solve"]
