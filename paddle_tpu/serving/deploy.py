"""Zero-downtime rolling weight deployment (ISSUE 16).

`DeploymentController` rolls a certified `WeightSet` across a
`ReplicaRouter` fleet one replica at a time, without dropping a single
admitted stream and without a single recompile:

    drain   — the replica leaves placement; its in-flight streams are
              failover-re-prefilled onto same-version survivors (the
              PR 14 machinery) or, when it is the last replica of its
              version, left to finish in place while the replica stays
              pumped but placement-excluded
    swap    — `LLMEngine.replace_params`: the params attribute is
              rebound under the scheduler lock with a tree whose
              abstract signature is verified identical, so the warm
              unified-step executable is reused (the compile
              observatory proves no `compile_recompile` fires)
    canary  — golden prompts decode greedily on the contiguous cache
              path: every logits tensor must be finite and the token
              sequences bit-identical to the reference (the manifest's
              golden block, or the first swapped replica)
    readmit — placement sees the replica again; an `SLOBurnMonitor`
              watch window plus a breaker check guard the re-admitted
              replica before the rollout proceeds

Any canary failure, mid-rollout SLO burn, breaker trip, or drain
timeout triggers an automatic fleet-wide rollback to each replica's
prior weights, after which streams still pinned to the dead version are
retired with a typed, retryable error (`version_retired`). The
controller emits `deploy_started / deploy_swap / deploy_canary_fail /
deploy_rollback / deploy_complete` flight events and the
`pdtpu_deploy_*` metric families.

Version-skew safety is owned by the router (`RouterHandle.weight_version`
pinning + version-aware placement); this module only ever moves streams
through `drain_replica`, which honors it.

Threading mirrors the router: under SimClock the harness interleaves
`controller.pump()` with `router.pump()`; under a real clock `run()`
blocks or `spawn()` pumps from a daemon thread (RouterServer's
POST /deploy uses the latter).
"""
from __future__ import annotations

import logging
import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..checkpoint import UncertifiedWeightsError
from ..obs.deploy_metrics import DeployMetrics
from ..obs.flight_recorder import flight_recorder
from ..utils.fault_injection import global_plan
from .clock import Clock, SimClock
from .llm.lora import AdapterError

_log = logging.getLogger("paddle_tpu.serving.deploy")

# golden prompts used when neither the manifest nor the config names any:
# tiny, low-id token sequences valid under any real vocab
_DEFAULT_CANARY_PROMPTS = ((1, 2, 3, 4, 5), (5, 4, 3, 2))


def _nan_poison(tree):
    """deploy_bad_weights fault: every float leaf becomes NaN, so the
    canary's finite-logits gate genuinely fails (the abstract signature
    is untouched — the swap itself still succeeds, as it would with a
    real bad-weights push)."""
    def bad(x):
        x = jnp.asarray(x)
        if jnp.issubdtype(x.dtype, jnp.floating):
            return jnp.full_like(x, jnp.nan)
        return x
    return jax.tree_util.tree_map(bad, tree)


@dataclass
class DeployConfig:
    canary_prompts: tuple = _DEFAULT_CANARY_PROMPTS   # overridden by the
    #                            weight set's manifest golden block
    canary_max_new_tokens: int = 4
    watch_window_s: float = 1.0    # SLO-burn/breaker watch after readmit
    settle_timeout_s: float = 120.0   # drain must quiesce within this or
    #                            the rollout aborts (rollback, NOT a
    #                            forced eviction — zero-drop wins)
    poll_interval_s: float = 0.005    # pump cadence in live mode
    history: int = 16              # finished rollouts kept for /debug/deploy

    def __post_init__(self):
        if self.canary_max_new_tokens < 1:
            raise ValueError("canary_max_new_tokens must be >= 1")
        if not self.canary_prompts:
            raise ValueError("need at least one canary prompt")
        if self.watch_window_s < 0 or self.settle_timeout_s <= 0:
            raise ValueError("watch_window_s must be >= 0 and "
                             "settle_timeout_s > 0")


class DeploymentController:
    """One rolling deploy at a time over a ReplicaRouter fleet.

    An explicit state machine advanced by `pump()`: per-replica phases
    drain → settle → canary_wait → canary → watch, then the next
    replica; a `rollback` super-phase restores every swapped replica's
    prior weights in the same drain-first, zero-drop manner. All public
    methods are thread-safe."""

    def __init__(self, router, config: Optional[DeployConfig] = None,
                 metrics: Optional[DeployMetrics] = None):
        self.router = router
        self.clock: Clock = router.clock
        self.config = config or DeployConfig()
        self.metrics = metrics or DeployMetrics()
        self._lock = threading.RLock()
        self._job: Optional[Dict[str, Any]] = None
        self._deploy_seq = 0       # lifetime rollouts (fault keying)
        self._history: deque = deque(maxlen=self.config.history)
        self._thread: Optional[threading.Thread] = None

    # ---- lifecycle ----

    def start(self, weightset) -> Dict[str, Any]:
        """Certify + load the weight set and begin a rollout. Raises
        `UncertifiedWeightsError` (typed) when certification fails and
        RuntimeError when a rollout is already in progress or the fleet
        has no live replica. Returns the initial status dict. Advance
        with `pump()` (or use `run()`/`spawn()`)."""
        with self._lock:
            if self._job is not None:
                raise RuntimeError(
                    f"deploy of {self._job['version']!r} already in "
                    "progress; wait for it to finish or roll back")
            manifest = weightset.certify()
            params = weightset.load()
            plan = global_plan()
            poisoned = (plan is not None
                        and plan.maybe_bad_weights(self._deploy_seq))
            self._deploy_seq += 1
            if poisoned:
                params = _nan_poison(params)
            version = weightset.version
            targets = [r.name for r in self.router.replicas
                       if not r.crashed]
            if not targets:
                raise RuntimeError("no live replica to deploy to")
            prompts = [list(map(int, p))
                       for p in self.config.canary_prompts]
            reference: Optional[List[np.ndarray]] = None
            golden = manifest.get("golden")
            if golden:
                prompts = [list(map(int, p)) for p in golden["prompts"]]
                if golden.get("tokens"):
                    reference = [np.asarray(t, np.int32)
                                 for t in golden["tokens"]]
            burn_baseline: Dict[str, set] = {}
            for r in self.router.replicas:
                burn = getattr(r.engine, "burn", None)
                if burn is not None:
                    burn_baseline[r.name] = set(
                        (burn.snapshot().get("fired") or {}).keys())
            now = self.clock.now()
            self._job = {
                "version": version,
                "params": params,
                "queue": targets,
                "idx": 0,
                "phase": "drain",
                "state": "rolling",
                "error": None,
                "prompts": prompts,
                "reference": reference,
                "prior": {},          # name -> (params, version)
                "swapped": [],        # readmitted on the new version
                "skipped": [],        # crashed mid-rollout
                "burn_baseline": burn_baseline,
                "started_at": now,
                "settle_deadline": None,
                "watch_until": None,
                "rb_queue": [],
                "rb_idx": 0,
                "rb_phase": None,
            }
            self.metrics.on_start(version)
            flight_recorder().record(
                "deploy_started", version=version, replicas=targets,
                prior={r.name: r.weight_version
                       for r in self.router.replicas},
                bad_weights_injected=bool(poisoned))
            return self.status()

    def active(self) -> bool:
        with self._lock:
            return self._job is not None

    def status(self) -> Dict[str, Any]:
        with self._lock:
            if self._job is None:
                return {"state": "idle", "history": list(self._history)}
            job = self._job
            target = None
            if job["state"] == "rolling" and job["idx"] < len(job["queue"]):
                target = job["queue"][job["idx"]]
            elif job["state"] == "rolling_back" \
                    and job["rb_idx"] < len(job["rb_queue"]):
                target = job["rb_queue"][job["rb_idx"]]
            return {"state": job["state"], "version": job["version"],
                    "phase": job["phase"], "target": target,
                    "swapped": list(job["swapped"]),
                    "skipped": list(job["skipped"]),
                    "error": job["error"],
                    "history": list(self._history)}

    def run(self, weightset, timeout_s: Optional[float] = None
            ) -> Dict[str, Any]:
        """Live-mode convenience: start + pump to completion. Returns the
        rollout's history record. Under SimClock drive `pump()` yourself
        alongside `router.pump()` instead."""
        if isinstance(self.clock, SimClock):
            raise RuntimeError(
                "DeploymentController.run() requires a real clock; under "
                "SimClock the harness interleaves pump() itself")
        self.start(weightset)
        deadline = (None if timeout_s is None
                    else time.monotonic() + timeout_s)
        while self.active():
            self.pump()
            if deadline is not None and time.monotonic() > deadline:
                raise TimeoutError(
                    f"rollout of {weightset.version!r} still "
                    f"{self.status()['phase']!r} after {timeout_s}s")
            time.sleep(self.config.poll_interval_s)
        return self._history[-1]

    def spawn(self, weightset) -> None:
        """start() synchronously (so certification errors surface to the
        caller), then pump from a daemon thread — the RouterServer
        POST /deploy path."""
        if isinstance(self.clock, SimClock):
            raise RuntimeError("spawn() requires a real clock")
        self.start(weightset)

        def _loop():
            while self.active():
                try:
                    self.pump()
                except Exception:
                    _log.exception("deploy pump failed")
                time.sleep(self.config.poll_interval_s)

        self._thread = threading.Thread(
            target=_loop, name="pdtpu-deploy", daemon=True)
        self._thread.start()

    # ---- the state machine ----

    def pump(self) -> None:
        """Advance the rollout one step. Idempotent when idle."""
        with self._lock:
            job = self._job
            if job is None:
                return
            now = self.clock.now()
            try:
                if job["state"] == "rolling_back":
                    self._pump_rollback(job, now)
                    return
                # fleet-wide abort triggers: a breaker trip or a newly
                # fired SLO-burn class on any replica already serving
                # the new version aborts the rollout wherever it stands
                abort = self._abort_reason(job)
                if abort is not None:
                    self._begin_rollback(job, abort, now)
                    return
                self._pump_rolling(job, now)
            except Exception as e:
                _log.exception("deploy pump: rolling back after error")
                if job["state"] == "rolling_back":
                    raise
                flight_recorder().record(
                    "deploy_error", version=job["version"],
                    error=f"{type(e).__name__}: {e}")
                self._begin_rollback(
                    job, f"error:{type(e).__name__}", now)

    # -- rolling --

    def _abort_reason(self, job) -> Optional[str]:
        for name in job["swapped"]:
            r = self.router._replica_by_name(name)
            if r.crashed:
                continue   # crash = failover territory, not weights
            if r.engine.broken:
                return f"breaker_trip:{name}"
            burn = getattr(r.engine, "burn", None)
            if burn is not None:
                fired = set((burn.snapshot().get("fired") or {}).keys())
                fresh = fired - job["burn_baseline"].get(name, set())
                if fresh:
                    return f"slo_burn:{name}:{sorted(fresh)[0]}"
        return None

    def _advance(self, job, now: float):
        """Move to the next replica, or finish the rollout."""
        job["idx"] += 1
        job["settle_deadline"] = None
        job["watch_until"] = None
        if job["idx"] < len(job["queue"]):
            job["phase"] = "drain"
            return
        duration = now - job["started_at"]
        flight_recorder().record(
            "deploy_complete", version=job["version"],
            replicas=list(job["swapped"]), skipped=list(job["skipped"]),
            duration_s=round(duration, 4))
        self.metrics.on_finish("completed", duration)
        self._history.append({
            "version": job["version"], "outcome": "completed",
            "reason": None, "swapped": list(job["swapped"]),
            "skipped": list(job["skipped"]),
            "duration_s": duration})
        self._job = None

    def _skip_target(self, job, name: str, now: float):
        job["skipped"].append(name)
        flight_recorder().record("deploy_skip", version=job["version"],
                                 replica=name, reason="crashed")
        self._advance(job, now)

    def _pump_rolling(self, job, now: float):
        name = job["queue"][job["idx"]]
        target = self.router._replica_by_name(name)
        phase = job["phase"]
        if target.crashed:
            # a replica lost mid-rollout is the failover machinery's
            # problem; the rollout continues over the survivors
            self._skip_target(job, name, now)
            return
        if phase == "drain":
            moved = self.router.drain_replica(name)
            if moved:
                # every moved stream is already re-queued at the router;
                # the engine-side rows are orphans — evict them so the
                # replica quiesces immediately
                target.engine.evacuate("deploy_drain")
            job["settle_deadline"] = now + self.config.settle_timeout_s
            job["phase"] = "settle"
        elif phase == "settle":
            if not target.engine.has_work():
                job["prior"][name] = (target.engine.params,
                                      target.engine.weight_version)
                prior_version = target.engine.weight_version
                target.swap(job["params"], job["version"])
                self.metrics.on_swap()
                flight_recorder().record(
                    "deploy_swap", version=job["version"],
                    replica=name, prior=prior_version)
                job["phase"] = "canary_wait"
            elif now >= job["settle_deadline"]:
                # streams finishing in place did not quiesce in time:
                # abort the rollout rather than evict them (zero-drop
                # beats rollout latency); the target was never swapped,
                # so rollback just readmits it
                self._begin_rollback(job, f"drain_timeout:{name}", now)
        elif phase == "canary_wait":
            if target.swap_ready():
                target.mark_canary()
                job["phase"] = "canary"
        elif phase == "canary":
            self._run_canary(job, target, now)
        elif phase == "watch":
            if now >= job["watch_until"]:
                self._advance(job, now)
        else:  # pragma: no cover - state machine invariant
            raise AssertionError(f"unknown deploy phase {phase!r}")

    def _run_canary(self, job, target, now: float):
        """Golden-prompt gate on the swapped, still-placement-excluded
        replica: finite logits on every step, token sequences
        bit-identical to the reference (manifest golden block, else the
        first swapped replica of this rollout)."""
        name = target.name
        outputs: List[np.ndarray] = []
        fail_reason = None
        for i, prompt in enumerate(job["prompts"]):
            toks, finite = target.engine.canary_probe(
                prompt, self.config.canary_max_new_tokens)
            if not finite:
                fail_reason = f"nonfinite_logits:prompt{i}"
                break
            if job["reference"] is not None:
                ref = job["reference"][i]
                if toks.shape != ref.shape or not np.array_equal(toks, ref):
                    fail_reason = f"reference_mismatch:prompt{i}"
                    break
            outputs.append(toks)
        passed = fail_reason is None
        self.metrics.on_canary(passed)
        if not passed:
            flight_recorder().record(
                "deploy_canary_fail", version=job["version"],
                replica=name, reason=fail_reason)
            self._begin_rollback(job, f"canary_fail:{fail_reason}", now)
            return
        if job["reference"] is None:
            # first replica through the gate defines the rollout's
            # bit-identity reference — replicas 2..N must match exactly
            job["reference"] = outputs
        flight_recorder().record(
            "deploy_canary_pass", version=job["version"], replica=name,
            prompts=len(job["prompts"]))
        self.router.readmit_replica(name)
        job["swapped"].append(name)
        job["watch_until"] = now + self.config.watch_window_s
        job["phase"] = "watch"

    # -- rollback --

    def _begin_rollback(self, job, reason: str, now: float):
        self.metrics.on_rollback(reason)
        job["state"] = "rolling_back"
        job["error"] = reason
        # replicas holding the new weights, newest swap last: everything
        # readmitted on the new version, plus the current target if its
        # swap already happened (canary_wait/canary failure paths) —
        # a target still in drain/settle was never swapped and only
        # needs readmission
        rb = list(job["swapped"])
        if job["idx"] < len(job["queue"]):
            name = job["queue"][job["idx"]]
            if name in job["prior"] and name not in rb:
                rb.append(name)
            elif name not in job["prior"]:
                # drained but never swapped: hand it straight back
                r = self.router._replica_by_name(name)
                if not r.crashed and r.deploy_state != "serving":
                    self.router.readmit_replica(name)
        job["rb_queue"] = rb
        job["rb_idx"] = 0
        job["rb_phase"] = "restore"
        job["phase"] = "rollback"
        flight_recorder().record(
            "deploy_rollback", version=job["version"], reason=reason,
            restoring=rb)
        _log.warning("deploy %s: rolling back (%s)", job["version"],
                     reason)

    def _pump_rollback(self, job, now: float):
        if job["rb_idx"] >= len(job["rb_queue"]):
            self._finish_rollback(job, now)
            return
        name = job["rb_queue"][job["rb_idx"]]
        target = self.router._replica_by_name(name)
        if target.crashed:
            job["rb_idx"] += 1
            job["rb_phase"] = "restore"
            return
        phase = job["rb_phase"]
        if phase == "restore":
            if target.deploy_state == "serving":
                # readmitted on the new version: drain it first, same
                # zero-drop contract as the forward direction — its
                # streams move to surviving new-version replicas or
                # finish in place
                moved = self.router.drain_replica(name)
                if moved:
                    target.engine.evacuate("deploy_rollback_drain")
                job["settle_deadline"] = \
                    now + self.config.settle_timeout_s
                job["rb_phase"] = "rb_settle"
                return
            # failed-canary target: already drained + idle
            self._restore_one(job, target, now)
        elif phase == "rb_settle":
            if not target.engine.has_work():
                self._restore_one(job, target, now)
            elif now >= job["settle_deadline"]:
                # rollback must converge: evict the stragglers (typed
                # rejects) rather than leave the fleet half-versioned
                target.engine.evacuate("deploy_rollback_timeout")
                self._restore_one(job, target, now)

    def _restore_one(self, job, target, now: float):
        name = target.name
        prior_params, prior_version = job["prior"][name]
        try:
            if target.deploy_state != "draining":
                # failed-canary targets sit in "swapping"/"canary";
                # replica.swap() insists on the drained state
                target.drain()
            target.swap(prior_params, prior_version)
            flight_recorder().record(
                "deploy_swap", version=prior_version, replica=name,
                prior=job["version"], rollback=True)
            self.metrics.on_swap()
        except Exception as e:
            # a replica that cannot take its old weights back (breaker
            # open with stuck work, etc.) is left for supervision;
            # recorded, never fatal to the rest of the rollback
            _log.exception("rollback: restoring %s failed", name)
            flight_recorder().record(
                "deploy_rollback_skip", replica=name,
                error=f"{type(e).__name__}: {e}")
        self.router.readmit_replica(name)
        job["rb_idx"] += 1
        job["rb_phase"] = "restore"

    def _finish_rollback(self, job, now: float):
        retired = self.router.retire_version(job["version"])
        if retired:
            self.metrics.on_retired(retired)
        duration = now - job["started_at"]
        flight_recorder().record(
            "deploy_rollback_done", version=job["version"],
            reason=job["error"], restored=list(job["rb_queue"]),
            retired_streams=retired, duration_s=round(duration, 4))
        self.metrics.on_finish("rolled_back", duration)
        self._history.append({
            "version": job["version"], "outcome": "rolled_back",
            "reason": job["error"], "swapped": list(job["swapped"]),
            "skipped": list(job["skipped"]),
            "duration_s": duration})
        self._job = None
        # the black box carries the deploy_canary_fail → deploy_rollback
        # sequence; drop the atomic dump now that the story is complete
        flight_recorder().try_dump(
            reason=f"deploy_rollback:{job['version']}")

    # ---- adapter rollout (ISSUE 20) ----

    def deploy_adapter(self, weightset, adapter_id: Optional[str] = None,
                       alpha: Optional[float] = None) -> Dict[str, Any]:
        """Fleet-wide LoRA adapter rollout — the lightweight sibling of
        `start()`/`pump()`, completing synchronously in one call.

        An adapter swap needs NONE of the base machinery's heavy phases:
        no drain (base weights and every other bank row are untouched —
        in-flight streams keep decoding through the whole rollout), no
        recompile (the bank's operand shapes are fixed), no settle. Per
        live replica: `register_adapter` rewrites the bank row between
        pump iterations (stashing the prior row as a rollback token),
        then golden prompts greedy-decode THROUGH the adapter
        (`canary_probe(adapter=...)`) — finite logits, and token
        sequences bit-identical to the manifest golden block or to the
        first replica through the gate. Any refusal or canary failure
        rolls the row back on every replica that already took it
        (`rollback_adapter`, newest first), so the fleet is never left
        serving a half-deployed or NaN adapter. Zero streams dropped in
        either direction.

        `weightset` must be an `AdapterWeightSet`; it is certified
        against the fleet's bank signature (`certify_for` — typed
        `adapter_mismatch` refusal on rank/target-module skew).
        `adapter_id` defaults to the weight-set version. Returns the
        history record ({"outcome": "completed" | "rolled_back", ...}).
        """
        with self._lock:
            if self._job is not None:
                raise RuntimeError(
                    f"deploy of {self._job['version']!r} in progress; an "
                    "adapter rollout cannot interleave with a base-weight "
                    "rollout")
            live = [r for r in self.router.replicas if not r.crashed]
            if not live:
                raise RuntimeError("no live replica to deploy to")
            banks = []
            for r in live:
                bank = getattr(r.engine, "adapter_bank", None)
                if bank is None:
                    raise RuntimeError(
                        f"replica {r.name} serves without an adapter bank "
                        "(config.max_adapters=0)")
                banks.append(bank)
            if not hasattr(weightset, "certify_for"):
                raise UncertifiedWeightsError(
                    "adapter rollout requires an AdapterWeightSet "
                    f"(got {type(weightset).__name__}); base WeightSets "
                    "go through start()", reason="bad_format")
            manifest = weightset.certify_for(banks[0].signature)
            tree = weightset.load()
            aid = str(adapter_id or weightset.version)
            plan = global_plan()
            poisoned = (plan is not None
                        and plan.maybe_bad_weights(self._deploy_seq))
            self._deploy_seq += 1
            if poisoned:
                tree = _nan_poison(tree)
            prompts = [list(map(int, p))
                       for p in self.config.canary_prompts]
            reference: Optional[List[np.ndarray]] = None
            golden = manifest.get("golden")
            if golden:
                prompts = [list(map(int, p)) for p in golden["prompts"]]
                if golden.get("tokens"):
                    reference = [np.asarray(t, np.int32)
                                 for t in golden["tokens"]]
            now = self.clock.now()
            self.metrics.on_start(f"adapter:{aid}")
            flight_recorder().record(
                "adapter_deploy_started", adapter=aid,
                version=weightset.version,
                replicas=[r.name for r in live],
                bad_weights_injected=bool(poisoned))
            snaps: Dict[str, Any] = {}    # name -> rollback token
            order: List[str] = []         # registration order
            done: List[str] = []
            fail: Optional[str] = None
            for r in live:
                try:
                    snaps[r.name] = r.engine.register_adapter(
                        aid, tree, alpha=alpha)
                    order.append(r.name)
                except AdapterError as e:
                    # typed refusal — the row was never written, so this
                    # replica needs no rollback
                    fail = f"register_fail:{r.name}:{e.reason}"
                    break
                outputs: List[np.ndarray] = []
                for i, prompt in enumerate(prompts):
                    toks, finite = r.engine.canary_probe(
                        prompt, self.config.canary_max_new_tokens,
                        adapter=aid)
                    if not finite:
                        fail = f"nonfinite_logits:{r.name}:prompt{i}"
                        break
                    if reference is not None:
                        ref = reference[i]
                        if toks.shape != ref.shape \
                                or not np.array_equal(toks, ref):
                            fail = f"reference_mismatch:{r.name}:prompt{i}"
                            break
                    outputs.append(toks)
                self.metrics.on_canary(fail is None)
                if fail is not None:
                    break
                if reference is None:
                    # first replica through the gate defines bit-identity
                    reference = outputs
                done.append(r.name)
            duration = self.clock.now() - now
            if fail is None:
                flight_recorder().record(
                    "adapter_deploy_complete", adapter=aid,
                    replicas=done, duration_s=round(duration, 4))
                record = {"version": f"adapter:{aid}",
                          "outcome": "completed", "reason": None,
                          "swapped": done, "skipped": [],
                          "duration_s": duration}
                self.metrics.on_finish("completed", duration)
                self._history.append(record)
                return record
            # fleet auto-rollback: every replica whose row was rewritten
            # takes its prior row back (None token = fresh load → unload)
            restored = []
            for name in reversed(order):
                r = self.router._replica_by_name(name)
                if r.crashed:
                    continue
                r.engine.rollback_adapter(aid, snaps[name])
                restored.append(name)
            self.metrics.on_rollback(fail)
            flight_recorder().record(
                "adapter_deploy_rollback", adapter=aid, reason=fail,
                restored=restored, duration_s=round(duration, 4))
            record = {"version": f"adapter:{aid}",
                      "outcome": "rolled_back", "reason": fail,
                      "swapped": done, "skipped": [],
                      "duration_s": duration}
            self.metrics.on_finish("rolled_back", duration)
            self._history.append(record)
            flight_recorder().try_dump(reason=f"adapter_rollback:{aid}")
            return record
