"""Injectable clocks for the serving engine.

The scheduler's flush/deadline decisions are pure functions of "now", so
swapping the time source makes the whole batching engine deterministic:
`MonotonicClock` is production, `SimClock` is a manually-advanced clock the
simulation harness (serving/sim.py) drives through scripted arrival traces —
no real sleeps, no wall-clock flake in tests.
"""
from __future__ import annotations

import threading
import time


class Clock:
    def now(self) -> float:
        """Seconds on this clock's timeline (monotonic)."""
        raise NotImplementedError

    def wait(self, cond: threading.Condition, timeout) -> None:
        """Block the scheduler thread on `cond` (held) for up to `timeout`
        seconds (None = until notified)."""
        raise NotImplementedError


class MonotonicClock(Clock):
    def now(self) -> float:
        return time.monotonic()

    def wait(self, cond, timeout):
        cond.wait(timeout)


class SimClock(Clock):
    """Scripted time. `wait` never sleeps: under a SimClock the engine runs
    threadless — the harness advances the clock and calls `engine.pump()`
    itself, so every flush decision happens at an exact scripted instant."""

    def __init__(self, start: float = 0.0):
        self._t = float(start)

    def now(self) -> float:
        return self._t

    def advance(self, dt: float) -> float:
        if dt < 0:
            raise ValueError(f"SimClock cannot go backwards (dt={dt})")
        self._t += dt
        return self._t

    def advance_to(self, t: float) -> float:
        self._t = max(self._t, float(t))
        return self._t

    def wait(self, cond, timeout):
        # notified or not, simulated waiting is the harness's job
        return
