"""Threaded stdlib-HTTP serving front end over a BatchingEngine.

Same idiom as the fleet KV server (distributed/fleet/utils/http_server.py —
ThreadingHTTPServer + BaseHTTPRequestHandler, whose hardened
`read_request_body` this module reuses):

    POST /predict   {"inputs": [[...], ...], "deadline_ms": 50}
                    -> 200 {"outputs": [...]}; 503 rejected (queue full /
                    draining); 504 deadline expired before dispatch
    POST /generate  {"input_ids": [...], "max_new_tokens": 32,
                    "eos_token_id": 2, "deadline_ms": 500,
                    "slo": "interactive"|"batch"|"best_effort",
                    "temperature": 0.8, "top_k": 40, "top_p": 0.95,
                    "seed": 1234, "grammar": {"schema": ...,
                    "tokens": {...}}}   # sampling fields optional
                    -> 200 {"tokens": [...], "ttft_ms": ...} from the
                    continuous-batching LLMEngine (serving/llm/); same
                    503/504 admission-control mapping. An optional
                    X-Tenant-Id header (1-64 chars [A-Za-z0-9._-],
                    malformed -> 400) selects the tenant: per-tenant
                    fair scheduling, quota (429 + Retry-After on
                    "tenant_quota"), metrics labels, and a private
                    prefix-cache namespace (ISSUE 8)
    GET  /healthz   -> 200 {"status": "ok"|"draining"};
                       503 {"status": "broken"} once an engine's circuit
                       breaker opens (ISSUE 6)
    GET  /metrics   -> 200 Prometheus text exposition (serving/metrics.py)
    GET  /debug/requests        -> recently finished request ids (the
                                   engines' bounded timeline LRUs)
    GET  /debug/requests/<rid>  -> one finished request's structured
                                   timeline (phases, marks, events)
    GET  /debug/flightrecorder  -> the process-global black-box ring
                                   (paddle_tpu.obs.flight_recorder)
    GET  /debug/costs           -> per-engine serving economics (ISSUE
                                   11): pump phase tiling, token
                                   efficiency, per-tenant / per-SLO-class
                                   device-seconds, SLO burn-rate state
                                   (null for engines without
                                   economics=True)

Request tracing (ISSUE 9): every /predict and /generate request gets a
request id — ingested from a W3C `traceparent` header when present, else
generated — echoed back as "rid" in the response body. Sending
`X-PDTPU-Trace: 1` additionally records a structured timeline (admission
-> queue wait -> prefill chunks -> decode -> finish) returned inline as
"trace" and retrievable later from /debug/requests/<rid>.

Backpressure (ISSUE 6): overload rejections — queue full, token budget
exhausted, or the request itself shed for a higher class — map to HTTP
429 with a Retry-After header, telling well-behaved clients to back off;
503 stays reserved for "this process is going away" (draining, circuit
breaker open). When an engine's circuit breaker trips, the server flips
/healthz to 503 {"status": "broken"} and starts a drain on its own
thread, so an external supervisor observes unhealthy -> drained -> exit
and replaces the process.

Graceful drain mirrors the ResilientTrainer preemption contract
(distributed/resilient.py): SIGTERM/SIGINT → stop admissions (new requests
get 503), flush every in-flight batch through the engine, let the attached
handler threads finish writing their responses, then exit 0 — no accepted
request is ever dropped. A `final_metrics_path` snapshot is written on the
way out so an external supervisor (or the drain test) can reconcile the
served totals against the replayed trace.

    python -m paddle_tpu.serving.server --model /path/prefix --port 8000
"""
from __future__ import annotations

import json
import logging
import os
import re
import signal
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

import numpy as np

from ..distributed.fleet.utils.http_server import read_request_body
from ..obs.flight_recorder import flight_recorder
from ..obs.trace import ingest_traceparent, new_request_id
from .engine import (BatchingEngine, DeadlineExceededError, EngineConfig,
                     RejectedError)
from .llm.sampling import SamplingParams
from .metrics import SLO_CLASSES

# RejectedError reasons that mean "try again later" (HTTP 429 +
# Retry-After) rather than "this process is going away" (503)
_RETRYABLE_REJECTS = frozenset({"queue_full", "token_budget", "shed",
                                "tenant_quota"})

# X-Tenant-Id values the LLM routes accept (ISSUE 8): tenant ids become
# metric labels and prefix-cache namespace keys, so they are restricted
# to a safe charset and bounded length; anything else is a 400
_TENANT_ID_RE = re.compile(r"^[A-Za-z0-9][A-Za-z0-9._-]{0,63}$")


def _decode_inputs(payload: dict):
    """JSON request body -> list of np arrays (leading batch dim). Each
    entry is either a nested list (float32) or {"data": ..., "dtype": ...}."""
    inputs = payload.get("inputs")
    if inputs is None:
        raise ValueError('request body needs an "inputs" list')
    arrays = []
    for entry in inputs:
        if isinstance(entry, dict):
            arrays.append(np.asarray(entry["data"],
                                     dtype=entry.get("dtype", "float32")))
        else:
            arrays.append(np.asarray(entry, dtype=np.float32))
    return arrays


class ServingServer:
    """HTTP front end + drain orchestration around a BatchingEngine
    (stateless /predict) and/or an LLMEngine (autoregressive /generate,
    ISSUE 5). At least one engine must be attached; each route 404s when
    its engine is absent. Both engines share the SIGTERM drain contract:
    stop admissions, finish every admitted request/sequence, snapshot
    final metrics, exit 0."""

    def __init__(self, engine: Optional[BatchingEngine] = None,
                 host: str = "127.0.0.1",
                 port: int = 0, final_metrics_path: Optional[str] = None,
                 request_timeout_s: float = 60.0, llm_engine=None):
        if engine is None and llm_engine is None:
            raise ValueError(
                "ServingServer needs a BatchingEngine (/predict), an "
                "LLMEngine (/generate), or both")
        self.engine = engine
        self.llm_engine = llm_engine
        self._thread: Optional[threading.Thread] = None
        self.final_metrics_path = final_metrics_path
        self.request_timeout_s = float(request_timeout_s)
        self._draining = False
        self._stop_lock = threading.Lock()
        self._stopped_event = threading.Event()
        self._active = 0                 # handler threads inside /predict
        self._active_lock = threading.Lock()
        outer = self

        class _Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):
                pass

            def _reply(self, code: int, body: bytes,
                       ctype: str = "application/json", headers=None):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def _reply_json(self, code: int, obj, headers=None):
                self._reply(code, json.dumps(obj).encode(), headers=headers)

            def _request_rid(self) -> str:
                """Request id: W3C traceparent trace-id when the client
                sent one (propagating an upstream trace), else fresh."""
                return (ingest_traceparent(self.headers.get("traceparent"))
                        or new_request_id())

            def _trace_wanted(self) -> bool:
                return self.headers.get("X-PDTPU-Trace", "").strip() == "1"

            def _reply_rejected(self, e: RejectedError):
                """Overload -> 429 + Retry-After (back off and come back);
                draining/broken/structural -> 503 (find another replica)."""
                reason = getattr(e, "reason", "rejected")
                if reason in _RETRYABLE_REJECTS:
                    retry_s = getattr(e, "retry_after_s", None) or 1.0
                    self._reply_json(
                        429, {"error": str(e), "reason": reason},
                        headers={"Retry-After": f"{retry_s:g}"})
                else:
                    self._reply_json(503,
                                     {"error": str(e), "reason": reason})

            def do_GET(self):
                if self.path == "/healthz":
                    broken = any(getattr(e, "broken", False)
                                 for e in outer._engines())
                    # an engine-initiated drain (stop(), breaker escalation)
                    # leaves outer._draining False while submissions already
                    # 503 "draining" — a router must see the drain HERE,
                    # before it eats rejects (ISSUE 14 fix)
                    draining = outer._draining or any(
                        getattr(e, "draining", False)
                        for e in outer._engines())
                    health = {
                        "status": ("broken" if broken else
                                   "draining" if draining else "ok"),
                    }
                    if outer.engine is not None:
                        health["queue_depth"] = \
                            outer.engine.metrics.queue_depth
                    if outer.llm_engine is not None:
                        m = outer.llm_engine.metrics
                        health["llm_queue_depth"] = m.queue_depth
                        health["llm_weight_version"] = \
                            outer.llm_engine.weight_version
                        health["llm_slots_active"] = m.slots_active
                        health["llm_slots_total"] = m.slots_total
                        health["llm_inflight_tokens"] = \
                            outer.llm_engine.inflight_tokens()
                        health["llm_prefix_probe"] = bool(
                            outer.llm_engine.prefix_cache is not None)
                        snap = m.snapshot()
                        health["llm_prefix_hit_rate"] = round(
                            snap.get("prefix_hit_rate", 0.0), 4)
                        health["llm_cached_blocks"] = \
                            snap.get("cached_blocks", 0)
                        health["llm_tenants"] = {
                            t: {"cache_hit_rate":
                                round(v["cache_hit_rate"], 4),
                                "cached_blocks": v["cached_blocks"],
                                "inflight_tokens": v["inflight_tokens"]}
                            for t, v in snap.get("tenants", {}).items()}
                    self._reply_json(503 if broken else 200, health)
                elif self.path == "/metrics":
                    # both engines scrape from one endpoint; the llm family
                    # renders under pdtpu_llm_* so names never collide
                    text = "".join(e.metrics.render() for e in
                                   (outer.engine, outer.llm_engine)
                                   if e is not None)
                    # pdtpu_compile_* families ride the same scrape; ""
                    # unless some engine armed the observatory (ISSUE 12)
                    from ..obs.compile_observatory import \
                        render_prom as _compile_render_prom
                    text += _compile_render_prom()
                    self._reply(200, text.encode(),
                                ctype="text/plain; version=0.0.4")
                elif self.path == "/debug/flightrecorder":
                    self._reply_json(200, flight_recorder().snapshot())
                elif self.path == "/debug/costs":
                    # serving economics (ISSUE 11): per-engine phase
                    # tiling, token efficiency, per-tenant/per-class
                    # device-seconds meters, and SLO burn-rate state;
                    # engines built without economics=True report null
                    costs = {}
                    for name, e in (("predict", outer.engine),
                                    ("llm", outer.llm_engine)):
                        if e is None:
                            continue
                        led = getattr(e, "ledger", None)
                        burn = getattr(e, "burn", None)
                        costs[name] = {
                            "economics": (led.snapshot()
                                          if led is not None else None),
                            "slo_burn": (burn.snapshot()
                                         if burn is not None else None),
                        }
                    self._reply_json(200, costs)
                elif self.path == "/debug/compiles":
                    # compile observatory (ISSUE 12): every registered
                    # executable (fingerprint, compile seconds, AOT
                    # cost/memory analyses, dispatches, device-seconds)
                    # plus recompiles grouped by culprit — the registry is
                    # process-global, so one table covers both engines
                    from ..obs.compile_observatory import compile_observatory
                    self._reply_json(
                        200, compile_observatory().snapshot(top=50))
                elif self.path == "/debug/requests":
                    ids = []
                    for e in outer._engines():
                        ids.extend(e.timelines.ids())
                    self._reply_json(200, {"ids": ids})
                elif self.path.startswith("/debug/requests/"):
                    rid = self.path[len("/debug/requests/"):]
                    for e in outer._engines():
                        tl = e.timelines.get(rid)
                        if tl is not None:
                            self._reply_json(200, tl)
                            return
                    self._reply_json(
                        404, {"error": f"no timeline for request {rid!r} "
                              "(untraced, unfinished, or evicted from the "
                              "bounded timeline buffer)"})
                else:
                    self._reply_json(404, {"error": "not found"})

            def do_POST(self):
                routes = {"/predict": (outer.engine, self._predict),
                          "/generate": (outer.llm_engine, self._generate)}
                route = routes.get(self.path)
                if route is None or route[0] is None:
                    self._reply_json(404, {"error": "not found"})
                    return
                body = read_request_body(self)
                if body is None:
                    return
                with outer._active_lock:
                    outer._active += 1
                try:
                    route[1](body)
                finally:
                    with outer._active_lock:
                        outer._active -= 1

            def _generate(self, body: bytes):
                try:
                    payload = json.loads(body or b"{}")
                    prompt = np.asarray(payload["input_ids"],
                                        dtype=np.int32).reshape(-1)
                    if prompt.size < 1:
                        raise ValueError("input_ids must be non-empty")
                    slo = payload.get("slo")
                    if slo is not None and slo not in SLO_CLASSES:
                        raise ValueError(
                            f"slo must be one of {list(SLO_CLASSES)}, "
                            f"got {slo!r}")
                    tenant = self.headers.get("X-Tenant-Id")
                    if tenant is not None \
                            and not _TENANT_ID_RE.match(tenant):
                        raise ValueError(
                            "malformed X-Tenant-Id (want 1-64 chars of "
                            "[A-Za-z0-9._-], starting alphanumeric), got "
                            f"{tenant!r}")
                    # sampling fields (ISSUE 18): temperature / top_k /
                    # top_p / seed / grammar; absent → greedy (None)
                    sampling = SamplingParams.from_payload(payload)
                    if sampling is not None:
                        sampling.validate()
                    # per-token logprobs (ISSUE 19): strictly boolean —
                    # a truthy 1 / "yes" is a malformed request
                    want_lp = payload.get("logprobs", False)
                    if not isinstance(want_lp, bool):
                        raise ValueError(
                            f"logprobs must be a boolean, got "
                            f"{want_lp!r}")
                except (ValueError, KeyError, TypeError) as e:
                    self._reply_json(400, {"error": f"bad request: {e}"})
                    return
                rid = self._request_rid()
                traced = self._trace_wanted()
                try:
                    handle = outer.llm_engine.submit(
                        prompt,
                        max_new_tokens=payload.get("max_new_tokens"),
                        eos_token_id=payload.get("eos_token_id"),
                        deadline_ms=payload.get("deadline_ms"),
                        slo=slo, tenant=tenant, rid=rid, trace=traced,
                        sampling=sampling, logprobs=want_lp)
                    toks = handle.result(timeout=outer.request_timeout_s)
                except RejectedError as e:
                    self._reply_rejected(e)
                    return
                except DeadlineExceededError as e:
                    self._reply_json(504, {"error": str(e)})
                    return
                except Exception as e:  # model/decode failure
                    self._reply_json(
                        500, {"error": f"{type(e).__name__}: {e}"})
                    return
                resp = {
                    "tokens": np.asarray(toks).tolist(),
                    "ttft_ms": handle.ttft_ms,
                    "rid": rid,
                }
                if want_lp:
                    resp["logprobs"] = handle.logprobs_so_far()
                if traced:
                    resp["trace"] = handle.timeline()
                self._reply_json(200, resp)

            def _predict(self, body: bytes):
                try:
                    payload = json.loads(body or b"{}")
                    arrays = _decode_inputs(payload)
                except (ValueError, KeyError, TypeError) as e:
                    self._reply_json(400, {"error": f"bad request: {e}"})
                    return
                rid = self._request_rid()
                traced = self._trace_wanted()
                try:
                    fut = outer.engine.submit(
                        arrays, deadline_ms=payload.get("deadline_ms"),
                        rid=rid, trace=traced)
                    outs = fut.result(timeout=outer.request_timeout_s)
                except RejectedError as e:
                    self._reply_rejected(e)
                    return
                except DeadlineExceededError as e:
                    self._reply_json(504, {"error": str(e)})
                    return
                except Exception as e:  # model/dispatch failure
                    self._reply_json(
                        500, {"error": f"{type(e).__name__}: {e}"})
                    return
                resp = {
                    "outputs": [np.asarray(o).tolist() for o in outs],
                    "rid": rid,
                }
                if traced:
                    # the engine publishes the timeline before resolving
                    # the future, so it is visible here
                    resp["trace"] = outer.engine.timelines.get(rid)
                self._reply_json(200, resp)

        # socket-level cap so a stalled client can't pin a handler thread
        # past the drain settle window
        _Handler.timeout = self.request_timeout_s + 30.0
        self._server = ThreadingHTTPServer((host, port), _Handler)
        # ThreadingHTTPServer defaults to daemon handler threads, which
        # server_close() does NOT join — a handler rejecting a late request
        # after the final snapshot was written would break the snapshot's
        # client-for-client reconciliation. Non-daemon + block_on_close
        # makes server_close() wait for every in-flight handler, so the
        # snapshot is written strictly after the last response.
        self._server.daemon_threads = False
        self._server.block_on_close = True
        self.host, self.port = self._server.server_address[:2]
        # circuit-breaker escalation: the trip fires on the engine's
        # scheduler thread, which cannot join itself — drain from a fresh
        # thread so /healthz reports "broken" while the drain runs and the
        # process exits for the supervisor to replace (ISSUE 6)
        for e in self._engines():
            if hasattr(e, "on_break") and e.on_break is None:
                e.on_break = self._drain_on_break

    def _drain_on_break(self):
        logging.getLogger("paddle_tpu.serving").error(
            "engine circuit breaker open; draining server")
        threading.Thread(target=self.stop, daemon=True,
                         name="pdtpu-serving-breaker-drain").start()

    # ---- lifecycle ----
    def _engines(self):
        return [e for e in (self.engine, self.llm_engine) if e is not None]

    def start(self) -> "ServingServer":
        """Engine scheduler(s) + HTTP accept loop on background threads."""
        for e in self._engines():
            e.start()
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True,
                                        name="pdtpu-serving-http")
        self._thread.start()
        return self

    def stop(self, drain: bool = True):
        """Stop admissions, flush the engine, stop the HTTP server. Safe to
        call twice (idempotent, same contract as KVServer.stop); the loser
        of a concurrent stop race waits for the winner to finish."""
        with self._stop_lock:
            if self._draining:
                already = True
            else:
                self._draining = True    # /predict now rejects via engine
                already = False
        drain_s = max(e.config.drain_timeout_s for e in self._engines())
        if already:
            self._stopped_event.wait(timeout=drain_s + 15.0)
            return
        for e in self._engines():
            e.stop(drain=drain)
        self._wait_active_settled()
        self._server.shutdown()
        self._server.server_close()
        if self.final_metrics_path:
            tmp = self.final_metrics_path + ".tmp"
            with open(tmp, "w") as f:
                f.write("".join(e.metrics.render()
                                for e in self._engines()))
            os.replace(tmp, self.final_metrics_path)
        self._stopped_event.set()

    def _wait_active_settled(self, timeout: float = 10.0):
        """Let handler threads holding already-resolved futures finish
        writing their responses before the accept loop dies — the 'no
        accepted request is dropped' half of the drain contract."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._active_lock:
                settled = self._active == 0
            if settled:
                # brief double-check window for a just-accepted socket
                # whose handler hasn't registered itself yet
                time.sleep(0.05)
                with self._active_lock:
                    if self._active == 0:
                        return
                continue
            time.sleep(0.01)
        with self._active_lock:
            still = self._active
        logging.getLogger("paddle_tpu.serving").warning(
            "drain settle window (%.1fs) expired with %d /predict "
            "handler(s) still active; their clients may see a connection "
            "reset", timeout, still)

    def serve_forever(self, install_signal_handlers: bool = True):
        """Foreground serve loop with the SIGTERM drain contract: returns
        after a graceful drain (caller exits 0), mirroring ResilientTrainer's
        preemption path."""
        if install_signal_handlers:
            def _on_term(signum, frame):
                # black-box dump FIRST: if the drain wedges and the
                # supervisor escalates to SIGKILL, the postmortem still
                # has everything up to the signal
                fr = flight_recorder()
                fr.record("sigterm", signum=int(signum))
                fr.try_dump(reason="sigterm")
                # drain from a helper thread: shutdown() would deadlock if
                # called on the main thread blocked inside serve_forever
                threading.Thread(target=self.stop, daemon=True,
                                 name="pdtpu-serving-drain").start()
            for sig in (signal.SIGTERM, signal.SIGINT):
                signal.signal(sig, _on_term)
        for e in self._engines():
            e.start()
        try:
            if self._thread is not None:
                # start() already owns an accept loop; a SECOND
                # serve_forever on the same socket would survive shutdown()
                # (the first loop's exit resets the shutdown flag) — block
                # until drain instead
                self._stopped_event.wait()
            else:
                self._server.serve_forever(poll_interval=0.05)
        finally:
            # signal case: the drain thread owns stop() — wait for it so the
            # process doesn't exit with the final snapshot half-written.
            # Direct shutdown() callers get the same flush here.
            self.stop()


def serve(model_path: str, host: str = "127.0.0.1", port: int = 8000,
          config: Optional[EngineConfig] = None,
          final_metrics_path: Optional[str] = None) -> ServingServer:
    """Load an exported model (inference.export_model artifacts) and return
    a ready-to-start ServingServer."""
    from ..inference import load_predictor
    predictor = load_predictor(model_path)
    engine = BatchingEngine.from_predictor(predictor, config=config)
    return ServingServer(engine, host=host, port=port,
                         final_metrics_path=final_metrics_path)


def main(argv=None):
    import argparse
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--model", required=True,
                    help="export_model artifact prefix")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=8000)
    ap.add_argument("--max-batch-size", type=int, default=8)
    ap.add_argument("--max-wait-ms", type=float, default=5.0)
    ap.add_argument("--max-queue-depth", type=int, default=256)
    ap.add_argument("--max-request-rows", type=int, default=None,
                    help="reject single requests larger than this many rows")
    ap.add_argument("--final-metrics", default=None)
    args = ap.parse_args(argv)
    server = serve(args.model, host=args.host, port=args.port,
                   config=EngineConfig(max_batch_size=args.max_batch_size,
                                       max_wait_ms=args.max_wait_ms,
                                       max_queue_depth=args.max_queue_depth,
                                       max_request_rows=args.max_request_rows),
                   final_metrics_path=args.final_metrics)
    print(f"serving {args.model} on {server.host}:{server.port}",
          file=sys.stderr)
    server.serve_forever()
    return 0


if __name__ == "__main__":
    sys.exit(main())
