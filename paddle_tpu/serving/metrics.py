"""Serving metrics: request counters, latency quantiles, batch-size
histogram, queue-depth gauge — collected by the BatchingEngine on every
admission/dispatch and exposed two ways:

- `render()` — Prometheus text exposition for the HTTP `/metrics` endpoint;
- `paddle_tpu.profiler.record_instant` — a `serving/dispatch` instant per
  engine dispatch, so serving activity lands on the same chrome trace
  timeline as training step spans when profiling is enabled.

Latency quantiles come from a bounded reservoir of recent completions
(exact over the window, not an approximation sketch); totals are lifetime
counters so a drain snapshot reconciles against a replayed trace:
submitted == completed + rejected + expired + failed.
"""
from __future__ import annotations

import threading
from collections import deque
from typing import Dict, Optional

# text-exposition plumbing lives in obs.prom (ISSUE 9) so training-side
# exporters render the same way; parse_exposition is re-exported from
# here for existing callers
from ..obs.prom import PromBuilder, parse_exposition  # noqa: F401

# cumulative histogram upper bounds for dispatched batch rows
BATCH_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 128)

# SLO classes in strict priority order (ISSUE 6 overload control): the
# scheduler admits interactive before batch before best_effort, and load
# shedding walks the same list from the BOTTOM up.
SLO_CLASSES = ("interactive", "batch", "best_effort")


class ServingMetrics:
    # metric family prefix — subclasses (LLMMetrics) override it so two
    # engines behind one server scrape without name collisions
    _PREFIX = "pdtpu_serving"

    def __init__(self, window: int = 4096):
        self._lock = threading.Lock()
        self.window = int(window)
        self._latencies_ms: deque = deque(maxlen=self.window)
        self.counters: Dict[str, int] = {
            "submitted": 0, "completed": 0, "rejected": 0,
            "expired": 0, "failed": 0, "dispatches": 0,
        }
        self.reject_reasons: Dict[str, int] = {}
        self.batch_hist: Dict[int, int] = {}   # exact dispatched rows -> n
        self.queue_depth = 0
        self.dispatched_rows = 0
        self.padded_rows = 0
        # supervision (ISSUE 6): dispatch failures by kind ("raise"/"hang"/
        # "poisoned"/"engine") and the engine circuit-breaker gauge
        self.dispatch_failures: Dict[str, int] = {}
        self.circuit_open = False
        # economics providers (ISSUE 11), attached by the engine when
        # built with economics=True and sampled only at snapshot/render
        # time (scrape-rate cost, never pump-rate cost)
        self.ledger = None   # obs.serving_ledger.ServingLedger
        self.burn = None     # obs.serving_ledger.SLOBurnMonitor

    # ---- engine callbacks ----
    def on_submit(self, queue_depth: int):
        with self._lock:
            self.counters["submitted"] += 1
            self.queue_depth = queue_depth

    def on_reject(self, reason: str):
        with self._lock:
            self.counters["rejected"] += 1
            self.reject_reasons[reason] = self.reject_reasons.get(reason, 0) + 1

    def on_expire(self, n: int = 1):
        with self._lock:
            self.counters["expired"] += n

    def on_complete(self, latency_ms: float):
        with self._lock:
            self.counters["completed"] += 1
            self._latencies_ms.append(float(latency_ms))

    def on_fail(self, n: int = 1):
        with self._lock:
            self.counters["failed"] += n

    def on_dispatch(self, rows: int, n_requests: int, padded_rows: int,
                    dispatch_ms: float, queue_depth: int):
        with self._lock:
            self.counters["dispatches"] += 1
            self.batch_hist[rows] = self.batch_hist.get(rows, 0) + 1
            self.dispatched_rows += rows
            self.padded_rows += padded_rows - rows
            self.queue_depth = queue_depth
        from ..profiler import record_instant
        record_instant("serving/dispatch", {
            "rows": rows, "requests": n_requests,
            "padded_rows": padded_rows, "dispatch_ms": dispatch_ms,
            "queue_depth": queue_depth,
        })

    def set_queue_depth(self, depth: int):
        with self._lock:
            self.queue_depth = depth

    def on_dispatch_failure(self, kind: str):
        with self._lock:
            self.dispatch_failures[kind] = \
                self.dispatch_failures.get(kind, 0) + 1

    def set_circuit_open(self, open_: bool):
        with self._lock:
            self.circuit_open = bool(open_)

    # ---- views ----
    def quantile_ms(self, q: float) -> Optional[float]:
        with self._lock:
            lat = sorted(self._latencies_ms)
        if not lat:
            return None
        idx = min(len(lat) - 1, max(0, int(round(q * (len(lat) - 1)))))
        return lat[idx]

    def snapshot(self) -> dict:
        with self._lock:
            counters = dict(self.counters)
            hist = dict(self.batch_hist)
            depth = self.queue_depth
            rows, padded = self.dispatched_rows, self.padded_rows
            dfail = dict(self.dispatch_failures)
            circuit = self.circuit_open
        mean_batch = rows / counters["dispatches"] if counters["dispatches"] \
            else 0.0
        return {
            **counters,
            "queue_depth": depth,
            "batch_hist": hist,
            "mean_batch_rows": mean_batch,
            "pad_overhead_rows": padded,
            "dispatch_failures": dfail,
            "circuit_open": circuit,
            "p50_ms": self.quantile_ms(0.50),
            "p95_ms": self.quantile_ms(0.95),
            "p99_ms": self.quantile_ms(0.99),
            **({"economics": self.ledger.snapshot()}
               if self.ledger is not None else {}),
            **({"slo_burn": self.burn.snapshot()}
               if self.burn is not None else {}),
        }

    def render(self) -> str:
        """Prometheus text exposition (served at /metrics)."""
        b = PromBuilder()
        self._render_into(b)
        return b.render()

    def _render_into(self, b: PromBuilder):
        s = self.snapshot()
        px = self._PREFIX
        b.family(f"{px}_requests_total", "counter")
        for outcome in ("submitted", "completed", "rejected", "expired",
                        "failed"):
            b.sample(f"{px}_requests_total", s[outcome],
                     {"outcome": outcome})
        b.family(f"{px}_dispatches_total", "counter")
        b.sample(f"{px}_dispatches_total", s["dispatches"])
        b.family(f"{px}_queue_depth", "gauge")
        b.sample(f"{px}_queue_depth", s["queue_depth"])
        b.family(f"{px}_latency_ms", "summary")
        for q, key in ((0.5, "p50_ms"), (0.95, "p95_ms"), (0.99, "p99_ms")):
            b.sample(f"{px}_latency_ms", s[key], {"quantile": q}, round_to=3)
        b.family(f"{px}_batch_rows", "histogram")
        hist = s["batch_hist"]
        for le in BATCH_BUCKETS:
            cum = sum(n for rows, n in hist.items() if rows <= le)
            b.sample(f"{px}_batch_rows_bucket", cum, {"le": le})
        b.sample(f"{px}_batch_rows_bucket", sum(hist.values()),
                 {"le": "+Inf"})
        b.sample(f"{px}_batch_rows_count", sum(hist.values()))
        b.sample(f"{px}_batch_rows_sum",
                 sum(r * n for r, n in hist.items()))
        b.family(f"{px}_dispatch_failures_total", "counter")
        for kind in sorted(s["dispatch_failures"]):
            b.sample(f"{px}_dispatch_failures_total",
                     s["dispatch_failures"][kind], {"kind": kind})
        b.family(f"{px}_circuit_open", "gauge")
        b.sample(f"{px}_circuit_open", int(s["circuit_open"]))
        self._render_economics_into(b, s)

    def _render_economics_into(self, b: PromBuilder, s: dict):
        """Serving-economics families (ISSUE 11): phase tiling, token
        efficiency, decode MFU, per-tenant/per-class device-seconds and
        SLO burn rates — rendered only when the engine attached the
        providers, under this metrics object's own prefix (pdtpu_serving
        for the predictor engine, pdtpu_llm for the LLM engine)."""
        px = self._PREFIX
        if self.ledger is not None:
            e = s["economics"]
            b.family(f"{px}_phase_seconds_total", "counter")
            for phase, secs in sorted(e["phase_seconds"].items()):
                b.sample(f"{px}_phase_seconds_total", secs,
                         labels={"phase": phase}, round_to=4)
            b.family(f"{px}_wall_seconds", "gauge")
            b.sample(f"{px}_wall_seconds", e["wall_seconds"], round_to=4)
            b.family(f"{px}_token_efficiency", "gauge")
            b.sample(f"{px}_token_efficiency", e["token_efficiency"],
                     round_to=4)
            b.family(f"{px}_host_fraction", "gauge")
            b.sample(f"{px}_host_fraction", e["host_fraction"], round_to=4)
            b.family(f"{px}_decode_mfu", "gauge")
            b.sample(f"{px}_decode_mfu", e["decode_mfu"], round_to=6)
            if e["tenants"]:
                b.family(f"{px}_tenant_device_seconds_total", "counter")
                for tenant in sorted(e["tenants"]):
                    b.sample(f"{px}_tenant_device_seconds_total",
                             e["tenants"][tenant]["device_seconds"],
                             {"tenant": tenant}, round_to=6)
                b.family(f"{px}_tenant_device_tokens_total", "counter")
                for tenant in sorted(e["tenants"]):
                    b.sample(f"{px}_tenant_device_tokens_total",
                             e["tenants"][tenant]["tokens"],
                             {"tenant": tenant})
            if e["classes"]:
                b.family(f"{px}_class_device_seconds_total", "counter")
                for cls in sorted(e["classes"]):
                    b.sample(f"{px}_class_device_seconds_total",
                             e["classes"][cls]["device_seconds"],
                             {"slo": cls}, round_to=6)
                b.family(f"{px}_class_device_tokens_total", "counter")
                for cls in sorted(e["classes"]):
                    b.sample(f"{px}_class_device_tokens_total",
                             e["classes"][cls]["tokens"], {"slo": cls})
        if self.burn is not None:
            burn = s["slo_burn"]
            b.family(f"{px}_slo_burn_rate", "gauge")
            b.family(f"{px}_slo_burn_fired", "gauge")
            for cls in sorted(burn["classes"]):
                v = burn["classes"][cls]
                for window in ("fast", "slow"):
                    b.sample(f"{px}_slo_burn_rate", v[f"burn_{window}"],
                             {"slo": cls, "window": window}, round_to=3)
                b.sample(f"{px}_slo_burn_fired", int(v["fired"]),
                         {"slo": cls})


def _quantile(sorted_vals, q: float) -> Optional[float]:
    if not sorted_vals:
        return None
    idx = min(len(sorted_vals) - 1,
              max(0, int(round(q * (len(sorted_vals) - 1)))))
    return sorted_vals[idx]


class LLMMetrics(ServingMetrics):
    """ServingMetrics extended for the continuous-batching LLM engine
    (ISSUE 5): TTFT and inter-token latency summaries, decode-throughput
    (tokens/sec) and slot-occupancy gauges, prefill/decode-step/token
    counters. Rendered under the `pdtpu_llm` family prefix so an LLM
    engine can share a /metrics endpoint with a predictor BatchingEngine
    without name collisions. The inherited batch-rows histogram counts
    ACTIVE rows per decode iteration — i.e. how well continuous batching
    keeps the fixed-width decode full."""

    _PREFIX = "pdtpu_llm"

    def __init__(self, window: int = 4096):
        super().__init__(window)
        self._ttft_ms: deque = deque(maxlen=self.window)
        self._intertoken_ms: deque = deque(maxlen=self.window)
        # (active_rows, step_ms) pairs: tokens/sec over the recent window
        self._decode_window: deque = deque(maxlen=self.window)
        self.counters.update({"prefills": 0, "decode_steps": 0,
                              "tokens_out": 0, "shed": 0, "quarantined": 0,
                              "brownout_entries": 0,
                              "prefix_hits": 0, "prefix_misses": 0,
                              "prefix_hit_tokens": 0,
                              "prefix_lookup_tokens": 0,
                              "spec_windows": 0, "spec_drafted": 0,
                              "spec_accepted": 0,
                              "spec_draft_quarantines": 0,
                              "sampled_tokens": 0,
                              "constrained_tokens": 0,
                              "adapter_swaps": 0,
                              "adapter_rollbacks": 0})
        self.slots_active = 0
        self.slots_total = 0
        # per-SLO-class accounting (ISSUE 6 overload control): aggregate
        # counters above stay authoritative for the drain reconciliation
        # invariant; these break the same events down by class so the
        # overload gates can pin e.g. interactive-only TTFT ceilings
        self.class_counters: Dict[str, Dict[str, int]] = {
            c: {"submitted": 0, "completed": 0, "shed": 0}
            for c in SLO_CLASSES}
        self._class_ttft: Dict[str, deque] = {
            c: deque(maxlen=self.window) for c in SLO_CLASSES}
        self.brownout = False
        self.inflight_tokens = 0
        # KV-pool block fragmentation (ISSUE 7): fraction of allocated
        # block tokens not holding valid KV, from
        # SlotPagedKVPool.fragmentation_ratio()
        self.fragmentation = 0.0
        # prefix cache + multi-tenancy (ISSUE 8): aggregate cache gauges
        # plus a per-tenant breakdown (lazily created per tenant id) —
        # aggregate counters above stay authoritative for the drain
        # reconciliation invariant
        self.cached_blocks = 0
        self.cache_evictions = 0
        self.tenants: Dict[str, Dict[str, int]] = {}
        # time-weighted slot occupancy (ISSUE 11 satellite): ∫occupancy·dt
        # integrated at pump granularity, so the average weighs each
        # occupancy level by how long it actually held — a snapshot-only
        # gauge read at scrape time sees whatever instant the scrape hit
        self._occ_integral = 0.0    # ∫ occupancy dt
        self._occ_wall = 0.0        # observed seconds
        self._occ_last_t: Optional[float] = None
        self._occ_prev = 0.0        # occupancy held since the last observe
        # per-slot sampling modes (ISSUE 18): slot occupancy broken down
        # by decode mode, plus the host-side cost of assembling the
        # per-step sampling operands (params, RNG lanes, grammar masks)
        self.sample_slots: Dict[str, int] = {
            "greedy": 0, "sampled": 0, "constrained": 0}
        self._mask_overhead_ms: deque = deque(maxlen=self.window)
        self.grammars_compiled = 0
        # host-RAM KV spill tier (ISSUE 19): the engine pushes the
        # HostKVPool's snapshot() each pump; None until a tiered engine
        # reports, so a device-only engine renders no host families
        self.host_kv: Optional[Dict[str, int]] = None
        # multi-LoRA serving (ISSUE 18/20): emitted tokens per adapter id
        # ("base" for row-0 streams) — on an armed engine every emission
        # lands in exactly one bucket, so these sum to tokens_out
        self.adapter_tokens: Dict[str, int] = {}

    def _class(self, slo) -> Optional[Dict[str, int]]:
        return self.class_counters.get(slo) if slo else None

    def _tenant(self, tenant) -> Optional[Dict[str, int]]:
        if not tenant:
            return None
        return self.tenants.setdefault(tenant, {
            "submitted": 0, "completed": 0, "rejected": 0,
            "prefix_hits": 0, "prefix_misses": 0, "prefix_hit_tokens": 0,
            "prefix_lookup_tokens": 0, "inflight_tokens": 0,
            "cached_blocks": 0})

    # ---- engine callbacks ----
    def on_submit(self, queue_depth: int, slo: Optional[str] = None,
                  tenant: Optional[str] = None):
        super().on_submit(queue_depth)
        with self._lock:
            c = self._class(slo)
            if c is not None:
                c["submitted"] += 1
            t = self._tenant(tenant)
            if t is not None:
                t["submitted"] += 1

    def on_complete(self, latency_ms: float, slo: Optional[str] = None,
                    tenant: Optional[str] = None):
        super().on_complete(latency_ms)
        with self._lock:
            c = self._class(slo)
            if c is not None:
                c["completed"] += 1
            t = self._tenant(tenant)
            if t is not None:
                t["completed"] += 1

    def on_reject(self, reason: str, tenant: Optional[str] = None):
        super().on_reject(reason)
        with self._lock:
            t = self._tenant(tenant)
            if t is not None:
                t["rejected"] += 1

    def on_prefix_lookup(self, tenant: Optional[str], hit_tokens: int,
                         prompt_tokens: int):
        """One admission-time prefix-cache lookup: `hit_tokens` prompt
        tokens were served from cached KV (attach + COW) out of
        `prompt_tokens` looked up. The token-weighted ratio of these two
        counters is the cache hit rate the bench gates pin."""
        with self._lock:
            hit = hit_tokens > 0
            self.counters["prefix_hits" if hit else "prefix_misses"] += 1
            self.counters["prefix_hit_tokens"] += int(hit_tokens)
            self.counters["prefix_lookup_tokens"] += int(prompt_tokens)
            t = self._tenant(tenant)
            if t is not None:
                t["prefix_hits" if hit else "prefix_misses"] += 1
                t["prefix_hit_tokens"] += int(hit_tokens)
                t["prefix_lookup_tokens"] += int(prompt_tokens)

    def set_tenant_inflight(self, per_tenant: Dict[str, int]):
        """Refresh per-tenant in-flight token gauges; tenants absent from
        the map (fully drained) read 0."""
        with self._lock:
            for t in self.tenants.values():
                t["inflight_tokens"] = 0
            for tenant, tokens in per_tenant.items():
                t = self._tenant(tenant)
                if t is not None:
                    t["inflight_tokens"] = int(tokens)

    def set_prefix_cache(self, cached_blocks: int, evictions: int,
                         per_tenant_cached: Optional[Dict[str, int]] = None):
        with self._lock:
            self.cached_blocks = int(cached_blocks)
            self.cache_evictions = int(evictions)
            for tenant, n in (per_tenant_cached or {}).items():
                t = self._tenant(tenant)
                if t is not None:
                    t["cached_blocks"] = int(n)

    def on_shed(self, slo: Optional[str] = None):
        """A queued request was load-shed to make room for higher-priority
        work. Also counted as rejected (reason "shed") by the engine, so
        submitted == completed + rejected + expired + failed still holds."""
        with self._lock:
            self.counters["shed"] += 1
            c = self._class(slo)
            if c is not None:
                c["shed"] += 1

    def on_quarantine(self):
        with self._lock:
            self.counters["quarantined"] += 1

    def set_brownout(self, active: bool):
        with self._lock:
            entered = active and not self.brownout
            self.brownout = bool(active)
            if entered:
                self.counters["brownout_entries"] += 1

    def set_inflight_tokens(self, tokens: int):
        with self._lock:
            self.inflight_tokens = int(tokens)

    def set_fragmentation(self, ratio: float):
        with self._lock:
            self.fragmentation = float(ratio)

    def on_prefill(self, ttft_ms: float, slo: Optional[str] = None):
        with self._lock:
            self.counters["prefills"] += 1
            self._ttft_ms.append(float(ttft_ms))
            if slo in self._class_ttft:
                self._class_ttft[slo].append(float(ttft_ms))

    def on_decode_step(self, active_rows: int, step_ms: float,
                       tokens: Optional[int] = None):
        """One committed decode iteration over `active_rows` rows.
        `tokens` is how many tokens the iteration actually emitted —
        under speculative decoding (ISSUE 17) an accepted draft window
        commits several tokens per row, so throughput counters take the
        real emission while the batch-rows histogram keeps counting HOW
        FULL the fixed-width step was (its documented meaning)."""
        tokens = int(active_rows) if tokens is None else int(tokens)
        with self._lock:
            self.counters["decode_steps"] += 1
            self.counters["tokens_out"] += tokens
            self.batch_hist[active_rows] = \
                self.batch_hist.get(active_rows, 0) + 1
            self.dispatched_rows += int(active_rows)
            self.counters["dispatches"] += 1
            self._intertoken_ms.append(float(step_ms))
            self._decode_window.append((tokens, float(step_ms)))
        from ..profiler import record_instant
        record_instant("serving/llm_decode", {
            "active_rows": active_rows, "step_ms": step_ms,
            "tokens": tokens,
        })

    def on_spec_window(self, drafted: int, accepted: int):
        """One verified speculative window (ISSUE 17): `drafted` tokens
        proposed, `accepted` of them kept (the corrective token is not
        counted either way — it is ordinary decode output)."""
        with self._lock:
            self.counters["spec_windows"] += 1
            self.counters["spec_drafted"] += int(drafted)
            self.counters["spec_accepted"] += int(accepted)

    def on_sample_token(self, mode: str):
        """One emitted token from a non-greedy slot (ISSUE 18): `mode` is
        "sampled" (temperature/top-k/top-p RNG lane) or "constrained"
        (grammar-masked lane). Greedy emissions stay in `tokens_out`
        alone, so the two counters partition the non-greedy traffic."""
        with self._lock:
            self.counters[f"{mode}_tokens"] += 1

    def set_sample_slots(self, counts: Dict[str, int]):
        """Refresh the per-mode slot occupancy gauge from the engine's
        sampling table (greedy / sampled / constrained active slots)."""
        with self._lock:
            self.sample_slots = {
                m: int(counts.get(m, 0))
                for m in ("greedy", "sampled", "constrained")}

    def on_mask_overhead(self, ms: float):
        """Host-side sampling-operand assembly time for one unified step
        (params + RNG-lane counters + DFA states + grammar bank): the
        per-step overhead the bench's mask-overhead ceiling row bounds."""
        with self._lock:
            self._mask_overhead_ms.append(float(ms))

    def set_grammars(self, compiled: int):
        with self._lock:
            self.grammars_compiled = int(compiled)

    def set_host_kv(self, snap: Dict[str, int]):
        """Refresh the host spill tier's gauges/counters from
        `HostKVPool.snapshot()` (pages, bytes, spills, onboards, hits,
        misses, evictions, rejected)."""
        with self._lock:
            self.host_kv = dict(snap)

    def mask_overhead_quantile_ms(self, q: float) -> Optional[float]:
        with self._lock:
            vals = sorted(self._mask_overhead_ms)
        return _quantile(vals, q)

    def on_draft_quarantine(self):
        """A request's draft was quarantined (spec_off) after a poisoned
        draft dispatch; its target stream continues as plain decode."""
        with self._lock:
            self.counters["spec_draft_quarantines"] += 1

    # ---- multi-LoRA serving (ISSUE 20) ----
    def on_adapter_token(self, adapter: str):
        """One emitted token attributed to a LoRA adapter: `adapter` is
        the bank id, or "base" for a row-0 (no-adapter) stream. The
        per-adapter counters partition `tokens_out` exactly — the token
        analogue of the ledger's adapter-seconds partitioning tenant
        device-seconds."""
        with self._lock:
            self.adapter_tokens[adapter] = \
                self.adapter_tokens.get(adapter, 0) + 1

    def on_adapter_swap(self):
        with self._lock:
            self.counters["adapter_swaps"] += 1

    def on_adapter_rollback(self):
        with self._lock:
            self.counters["adapter_rollbacks"] += 1

    def set_slots(self, active: int, total: int):
        with self._lock:
            self.slots_active = int(active)
            self.slots_total = int(total)

    def observe_occupancy(self, now: float):
        """Advance the occupancy·dt integral to `now` (called once per
        pump iteration): the occupancy the LAST observation left behind
        is credited for the elapsed interval, then the current gauge
        becomes the new level. The averaged value is the utilization the
        ledger's `token_efficiency` is bounded by (a padded-but-occupied
        slot still advances positions; an empty one cannot)."""
        with self._lock:
            if self._occ_last_t is not None:
                dt = now - self._occ_last_t
                if dt > 0:
                    self._occ_integral += self._occ_prev * dt
                    self._occ_wall += dt
            self._occ_last_t = now
            self._occ_prev = (self.slots_active / self.slots_total
                              if self.slots_total else 0.0)

    # ---- views ----
    def ttft_quantile_ms(self, q: float,
                         slo: Optional[str] = None) -> Optional[float]:
        with self._lock:
            src = self._class_ttft[slo] if slo else self._ttft_ms
            vals = sorted(src)
        return _quantile(vals, q)

    def intertoken_quantile_ms(self, q: float) -> Optional[float]:
        with self._lock:
            vals = sorted(self._intertoken_ms)
        return _quantile(vals, q)

    def tokens_per_s(self) -> float:
        """Decode throughput over the recent window: generated tokens per
        second of decode-step wall time (idle gaps excluded, so the gauge
        means 'how fast the decode loop moves when it moves')."""
        with self._lock:
            pairs = list(self._decode_window)
        total_ms = sum(ms for _, ms in pairs)
        if total_ms <= 0:
            return 0.0
        return sum(rows for rows, _ in pairs) / (total_ms / 1e3)

    def snapshot(self) -> dict:
        s = super().snapshot()
        with self._lock:
            s["slots_active"] = self.slots_active
            s["slots_total"] = self.slots_total
            s["classes"] = {c: dict(v)
                            for c, v in self.class_counters.items()}
            s["brownout"] = self.brownout
            s["inflight_tokens"] = self.inflight_tokens
            s["kv_fragmentation"] = self.fragmentation
            s["cached_blocks"] = self.cached_blocks
            s["cache_evictions"] = self.cache_evictions
            s["tenants"] = {t: dict(v) for t, v in self.tenants.items()}
            s["slot_occupancy_avg"] = (
                self._occ_integral / self._occ_wall
                if self._occ_wall > 0 else None)
        for t in s["tenants"].values():
            t["cache_hit_rate"] = (
                t["prefix_hit_tokens"] / t["prefix_lookup_tokens"]
                if t["prefix_lookup_tokens"] else 0.0)
        s["prefix_hit_rate"] = (
            s["prefix_hit_tokens"] / s["prefix_lookup_tokens"]
            if s["prefix_lookup_tokens"] else 0.0)
        s["slot_occupancy"] = (self.slots_active / self.slots_total
                               if self.slots_total else 0.0)
        s["tokens_per_s"] = self.tokens_per_s()
        s["spec_accept_rate"] = (s["spec_accepted"] / s["spec_drafted"]
                                 if s["spec_drafted"] else None)
        with self._lock:
            s["sample_slots"] = dict(self.sample_slots)
            s["grammars_compiled"] = self.grammars_compiled
            s["host_kv"] = (dict(self.host_kv)
                            if self.host_kv is not None else None)
            s["adapter_tokens"] = dict(self.adapter_tokens)
        s["mask_overhead_p99_ms"] = self.mask_overhead_quantile_ms(0.99)
        s["shed_rate"] = (s["shed"] / s["submitted"] if s["submitted"]
                          else 0.0)
        for q, key in ((0.5, "p50"), (0.95, "p95"), (0.99, "p99")):
            s[f"ttft_{key}_ms"] = self.ttft_quantile_ms(q)
            s[f"intertoken_{key}_ms"] = self.intertoken_quantile_ms(q)
        for c in SLO_CLASSES:
            s[f"ttft_p99_ms_{c}"] = self.ttft_quantile_ms(0.99, slo=c)
        return s

    def _render_into(self, b: PromBuilder):
        super()._render_into(b)
        s = self.snapshot()
        px = self._PREFIX
        for fam, prefix in ((f"{px}_ttft_ms", "ttft"),
                            (f"{px}_intertoken_ms", "intertoken")):
            b.family(fam, "summary")
            for q, key in ((0.5, "p50"), (0.95, "p95"), (0.99, "p99")):
                b.sample(fam, s[f"{prefix}_{key}_ms"], {"quantile": q},
                         round_to=3)
        b.family(f"{px}_tokens_per_s", "gauge")
        b.sample(f"{px}_tokens_per_s", s["tokens_per_s"], round_to=3)
        b.family(f"{px}_slots_active", "gauge")
        b.sample(f"{px}_slots_active", s["slots_active"])
        b.family(f"{px}_slots_total", "gauge")
        b.sample(f"{px}_slots_total", s["slots_total"])
        b.family(f"{px}_slot_occupancy", "gauge")
        b.sample(f"{px}_slot_occupancy", s["slot_occupancy"], round_to=4)
        b.family(f"{px}_slot_occupancy_avg", "gauge")
        b.sample(f"{px}_slot_occupancy_avg", s["slot_occupancy_avg"],
                 round_to=4)
        b.family(f"{px}_tokens_total", "counter")
        b.sample(f"{px}_tokens_total", s["tokens_out"])
        b.family(f"{px}_decode_steps_total", "counter")
        b.sample(f"{px}_decode_steps_total", s["decode_steps"])
        b.family(f"{px}_prefills_total", "counter")
        b.sample(f"{px}_prefills_total", s["prefills"])
        # ---- speculative decoding families (ISSUE 17) ----
        b.family(f"{px}_spec_windows_total", "counter")
        b.sample(f"{px}_spec_windows_total", s["spec_windows"])
        b.family(f"{px}_spec_drafted_total", "counter")
        b.sample(f"{px}_spec_drafted_total", s["spec_drafted"])
        b.family(f"{px}_spec_accepted_total", "counter")
        b.sample(f"{px}_spec_accepted_total", s["spec_accepted"])
        b.family(f"{px}_spec_accept_rate", "gauge")
        b.sample(f"{px}_spec_accept_rate", s["spec_accept_rate"],
                 round_to=4)
        b.family(f"{px}_spec_draft_quarantines_total", "counter")
        b.sample(f"{px}_spec_draft_quarantines_total",
                 s["spec_draft_quarantines"])
        # ---- sampling + constrained decoding families (ISSUE 18) ----
        b.family(f"{px}_sample_slots", "gauge")
        for mode in ("greedy", "sampled", "constrained"):
            b.sample(f"{px}_sample_slots", s["sample_slots"].get(mode, 0),
                     {"mode": mode})
        b.family(f"{px}_sample_tokens_total", "counter")
        for mode in ("sampled", "constrained"):
            b.sample(f"{px}_sample_tokens_total", s[f"{mode}_tokens"],
                     {"mode": mode})
        b.family(f"{px}_sample_mask_overhead_ms", "summary")
        b.sample(f"{px}_sample_mask_overhead_ms", s["mask_overhead_p99_ms"],
                 {"quantile": "0.99"}, round_to=3)
        b.family(f"{px}_sample_grammars_compiled", "gauge")
        b.sample(f"{px}_sample_grammars_compiled", s["grammars_compiled"])
        # ---- multi-LoRA serving families (ISSUE 20) ----
        if s["adapter_tokens"]:
            b.family(f"{px}_adapter_tokens_total", "counter")
            for aid in sorted(s["adapter_tokens"]):
                b.sample(f"{px}_adapter_tokens_total",
                         s["adapter_tokens"][aid], {"adapter": aid})
            b.family(f"{px}_adapter_swaps_total", "counter")
            b.sample(f"{px}_adapter_swaps_total", s["adapter_swaps"])
            b.family(f"{px}_adapter_rollbacks_total", "counter")
            b.sample(f"{px}_adapter_rollbacks_total",
                     s["adapter_rollbacks"])
        # ---- tiered KV cache families (ISSUE 19) ----
        if s["host_kv"] is not None:
            hk = s["host_kv"]
            b.family(f"{px}_kv_host_pages_total", "gauge")
            b.sample(f"{px}_kv_host_pages_total", hk["pages"])
            b.family(f"{px}_kv_host_bytes_total", "gauge")
            b.sample(f"{px}_kv_host_bytes_total", hk["bytes"])
            b.family(f"{px}_kv_host_spills_total", "counter")
            b.sample(f"{px}_kv_host_spills_total", hk["spills"])
            b.family(f"{px}_kv_host_onboards_total", "counter")
            b.sample(f"{px}_kv_host_onboards_total", hk["onboards"])
            b.family(f"{px}_kv_host_evictions_total", "counter")
            b.sample(f"{px}_kv_host_evictions_total", hk["evictions"])
        # ---- overload control + supervision families (ISSUE 6) ----
        b.family(f"{px}_class_requests_total", "counter")
        for c in SLO_CLASSES:
            for outcome in ("submitted", "completed", "shed"):
                b.sample(f"{px}_class_requests_total",
                         s["classes"][c][outcome],
                         {"slo": c, "outcome": outcome})
        b.family(f"{px}_class_ttft_ms", "summary")
        for c in SLO_CLASSES:
            b.sample(f"{px}_class_ttft_ms", s[f"ttft_p99_ms_{c}"],
                     {"slo": c, "quantile": "0.99"}, round_to=3)
        b.family(f"{px}_shed_total", "counter")
        b.sample(f"{px}_shed_total", s["shed"])
        b.family(f"{px}_quarantined_total", "counter")
        b.sample(f"{px}_quarantined_total", s["quarantined"])
        b.family(f"{px}_brownout", "gauge")
        b.sample(f"{px}_brownout", int(s["brownout"]))
        b.family(f"{px}_brownout_entries_total", "counter")
        b.sample(f"{px}_brownout_entries_total", s["brownout_entries"])
        b.family(f"{px}_inflight_tokens", "gauge")
        b.sample(f"{px}_inflight_tokens", s["inflight_tokens"])
        b.family(f"{px}_kv_fragmentation", "gauge")
        b.sample(f"{px}_kv_fragmentation", s["kv_fragmentation"], round_to=4)
        # ---- prefix cache + multi-tenancy families (ISSUE 8) ----
        b.family(f"{px}_prefix_hits_total", "counter")
        b.sample(f"{px}_prefix_hits_total", s["prefix_hits"])
        b.family(f"{px}_prefix_misses_total", "counter")
        b.sample(f"{px}_prefix_misses_total", s["prefix_misses"])
        b.family(f"{px}_prefix_hit_tokens_total", "counter")
        b.sample(f"{px}_prefix_hit_tokens_total", s["prefix_hit_tokens"])
        b.family(f"{px}_prefix_hit_rate", "gauge")
        b.sample(f"{px}_prefix_hit_rate", s["prefix_hit_rate"], round_to=4)
        b.family(f"{px}_cached_blocks", "gauge")
        b.sample(f"{px}_cached_blocks", s["cached_blocks"])
        b.family(f"{px}_cache_evictions_total", "counter")
        b.sample(f"{px}_cache_evictions_total", s["cache_evictions"])
        if s["tenants"]:
            b.family(f"{px}_tenant_requests_total", "counter")
            for tenant in sorted(s["tenants"]):
                tv = s["tenants"][tenant]
                for outcome in ("submitted", "completed", "rejected"):
                    b.sample(f"{px}_tenant_requests_total", tv[outcome],
                             {"tenant": tenant, "outcome": outcome})
            for fam, key, typ, rnd in (
                    ("tenant_cache_hit_rate", "cache_hit_rate", "gauge", 4),
                    ("tenant_cached_blocks", "cached_blocks", "gauge", None),
                    ("tenant_inflight_tokens", "inflight_tokens", "gauge",
                     None)):
                b.family(f"{px}_{fam}", typ)
                for tenant in sorted(s["tenants"]):
                    b.sample(f"{px}_{fam}", s["tenants"][tenant][key],
                             {"tenant": tenant}, round_to=rnd)


class RouterMetrics:
    """Front-of-fleet router counters (ISSUE 14): routing decisions per
    replica, prefix-affinity hit rate, per-replica health/quarantine
    state, failovers with resumed-stream totals, and router-level
    rejects. Rendered under the `pdtpu_router_*` prefix so the router's
    /metrics can concatenate the replicas' `pdtpu_llm_*` families
    without a name collision."""

    _PREFIX = "pdtpu_router"

    def __init__(self):
        self._lock = threading.Lock()
        self.counters: Dict[str, int] = {
            "submitted": 0, "completed": 0, "rejected": 0, "failed": 0,
        }
        self.reject_reasons: Dict[str, int] = {}
        self.routed: Dict[str, int] = {}           # replica -> decisions
        self.replica_state: Dict[str, str] = {}    # replica -> health word
        self.quarantines: Dict[str, int] = {}      # replica -> times down
        self.failovers: Dict[str, int] = {}        # dead replica -> events
        self.resumed_streams = 0
        self.readmissions: Dict[str, int] = {}
        self.affinity_hits = 0                     # routed to a prefix match
        self.affinity_decisions = 0
        self.replica_inflight: Dict[str, int] = {}
        self.replica_weight_version: Dict[str, str] = {}   # ISSUE 16
        # prefill/decode disaggregation (ISSUE 19)
        self.replica_role: Dict[str, str] = {}     # replica -> role tag
        self.handoffs = 0                          # prefill→decode moves
        self.handoffs_failed = 0                   # export succeeded but no
        #                                            decode home re-admitted
        #                                            the stream in time
        self._handoff_ms: deque = deque(maxlen=4096)

    # ---- router callbacks ----
    def on_submit(self):
        with self._lock:
            self.counters["submitted"] += 1

    def on_route(self, replica: str, prefix_hit: bool):
        with self._lock:
            self.routed[replica] = self.routed.get(replica, 0) + 1
            self.affinity_decisions += 1
            if prefix_hit:
                self.affinity_hits += 1

    def on_reject(self, reason: str):
        with self._lock:
            self.counters["rejected"] += 1
            self.reject_reasons[reason] = \
                self.reject_reasons.get(reason, 0) + 1

    def on_complete(self):
        with self._lock:
            self.counters["completed"] += 1

    def on_fail(self):
        with self._lock:
            self.counters["failed"] += 1

    def set_replica(self, replica: str, state: str, inflight_tokens: int,
                    weight_version: Optional[str] = None,
                    role: Optional[str] = None):
        with self._lock:
            self.replica_state[replica] = state
            self.replica_inflight[replica] = int(inflight_tokens)
            if weight_version is not None:
                self.replica_weight_version[replica] = str(weight_version)
            if role is not None:
                self.replica_role[replica] = str(role)

    def on_handoff(self, src: str, dst: str, ms: float):
        """One completed prefill→decode stream handoff (ISSUE 19): KV
        exported from `src`, stream re-admitted on `dst` after `ms`
        milliseconds of export-to-accepted-submit wall time — the
        latency the bench's `llm_handoff_ms` ceiling bounds."""
        with self._lock:
            self.handoffs += 1
            self._handoff_ms.append(float(ms))

    def on_handoff_failed(self):
        """A handoff export could not be re-admitted anywhere (the stream
        falls back to failover re-prefill, never dropped)."""
        with self._lock:
            self.handoffs_failed += 1

    def handoff_quantile_ms(self, q: float) -> Optional[float]:
        with self._lock:
            vals = sorted(self._handoff_ms)
        return _quantile(vals, q)

    def on_quarantine(self, replica: str):
        with self._lock:
            self.quarantines[replica] = self.quarantines.get(replica, 0) + 1

    def on_readmit(self, replica: str):
        with self._lock:
            self.readmissions[replica] = \
                self.readmissions.get(replica, 0) + 1

    def on_failover(self, replica: str, resumed: int):
        with self._lock:
            self.failovers[replica] = self.failovers.get(replica, 0) + 1
            self.resumed_streams += resumed

    # ---- views ----
    def affinity_hit_rate(self) -> float:
        with self._lock:
            if self.affinity_decisions == 0:
                return 0.0
            return self.affinity_hits / self.affinity_decisions

    def snapshot(self) -> dict:
        with self._lock:
            return {
                **self.counters,
                "reject_reasons": dict(self.reject_reasons),
                "routed": dict(self.routed),
                "replica_state": dict(self.replica_state),
                "replica_inflight": dict(self.replica_inflight),
                "quarantines": dict(self.quarantines),
                "readmissions": dict(self.readmissions),
                "failovers": dict(self.failovers),
                "replica_weight_version": dict(self.replica_weight_version),
                "replica_role": dict(self.replica_role),
                "resumed_streams": self.resumed_streams,
                "handoffs": self.handoffs,
                "handoffs_failed": self.handoffs_failed,
                "affinity_hit_rate": (
                    self.affinity_hits / self.affinity_decisions
                    if self.affinity_decisions else 0.0),
            }

    def render(self) -> str:
        b = PromBuilder()
        self._render_into(b)
        return b.render()

    def _render_into(self, b: PromBuilder):
        s = self.snapshot()
        px = self._PREFIX
        b.family(f"{px}_requests_total", "counter")
        for outcome in ("submitted", "completed", "rejected", "failed"):
            b.sample(f"{px}_requests_total", s[outcome],
                     {"outcome": outcome})
        b.family(f"{px}_rejects_total", "counter")
        for reason in sorted(s["reject_reasons"]):
            b.sample(f"{px}_rejects_total", s["reject_reasons"][reason],
                     {"reason": reason})
        b.family(f"{px}_routed_total", "counter")
        for replica in sorted(s["routed"]):
            b.sample(f"{px}_routed_total", s["routed"][replica],
                     {"replica": replica})
        b.family(f"{px}_replica_up", "gauge")
        for replica in sorted(s["replica_state"]):
            up = int(s["replica_state"][replica] == "ok")
            b.sample(f"{px}_replica_up", up, {"replica": replica})
        b.family(f"{px}_replica_inflight_tokens", "gauge")
        for replica in sorted(s["replica_inflight"]):
            b.sample(f"{px}_replica_inflight_tokens",
                     s["replica_inflight"][replica], {"replica": replica})
        b.family(f"{px}_quarantines_total", "counter")
        for replica in sorted(s["quarantines"]):
            b.sample(f"{px}_quarantines_total", s["quarantines"][replica],
                     {"replica": replica})
        b.family(f"{px}_readmissions_total", "counter")
        for replica in sorted(s["readmissions"]):
            b.sample(f"{px}_readmissions_total",
                     s["readmissions"][replica], {"replica": replica})
        b.family(f"{px}_failovers_total", "counter")
        for replica in sorted(s["failovers"]):
            b.sample(f"{px}_failovers_total", s["failovers"][replica],
                     {"replica": replica})
        b.family(f"{px}_replica_weight_info", "gauge")
        for replica in sorted(s["replica_weight_version"]):
            # info-style gauge: constant 1, the version rides the label
            b.sample(f"{px}_replica_weight_info", 1,
                     {"replica": replica,
                      "version": s["replica_weight_version"][replica]})
        b.family(f"{px}_resumed_streams_total", "counter")
        b.sample(f"{px}_resumed_streams_total", s["resumed_streams"])
        b.family(f"{px}_prefix_affinity_hit_rate", "gauge")
        b.sample(f"{px}_prefix_affinity_hit_rate", s["affinity_hit_rate"],
                 round_to=4)
        # ---- prefill/decode disaggregation families (ISSUE 19) ----
        if s["replica_role"]:
            b.family(f"{px}_replica_role_info", "gauge")
            for replica in sorted(s["replica_role"]):
                b.sample(f"{px}_replica_role_info", 1,
                         {"replica": replica,
                          "role": s["replica_role"][replica]})
        b.family(f"{px}_handoffs_total", "counter")
        b.sample(f"{px}_handoffs_total", s["handoffs"])
        b.family(f"{px}_handoffs_failed_total", "counter")
        b.sample(f"{px}_handoffs_failed_total", s["handoffs_failed"])
        hq = self.handoff_quantile_ms(0.99)
        if hq is not None:
            b.family(f"{px}_handoff_ms", "summary")
            b.sample(f"{px}_handoff_ms", hq, {"quantile": "0.99"},
                     round_to=3)
