"""Serving metrics: request counters, latency quantiles, batch-size
histogram, queue-depth gauge — collected by the BatchingEngine on every
admission/dispatch and exposed two ways:

- `render()` — Prometheus text exposition for the HTTP `/metrics` endpoint;
- `paddle_tpu.profiler.record_instant` — a `serving/dispatch` instant per
  engine dispatch, so serving activity lands on the same chrome trace
  timeline as training step spans when profiling is enabled.

Latency quantiles come from a bounded reservoir of recent completions
(exact over the window, not an approximation sketch); totals are lifetime
counters so a drain snapshot reconciles against a replayed trace:
submitted == completed + rejected + expired + failed.
"""
from __future__ import annotations

import threading
from collections import deque
from typing import Dict, Optional

# cumulative histogram upper bounds for dispatched batch rows
BATCH_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 128)


class ServingMetrics:
    def __init__(self, window: int = 4096):
        self._lock = threading.Lock()
        self.window = int(window)
        self._latencies_ms: deque = deque(maxlen=self.window)
        self.counters: Dict[str, int] = {
            "submitted": 0, "completed": 0, "rejected": 0,
            "expired": 0, "failed": 0, "dispatches": 0,
        }
        self.reject_reasons: Dict[str, int] = {}
        self.batch_hist: Dict[int, int] = {}   # exact dispatched rows -> n
        self.queue_depth = 0
        self.dispatched_rows = 0
        self.padded_rows = 0

    # ---- engine callbacks ----
    def on_submit(self, queue_depth: int):
        with self._lock:
            self.counters["submitted"] += 1
            self.queue_depth = queue_depth

    def on_reject(self, reason: str):
        with self._lock:
            self.counters["rejected"] += 1
            self.reject_reasons[reason] = self.reject_reasons.get(reason, 0) + 1

    def on_expire(self, n: int = 1):
        with self._lock:
            self.counters["expired"] += n

    def on_complete(self, latency_ms: float):
        with self._lock:
            self.counters["completed"] += 1
            self._latencies_ms.append(float(latency_ms))

    def on_fail(self, n: int = 1):
        with self._lock:
            self.counters["failed"] += n

    def on_dispatch(self, rows: int, n_requests: int, padded_rows: int,
                    dispatch_ms: float, queue_depth: int):
        with self._lock:
            self.counters["dispatches"] += 1
            self.batch_hist[rows] = self.batch_hist.get(rows, 0) + 1
            self.dispatched_rows += rows
            self.padded_rows += padded_rows - rows
            self.queue_depth = queue_depth
        from ..profiler import record_instant
        record_instant("serving/dispatch", {
            "rows": rows, "requests": n_requests,
            "padded_rows": padded_rows, "dispatch_ms": dispatch_ms,
            "queue_depth": queue_depth,
        })

    def set_queue_depth(self, depth: int):
        with self._lock:
            self.queue_depth = depth

    # ---- views ----
    def quantile_ms(self, q: float) -> Optional[float]:
        with self._lock:
            lat = sorted(self._latencies_ms)
        if not lat:
            return None
        idx = min(len(lat) - 1, max(0, int(round(q * (len(lat) - 1)))))
        return lat[idx]

    def snapshot(self) -> dict:
        with self._lock:
            counters = dict(self.counters)
            hist = dict(self.batch_hist)
            depth = self.queue_depth
            rows, padded = self.dispatched_rows, self.padded_rows
        mean_batch = rows / counters["dispatches"] if counters["dispatches"] \
            else 0.0
        return {
            **counters,
            "queue_depth": depth,
            "batch_hist": hist,
            "mean_batch_rows": mean_batch,
            "pad_overhead_rows": padded,
            "p50_ms": self.quantile_ms(0.50),
            "p95_ms": self.quantile_ms(0.95),
            "p99_ms": self.quantile_ms(0.99),
        }

    def render(self) -> str:
        """Prometheus text exposition (served at /metrics)."""
        s = self.snapshot()
        lines = [
            "# TYPE pdtpu_serving_requests_total counter",
        ]
        for outcome in ("submitted", "completed", "rejected", "expired",
                        "failed"):
            lines.append("pdtpu_serving_requests_total"
                         f'{{outcome="{outcome}"}} {s[outcome]}')
        lines += [
            "# TYPE pdtpu_serving_dispatches_total counter",
            f"pdtpu_serving_dispatches_total {s['dispatches']}",
            "# TYPE pdtpu_serving_queue_depth gauge",
            f"pdtpu_serving_queue_depth {s['queue_depth']}",
            "# TYPE pdtpu_serving_latency_ms summary",
        ]
        for q, key in ((0.5, "p50_ms"), (0.95, "p95_ms"), (0.99, "p99_ms")):
            v = s[key]
            lines.append(f'pdtpu_serving_latency_ms{{quantile="{q}"}} '
                         f"{'NaN' if v is None else round(v, 3)}")
        lines.append("# TYPE pdtpu_serving_batch_rows histogram")
        cum = 0
        hist = s["batch_hist"]
        for le in BATCH_BUCKETS:
            cum = sum(n for rows, n in hist.items() if rows <= le)
            lines.append(f'pdtpu_serving_batch_rows_bucket{{le="{le}"}} {cum}')
        lines.append('pdtpu_serving_batch_rows_bucket{le="+Inf"} '
                     f"{sum(hist.values())}")
        lines.append(f"pdtpu_serving_batch_rows_count {sum(hist.values())}")
        lines.append("pdtpu_serving_batch_rows_sum "
                     f"{sum(r * n for r, n in hist.items())}")
        return "\n".join(lines) + "\n"


def parse_exposition(text: str) -> Dict[str, float]:
    """Inverse of render() for tests/tools: flat {metric{labels}: value}."""
    out: Dict[str, float] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        name, _, val = line.rpartition(" ")
        try:
            out[name] = float(val)
        except ValueError:
            continue
    return out
