"""Online serving runtime (ISSUE 3): continuous micro-batching over an
exported model — the scheduling layer between concurrent user requests and
batched TPU dispatches.

    training (parallel/) -> export (inference.export_model) -> serve (here)

    from paddle_tpu import inference, serving
    pred = inference.load_predictor("/models/my_model")
    engine = serving.BatchingEngine.from_predictor(
        pred, serving.EngineConfig(max_batch_size=16, max_wait_ms=4))
    server = serving.ServingServer(engine, port=8000)
    server.serve_forever()        # SIGTERM -> graceful drain, exit 0

Deterministic scheduler testing (no real sleeps):

    clock = serving.SimClock()
    engine = serving.BatchingEngine(fn, cfg, clock=clock)
    report = serving.replay(engine, serving.poisson_trace(...))

See docs/serving.md for architecture and tuning (max_wait_ms vs p99,
pow2 bucketing vs symbolic-batch exports) and its reliability section
(ISSUE 6) for the supervision + overload-control layer: EngineSupervisor
(hung-dispatch watchdog, typed DispatchFailedError, circuit breaker ->
/healthz 503 + drain), SLO classes with shed-lowest-first admission,
token-budget backpressure (HTTP 429 + Retry-After) and brownout.
"""
from .clock import Clock, MonotonicClock, SimClock  # noqa: F401
from .deploy import DeployConfig, DeploymentController  # noqa: F401
from .engine import (BatchingEngine, DeadlineExceededError,  # noqa: F401
                     EngineConfig, RejectedError)
from .metrics import (SLO_CLASSES, LLMMetrics, RouterMetrics,  # noqa: F401
                      ServingMetrics, parse_exposition)
from .supervisor import (DispatchFailedError, DispatchHungError,  # noqa: F401
                         EngineSupervisor)
from .sim import (Arrival, ReplayReport, poisson_trace,  # noqa: F401
                  replay, uniform_trace)
from .server import ServingServer, serve  # noqa: F401
from .router import (InProcessReplica, ReplicaRouter,  # noqa: F401
                     RouterConfig, RouterHandle, RouterServer)
from . import llm  # noqa: F401
from .llm import (GenerationHandle, LLMEngine,  # noqa: F401
                  LLMEngineConfig, PrefixCache, SlotPagedKVPool,
                  SlotsExhaustedError, WeightSwapError)
