"""Continuous micro-batching engine (ISSUE 3 tentpole).

Turns concurrent single-request traffic into efficient batched TPU
dispatches over one compiled executable — the scheduling layer the Ragged
Paged Attention / Gemma-on-TPU serving comparisons show TPU throughput is
won or lost in:

- bounded request queue + per-request futures (admission control: a full
  queue fast-fails with `RejectedError` instead of building unbounded
  latency; a draining engine rejects immediately);
- a scheduler that coalesces requests into batches and flushes on
  `max_batch_size` rows OR `max_wait_ms` since the oldest pending request,
  whichever comes first;
- per-request deadlines enforced BEFORE dispatch: expired requests are
  dropped at batch formation (their rows never reach the device), not
  discovered after a wasted dispatch;
- shape discipline per export flavor: a symbolic-batch export
  (`export_model(dynamic_batch=True)`) is dispatched at the exact coalesced
  row count (the module accepts any leading size natively); a static export
  is padded to the next power of two (bucketed batching) so the number of
  distinct dispatch shapes — and compiled-executable cache entries for
  plain-callable backends — stays logarithmic.

Determinism: every flush decision is a pure function of `clock.now()`.
Under a `SimClock` (serving/clock.py) the engine runs threadless and the
simulation harness (serving/sim.py) drives `pump()` at scripted instants;
under the default `MonotonicClock`, `start()` runs the same `pump()` from a
scheduler thread woken by a condition variable. One code path, two time
sources — the unit tests exercise exactly the production scheduler.
"""
from __future__ import annotations

import logging
import threading
from collections import deque
from concurrent.futures import Future
from dataclasses import dataclass
from typing import Callable, List, Optional, Sequence

import numpy as np

from ..obs.flight_recorder import flight_recorder
from ..obs.trace import (SERVING_PHASES, RequestTrace, TimelineStore,
                         new_request_id)
from .clock import Clock, MonotonicClock, SimClock
from .metrics import ServingMetrics
from .supervisor import DispatchFailedError, EngineSupervisor

_log = logging.getLogger("paddle_tpu.serving")


class RejectedError(RuntimeError):
    """Admission control fast-fail. `reason` is machine-readable and
    matches the reject-reason metric label ("queue_full", "draining",
    "shed", "token_budget", "circuit_open", "drain_timeout", ...);
    `retry_after_s`, when set, is the backpressure hint the HTTP layer
    surfaces as a Retry-After header on 429 responses."""

    def __init__(self, msg: str, reason: str = "rejected",
                 retry_after_s: Optional[float] = None):
        super().__init__(msg)
        self.reason = reason
        self.retry_after_s = retry_after_s


class DeadlineExceededError(TimeoutError):
    """The request's deadline expired while queued; it was dropped before
    dispatch (its rows never reached the device)."""


@dataclass
class EngineConfig:
    max_batch_size: int = 8        # flush when coalesced rows reach this
    max_wait_ms: float = 5.0       # ...or the oldest request waited this long
    max_queue_depth: int = 256     # pending-request cap (admission control)
    max_request_rows: Optional[int] = None  # per-request row cap (None: an
    #                                         oversized request dispatches
    #                                         alone, pow2-padded)
    default_deadline_ms: Optional[float] = None  # per-request override wins
    bucket_pow2: Optional[bool] = None  # None: True for static exports /
    #                                     plain callables, False for
    #                                     symbolic-batch (dynamic) exports
    drain_timeout_s: float = 30.0
    dispatch_timeout_s: Optional[float] = None  # hung-dispatch watchdog
    #                                  (None: a wedged predict_fn blocks the
    #                                  scheduler until drain_timeout_s bails
    #                                  the queue out)
    breaker_threshold: int = 3     # consecutive failed dispatches that open
    #                                the engine circuit breaker
    retry_after_s: float = 1.0     # backpressure hint on overload rejects
    economics: bool = False        # arm the serving economics ledger
    #                                (ISSUE 11): pump phase tiling +
    #                                pad-waste token efficiency; off = one
    #                                predicate per hook
    observatory: bool = False      # register every predict executable with
    #                                the process-global CompileObservatory
    #                                (ISSUE 12); off = one predicate

    def __post_init__(self):
        if self.max_batch_size < 1:
            raise ValueError(
                f"max_batch_size must be >= 1, got {self.max_batch_size}")
        if self.max_wait_ms < 0:
            raise ValueError(
                f"max_wait_ms must be >= 0, got {self.max_wait_ms}")
        if self.max_queue_depth < 1:
            raise ValueError(
                f"max_queue_depth must be >= 1, got {self.max_queue_depth}")
        if self.max_request_rows is not None and self.max_request_rows < 1:
            raise ValueError(
                f"max_request_rows must be >= 1, got "
                f"{self.max_request_rows}")
        if self.breaker_threshold < 1:
            raise ValueError(
                f"breaker_threshold must be >= 1, got "
                f"{self.breaker_threshold}")


class _Request:
    __slots__ = ("inputs", "rows", "arrival", "deadline", "future", "rid",
                 "trace")

    def __init__(self, inputs, rows, arrival, deadline):
        self.inputs = inputs          # list of np arrays, leading batch dim
        self.rows = rows
        self.arrival = arrival        # clock seconds
        self.deadline = deadline      # absolute clock seconds or None
        self.future: Future = Future()
        self.rid: Optional[str] = None
        self.trace: Optional[RequestTrace] = None  # None: untraced (the
        #                                            hot-path cost is one
        #                                            `is not None` test)


def _next_pow2(n: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return p


def _coalescable(head: "_Request", r: "_Request") -> bool:
    """Two requests may share a dispatch only when their inputs concatenate
    cleanly AND mean the same thing to the executable: same input count,
    same trailing shapes, same dtypes. Independent HTTP clients owe each
    other nothing — without this check one client's odd shapes would poison
    a stranger's batch."""
    if len(head.inputs) != len(r.inputs):
        return False
    return all(a.shape[1:] == b.shape[1:] and a.dtype == b.dtype
               for a, b in zip(head.inputs, r.inputs))


class BatchingEngine:
    """`submit()` request rows, get a Future of per-request outputs.

    predict_fn: list-of-arrays (each with a shared leading batch dim) ->
        sequence of output arrays. Built from a Predictor via
        `BatchingEngine.from_predictor` (the recommended path: it also picks
        the right bucketing mode from the export's `dynamic_batch` flag).

    Each request's inputs must carry a leading batch dim (>= 1 rows); the
    engine concatenates along axis 0, dispatches, and splits batched
    outputs back by the request row counts. An output whose leading dim
    does not ride the batch is delivered whole to every request in the
    dispatch (constant / state-table outputs).
    """

    def __init__(self, predict_fn: Callable, config: Optional[EngineConfig]
                 = None, clock: Optional[Clock] = None,
                 metrics: Optional[ServingMetrics] = None,
                 dynamic_batch: bool = False, fault_plan=None,
                 on_break: Optional[Callable[[], None]] = None):
        self.predict_fn = predict_fn
        self.config = config or EngineConfig()
        self.clock = clock or MonotonicClock()
        self.metrics = metrics or ServingMetrics()
        self.dynamic_batch = bool(dynamic_batch)
        self._bucket = (not self.dynamic_batch
                        if self.config.bucket_pow2 is None
                        else bool(self.config.bucket_pow2))
        self._pending: deque = deque()
        self._cond = threading.Condition()
        self._draining = False
        self._stopped = False
        self._thread: Optional[threading.Thread] = None
        # supervision (ISSUE 6): watchdog-bounded dispatches, a circuit
        # breaker over consecutive dispatch failures, and the shared
        # fault-injection plan (None -> the env-driven global plan)
        if fault_plan is None:
            from ..utils.fault_injection import global_plan
            fault_plan = global_plan()
        self._fault_plan = fault_plan
        self.on_break = on_break
        self.supervisor = EngineSupervisor(
            dispatch_timeout_s=self.config.dispatch_timeout_s,
            breaker_threshold=self.config.breaker_threshold,
            on_trip=self._on_breaker_trip, name="serving")
        self._dispatch_idx = 0   # running count of supervised dispatches
        # finished-request timelines, bounded LRU (served by the HTTP
        # layer's /debug/requests endpoint)
        self.timelines = TimelineStore(256)
        # serving economics (ISSUE 11): None unless armed — every hook
        # below guards on this one predicate
        self.ledger = None
        if self.config.economics:
            from ..obs.serving_ledger import ServingLedger
            self.ledger = ServingLedger(clock=self.clock.now)
        self.metrics.ledger = self.ledger
        # compile observatory (ISSUE 12): None unless armed
        self.observatory = None
        if self.config.observatory:
            from ..obs.compile_observatory import compile_observatory
            self.observatory = compile_observatory().enable()

    @classmethod
    def from_predictor(cls, predictor, config: Optional[EngineConfig] = None,
                       clock: Optional[Clock] = None,
                       metrics: Optional[ServingMetrics] = None
                       ) -> "BatchingEngine":
        """Wrap an inference.Predictor: symbolic-batch exports dispatch at
        the native coalesced size, static exports get pow2 bucketing (the
        predictor then pads/chunks the bucket to its exported batch)."""
        dyn = bool(predictor._meta.get("dynamic_batch"))
        return cls(lambda args: predictor.run(list(args)), config=config,
                   clock=clock, metrics=metrics, dynamic_batch=dyn)

    # ---- lifecycle ----
    def start(self) -> "BatchingEngine":
        """Run the scheduler on a background thread (production mode). Not
        needed under a SimClock — the sim harness calls pump() itself."""
        if isinstance(self.clock, SimClock):
            raise RuntimeError(
                "BatchingEngine.start() with a SimClock would busy-spin: "
                "drive pump() from the simulation harness instead")
        with self._cond:
            if self._stopped:
                raise RuntimeError("engine already stopped")
            if self._thread is not None:
                return self
            self._thread = threading.Thread(
                target=self._scheduler_main, daemon=True,
                name="pdtpu-serving-scheduler")
            self._thread.start()
        return self

    def stop(self, drain: bool = True, timeout: Optional[float] = None):
        """Graceful drain: stop admissions (submit -> RejectedError), flush
        every already-accepted request, then stop the scheduler. With
        drain=False pending futures fail with RejectedError instead."""
        with self._cond:
            if self._stopped:
                return
            self._draining = True
            flight_recorder().record("drain_begin", engine="serving",
                                     drain=drain, queued=len(self._pending))
            if not drain:
                while self._pending:
                    req = self._pending.popleft()
                    self._conclude(req, "rejected:shutdown")
                    req.future.set_exception(
                        RejectedError("engine shut down before dispatch",
                                      reason="shutdown"))
                    self.metrics.on_reject("shutdown")
                self.metrics.set_queue_depth(0)
            self._cond.notify_all()
            thread = self._thread
        if thread is not None:
            join_s = (timeout if timeout is not None
                      else self.config.drain_timeout_s)
            thread.join(join_s)
            if thread.is_alive():
                _log.warning(
                    "serving drain did not complete within %.1fs; failing "
                    "requests still queued", join_s)
        else:
            # threadless (sim) mode: flush inline — draining makes every
            # pending batch due
            self.pump()
        with self._cond:
            # a timed-out (or dead) scheduler leaves accepted requests
            # queued forever — fail them now so waiting callers get a
            # definite answer instead of blocking until their own future
            # timeouts (after a clean drain this deque is already empty)
            stranded = 0
            while self._pending:
                req = self._pending.popleft()
                self._conclude(req, "rejected:drain_timeout")
                req.future.set_exception(RejectedError(
                    "engine drain timed out before dispatch",
                    reason="drain_timeout"))
                self.metrics.on_reject("drain_timeout")
                stranded += 1
            if stranded:
                self.metrics.set_queue_depth(0)
            self._stopped = True
            self._cond.notify_all()
        flight_recorder().record("drain_end", engine="serving",
                                 stranded=stranded)

    @property
    def draining(self) -> bool:
        return self._draining

    @property
    def broken(self) -> bool:
        """Circuit breaker open: the engine saw `breaker_threshold`
        consecutive dispatch failures and has stopped admitting."""
        return self.supervisor.open

    def _on_breaker_trip(self):
        """Repeated engine-level failures: stop admitting (submit ->
        RejectedError reason "circuit_open"), fail everything still queued
        — each pending dispatch would only fail again — and notify the
        front end (which flips /healthz to 503 and starts a drain on its
        own thread)."""
        flushed = 0
        with self._cond:
            while self._pending:
                req = self._pending.popleft()
                self._conclude(req, "rejected:circuit_open")
                req.future.set_exception(RejectedError(
                    "engine circuit breaker open after repeated dispatch "
                    "failures", reason="circuit_open"))
                self.metrics.on_reject("circuit_open")
                flushed += 1
            self.metrics.set_queue_depth(0)
            self._cond.notify_all()
        flight_recorder().record("queue_flushed", engine="serving",
                                 reason="circuit_open", n=flushed)
        self.metrics.set_circuit_open(True)
        if self.on_break is not None:
            try:
                self.on_break()
            except Exception:
                _log.exception("on_break callback failed")

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop(drain=True)
        return False

    # ---- tracing / black-box hooks ----
    def _conclude(self, req: _Request, outcome: str,
                  now: Optional[float] = None):
        """Finalize a request's trace (if any) and publish its timeline."""
        if req.trace is None:
            return
        tr = req.trace
        tr.finish(self.clock.now() if now is None else now, outcome)
        self.timelines.put(tr.rid, tr.to_dict())
        tr.emit_chrome()

    def _record_reject(self, reason: str, rid: Optional[str] = None):
        flight_recorder().record("reject", engine="serving", reason=reason,
                                 rid=rid)

    # ---- admission ----
    def submit(self, inputs, deadline_ms: Optional[float] = None,
               rid: Optional[str] = None, trace: bool = False) -> Future:
        """Admit one request. inputs: array or list of arrays, each with a
        leading batch dim (>= 1 rows, all inputs agreeing). Raises
        RejectedError when the queue is full or the engine is draining.

        `rid` is the request id (ingested from a `traceparent` header by
        the HTTP layer, or generated here); `trace=True` additionally
        records a structured timeline, retrievable from
        `engine.timelines` after the request finishes."""
        if not isinstance(inputs, (list, tuple)):
            inputs = [inputs]
        arrays = [np.asarray(a) for a in inputs]
        if not arrays or arrays[0].ndim < 1:
            raise ValueError(
                "request inputs must be non-empty arrays with a leading "
                "batch dim (wrap a single sample as shape (1, ...))")
        rows = arrays[0].shape[0]
        for a in arrays:
            if a.ndim < 1 or a.shape[0] != rows:
                raise ValueError(
                    f"all request inputs must share the leading batch dim "
                    f"({rows}); got shapes "
                    f"{[tuple(x.shape) for x in arrays]}")
        rid = rid or new_request_id()
        if (self.config.max_request_rows is not None
                and rows > self.config.max_request_rows):
            self.metrics.on_reject("too_many_rows")
            self._record_reject("too_many_rows", rid=rid)
            raise RejectedError(
                f"request rows ({rows}) exceed max_request_rows "
                f"({self.config.max_request_rows})", reason="too_many_rows")
        if deadline_ms is None:
            deadline_ms = self.config.default_deadline_ms
        now = self.clock.now()
        deadline = None if deadline_ms is None else now + deadline_ms / 1e3
        with self._cond:
            if self.supervisor.open:
                self.metrics.on_reject("circuit_open")
                self._record_reject("circuit_open", rid=rid)
                raise RejectedError(
                    "engine circuit breaker open after repeated dispatch "
                    "failures; request rejected", reason="circuit_open")
            if self._draining or self._stopped:
                self.metrics.on_reject("draining")
                self._record_reject("draining", rid=rid)
                raise RejectedError("engine is draining; request rejected",
                                    reason="draining")
            if len(self._pending) >= self.config.max_queue_depth:
                self.metrics.on_reject("queue_full")
                self._record_reject("queue_full", rid=rid)
                raise RejectedError(
                    f"queue at capacity ({self.config.max_queue_depth} "
                    "pending requests)", reason="queue_full",
                    retry_after_s=self.config.retry_after_s)
            req = _Request(arrays, rows, now, deadline)
            req.rid = rid
            if trace:
                req.trace = RequestTrace(rid, now,
                                         phase_defs=SERVING_PHASES)
                req.trace.event("submitted", now, rows=rows)
            self._pending.append(req)
            self.metrics.on_submit(len(self._pending))
            self._cond.notify_all()
        return req.future

    def predict(self, inputs, deadline_ms: Optional[float] = None,
                timeout: Optional[float] = None) -> List[np.ndarray]:
        """Synchronous convenience: submit + wait."""
        return self.submit(inputs, deadline_ms=deadline_ms).result(timeout)

    # ---- scheduling ----
    def next_event_time(self) -> Optional[float]:
        """Clock instant of the next time-driven action (oldest request's
        max_wait flush, or the earliest deadline expiry) — None when the
        queue is empty. The sim harness advances the clock here between
        scripted arrivals."""
        with self._cond:
            if not self._pending:
                return None
            t = self._pending[0].arrival + self.config.max_wait_ms / 1e3
            for r in self._pending:
                if r.deadline is not None:
                    t = min(t, r.deadline)
            return t

    def pump(self) -> int:
        """One scheduler pass: drop expired requests, dispatch every batch
        that is due at clock.now(). Returns the number of dispatches. This
        is THE scheduler — the background thread and the sim harness both
        call it.

        With economics armed (ISSUE 11) the pass runs inside the serving
        ledger's ``measure("host")`` frame; `_dispatch` books each
        predict's device span out of it, so host/compute/idle tile the
        pump wall clock."""
        led = self.ledger
        if led is None:
            return self._pump_inner()
        with led.measure("host"):
            return self._pump_inner()

    def _pump_inner(self) -> int:
        dispatched = 0
        while True:
            batch = self._take_batch()
            if not batch:
                return dispatched
            self._dispatch(batch)
            dispatched += 1

    def _take_batch(self) -> Optional[List[_Request]]:
        now = self.clock.now()
        with self._cond:
            self._drop_expired_locked(now)
            if not self._pending:
                return None
            total_rows = sum(r.rows for r in self._pending)
            # compare against the ABSOLUTE flush instant (the same
            # expression next_event_time/the scheduler thread compute) —
            # re-deriving a waited-duration here loses a float ulp and a
            # pump at exactly the flush instant would never come due
            flush_t = self._pending[0].arrival + self.config.max_wait_ms / 1e3
            due = (total_rows >= self.config.max_batch_size
                   or now >= flush_t
                   or self._draining)
            if not due:
                return None
            batch, rows = [], 0
            while self._pending:
                r = self._pending[0]
                if batch and (rows + r.rows > self.config.max_batch_size
                              or not _coalescable(batch[0], r)):
                    break       # incompatible request starts its own batch
                batch.append(self._pending.popleft())
                rows += r.rows
            self.metrics.set_queue_depth(len(self._pending))
            return batch

    def _drop_expired_locked(self, now: float):
        if not self._pending:
            return
        alive = deque()
        expired = 0
        for r in self._pending:
            if r.deadline is not None and now >= r.deadline:
                self._conclude(r, "expired:queued", now)
                r.future.set_exception(DeadlineExceededError(
                    f"deadline expired after "
                    f"{(now - r.arrival) * 1e3:.1f}ms in queue "
                    "(dropped before dispatch)"))
                expired += 1
            else:
                alive.append(r)
        if expired:
            self._pending = alive
            self.metrics.on_expire(expired)
            self.metrics.set_queue_depth(len(alive))

    # ---- dispatch ----
    def _supervised_predict(self, args):
        """One watchdog-bounded, fault-injectable predict dispatch. Raises
        DispatchFailedError / DispatchHungError, counted by the circuit
        breaker: the stateless engine has no per-request retry (a batch's
        rows left the queue; re-running them after a partial failure could
        double-apply side-effectful predictors), so every failed dispatch
        is an engine-level failure."""
        idx = self._dispatch_idx
        self._dispatch_idx += 1
        plan = self._fault_plan

        def guarded():
            if plan is not None:
                plan.maybe_dispatch_fault(idx, kind="predict")
            return self.predict_fn(args)

        try:
            outs = self.supervisor.run(guarded, label="predict")
        except DispatchFailedError as e:
            self.metrics.on_dispatch_failure(e.reason)
            self.supervisor.record_failure()
            raise
        self.supervisor.record_success()
        return outs

    def _dispatch(self, batch: List[_Request]):
        t0 = self.clock.now()
        total = sum(r.rows for r in batch)
        padded = total
        for r in batch:
            if r.trace is not None:
                r.trace.mark("dispatched", t0)
                r.trace.event("dispatched", t0, batch_rows=total,
                              batch_requests=len(batch))
        # batch assembly sits INSIDE the try: an exception anywhere between
        # here and predict_fn must fail this batch's futures, never escape
        # into (and kill) the scheduler thread
        try:
            n_inputs = len(batch[0].inputs)
            args = [np.concatenate([r.inputs[i] for r in batch], axis=0)
                    for i in range(n_inputs)]
            if self._bucket:
                if total <= self.config.max_batch_size:
                    padded = min(_next_pow2(total),
                                 self.config.max_batch_size)
                else:
                    # a single request larger than max_batch_size still
                    # dispatches on a pow2 shape, keeping the number of
                    # distinct compiled shapes logarithmic
                    padded = _next_pow2(total)
                if padded > total:
                    args = [np.concatenate(
                        [a,
                         np.zeros((padded - total,) + a.shape[1:], a.dtype)],
                        axis=0) for a in args]
            if self.observatory is not None:
                self.observatory.observe_call(
                    "serve/predict", self.predict_fn, tuple(args))
            tc0 = self.clock.now() \
                if self.ledger is not None or self.observatory is not None \
                else None
            outs = list(self._supervised_predict(args))
        except Exception as e:
            for r in batch:
                self._conclude(r, "failed:dispatch")
                r.future.set_exception(e)
            self.metrics.on_fail(len(batch))
            return
        if self.ledger is not None or self.observatory is not None:
            # block on the device results so the measured span is
            # execution; real rows are "prefill" positions and the pow2
            # pad rows are the waste token_efficiency exposes. The
            # stateless engine has no row ownership -> no owner meters.
            import jax
            jax.block_until_ready(outs)
            dt = self.clock.now() - tc0
            if self.ledger is not None:
                self.ledger.book_dispatch(
                    dt, prefill_positions=total,
                    decode_positions=0, total_positions=padded, owners=())
            if self.observatory is not None:
                # blocked above, so dt is device execution (ISSUE 12)
                self.observatory.note_device_seconds("serve/predict", dt)
        # un-pad, then split batched outputs by request row counts
        trimmed = []
        for o in outs:
            o = np.asarray(o)
            if padded != total and o.ndim >= 1 and o.shape[0] == padded:
                o = o[:total]
            trimmed.append(o)
        now = self.clock.now()
        offset = 0
        for r in batch:
            result = []
            for o in trimmed:
                if o.ndim >= 1 and o.shape[0] == total:
                    result.append(o[offset:offset + r.rows])
                else:  # non-batched output (constant/state table)
                    result.append(o)
            offset += r.rows
            # finalize the trace BEFORE resolving the future: a waiter
            # unblocked by set_result must find the completed timeline
            self._conclude(r, "completed", now)
            r.future.set_result(result)
            self.metrics.on_complete((now - r.arrival) * 1e3)
        with self._cond:
            depth = len(self._pending)
        self.metrics.on_dispatch(total, len(batch), padded,
                                 (now - t0) * 1e3, depth)

    # ---- scheduler thread (production mode) ----
    def _scheduler_main(self):
        cfg = self.config
        while True:
            with self._cond:
                while True:
                    if self._stopped:
                        return
                    if self._draining and not self._pending:
                        return          # drained: stop() joins us
                    if self._pending:
                        now = self.clock.now()
                        total = sum(r.rows for r in self._pending)
                        wake = self._pending[0].arrival + cfg.max_wait_ms / 1e3
                        for r in self._pending:
                            if r.deadline is not None:
                                wake = min(wake, r.deadline)
                        if (total >= cfg.max_batch_size or now >= wake
                                or self._draining):
                            break
                        self.clock.wait(self._cond, max(0.0, wake - now))
                    else:
                        self.clock.wait(self._cond, None)
            try:
                self.pump()
            except Exception as e:
                # _dispatch already routes per-batch errors to the batch's
                # futures; anything escaping pump() is a scheduler bug. Log
                # and keep scheduling — a dead scheduler would wedge every
                # queued and future request until their own timeouts.
                _log.exception("serving scheduler pump failed; continuing")
                fr = flight_recorder()
                fr.record("pump_exception", engine="serving",
                          error=f"{type(e).__name__}: {e}")
                fr.try_dump(reason="pump_exception:serving")
