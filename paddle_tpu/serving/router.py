"""Fault-tolerant multi-replica serving tier (ISSUE 14).

One LLMEngine is a single blast domain: a breaker trip, a hung forward,
or a process loss takes every in-flight stream with it. This module puts
a front-of-fleet router over N engine replicas so the fleet degrades one
replica at a time instead:

- **Routing** is prefix-aware then load-aware: every admission probes
  each healthy replica's radix prefix cache (`LLMEngine.prefix_probe`, a
  read-only walk that moves no refcounts or LRU ticks) and routes to the
  longest block-aligned match, tie-broken by in-flight token load, then
  replica index. Affinity compounds: the replica that served a tenant's
  prefix keeps winning that prefix, so fleet-wide hit rate approaches
  single-engine hit rate instead of 1/N-ing it.

- **Supervision** speaks the existing breaker vocabulary. Each pump the
  router reads replica health (crashed / broken / draining / ok — the
  same words `/healthz` serves) and runs a hung-forward watchdog on the
  engine's dispatch counter. Consecutive watchdog failures, or any
  hard-down state, quarantine the replica; re-admission is probed on an
  exponential backoff ladder so a flapping replica cannot oscillate
  traffic. When the whole fleet is quarantined or saturated the router
  sheds at its own door (RejectedError -> 429 + Retry-After upstream),
  best-effort traffic first.

- **Zero dropped streams.** When a replica dies mid-decode, every
  in-flight stream it owned is re-prefilled on a survivor from the
  tokens already emitted: resubmit concat(prompt, emitted) with the
  remaining token budget. Decoding is greedy (argmax), so the survivor's
  continuation is bit-identical to what the dead replica would have
  produced — the stitched stream equals an uninterrupted single-engine
  `generate()` exactly, regardless of where the failure landed. Each
  resumed stream is recorded as a `router_failover` flight event naming
  the dead replica and the rid, in submit order.

Replicas are in-process (`InProcessReplica`): the engine pump split off
the HTTP front end, so N replicas run under one SimClock and the whole
failover dance is scripted-time deterministic in tests. `RouterServer`
is the HTTP face (same /generate contract as `ServingServer`, plus
fleet-level /healthz and pdtpu_router_* /metrics).

Prefill/decode disaggregation (ISSUE 19): replicas carry a role
(`prefill` / `decode` / `mixed`). A stream that finishes prefill on a
prefill-role replica exports its KV row + sampling lane atomically
(`LLMEngine.export_stream`) and is re-placed decode-first with the
staged payload, paying a one-token prefill on the destination instead
of recomputing the prompt. The staged KV stays on the handle until the
stream completes, so a decode replica crashing right after the handoff
re-places the SAME payload — and when it has gone stale (tokens emitted
since), the stream falls back to the ordinary failover re-prefill.
Role preference is exactly that — a preference: every healthy replica
stays in the ranked candidate list, because zero dropped streams beats
role purity.
"""
from __future__ import annotations

import json
import logging
import re
import threading
import time
from concurrent.futures import Future
from dataclasses import dataclass
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..obs.flight_recorder import flight_recorder
from ..obs.trace import ingest_traceparent, new_request_id
from ..utils.fault_injection import FaultPlan, global_plan
from .clock import Clock, SimClock
from .engine import DeadlineExceededError, RejectedError
from .llm.sampling import SamplingParams
from .metrics import RouterMetrics, SLO_CLASSES

_log = logging.getLogger("paddle_tpu.serving.router")


# ---------------------------------------------------------------------------
# replica: engine pump split off the HTTP front end


class InProcessReplica:
    """One LLMEngine as a routable fleet member.

    Wraps the engine with an identity (index/name), a crash switch, and
    the replica-tier fault injection point (`replica_crash@i`,
    `replica_hang@i:s`, `replica_slow@i:ms` — keyed on the replica INDEX,
    polled at the top of every pump). Under SimClock the router pumps the
    engine through here; under MonotonicClock the engine runs its own
    scheduler thread and `pump()` only applies faults and observes
    progress for the hung-forward watchdog."""

    def __init__(self, engine, index: int, name: Optional[str] = None,
                 fault_plan: Optional[FaultPlan] = None,
                 role: str = "mixed"):
        if role not in ("prefill", "decode", "mixed"):
            raise ValueError(
                f"role must be 'prefill', 'decode' or 'mixed', got "
                f"{role!r}")
        self.engine = engine
        self.index = int(index)
        self.name = name or f"replica{index}"
        self.role = role
        self.clock: Clock = engine.clock
        self._fault_plan = fault_plan
        self.crashed = False
        self._hang_until: Optional[float] = None
        self.last_progress = self.clock.now()
        self._seen_idx = engine._dispatch_idx
        # rolling-deploy lifecycle (ISSUE 16): serving | draining |
        # swapping | canary. Any non-serving state excludes the replica
        # from placement (health() != "ok") but — unlike quarantine —
        # keeps it pumped, so streams finishing in place still decode.
        self.deploy_state = "serving"
        self._swap_ready_at: Optional[float] = None

    # -- health vocabulary (same words /healthz speaks) --

    def health(self) -> str:
        if self.crashed:
            return "crashed"
        if self.engine.broken:
            return "broken"
        if self.engine.draining:
            return "draining"
        if self.deploy_state != "serving":
            # deploy lifecycle word: "draining" / "swapping" / "canary"
            return self.deploy_state
        return "ok"

    @property
    def weight_version(self) -> str:
        return self.engine.weight_version

    # -- routing inputs --

    def prefix_probe(self, prompt, tenant: Optional[str] = None,
                     adapter: Optional[str] = None) -> int:
        if self.crashed:
            return 0
        return self.engine.prefix_probe(prompt, tenant=tenant,
                                        adapter=adapter)

    def inflight_tokens(self) -> int:
        if self.crashed:
            return 1 << 30
        return self.engine.inflight_tokens()

    # -- admission --

    def submit(self, *args, **kwargs):
        if self.crashed:
            raise RejectedError(
                f"replica {self.name} is down", reason="replica_down",
                retry_after_s=1.0)
        return self.engine.submit(*args, **kwargs)

    def export_stream(self, rid: str) -> dict:
        """Atomic KV + lane export for a prefill→decode handoff
        (ISSUE 19). ValueError propagates when the stream is still
        mid-prefill; RuntimeError when the replica is down."""
        if self.crashed:
            raise RuntimeError(f"replica {self.name} is down")
        return self.engine.export_stream(rid)

    # -- lifecycle --

    def crash(self):
        """Hard-kill analog: the replica stops answering anything. A live
        engine thread is torn down (a dead process stops computing);
        under SimClock the engine is simply never pumped again — either
        way in-flight state is abandoned exactly as a process loss would
        abandon it, and only the handles' already-emitted tokens survive
        for the router to re-prefill from."""
        if self.crashed:
            return
        self.crashed = True
        if getattr(self.engine, "_thread", None) is not None:
            try:
                self.engine.stop(drain=False, timeout=10.0)
            except Exception:
                _log.exception("replica %s: engine stop after crash failed",
                               self.name)

    # -- rolling-deploy lifecycle (ISSUE 16) --

    def drain(self):
        """Enter deploy-drain: placement skips this replica from now on
        (health() reads "draining") while it keeps being pumped, so any
        stream the router chose to leave in place decodes to completion.
        The router's `drain_replica` is the entry point — it also moves
        movable streams; call that, not this, unless testing."""
        if self.crashed:
            raise RuntimeError(f"replica {self.name} is crashed")
        self.deploy_state = "draining"

    def swap(self, params, version: str):
        """In-place weight swap on a drained, idle replica. Applies the
        `swap_stall@i:s` fault clause (the new weights need s more
        seconds to be trustworthy — `swap_ready()` gates the canary),
        then delegates to the engine's signature-checked
        `replace_params`."""
        if self.crashed:
            raise RuntimeError(f"replica {self.name} is crashed")
        if self.deploy_state != "draining":
            raise RuntimeError(
                f"replica {self.name} must be draining to swap "
                f"(deploy_state={self.deploy_state!r})")
        plan = (self._fault_plan if self._fault_plan is not None
                else global_plan())
        if plan is not None:
            stall = plan.maybe_swap_stall(self.index)
            if stall is not None:
                self._swap_ready_at = self.clock.now() + float(stall)
        self.engine.replace_params(params, version)
        self.deploy_state = "swapping"

    def swap_ready(self) -> bool:
        """True once any injected swap stall has elapsed."""
        if self._swap_ready_at is None:
            return True
        if self.clock.now() >= self._swap_ready_at:
            self._swap_ready_at = None
            return True
        return False

    def mark_canary(self):
        self.deploy_state = "canary"

    def readmit(self):
        """Leave the deploy lifecycle: placement sees the replica again."""
        self.deploy_state = "serving"
        self._swap_ready_at = None

    def observe_progress(self, now: float):
        """Watchdog input: the dispatch counter moved, or there is
        nothing to dispatch — either counts as forward progress."""
        idx = self.engine._dispatch_idx
        if idx != self._seen_idx or not self.engine.has_work():
            self._seen_idx = idx
            self.last_progress = now

    def pump(self) -> int:
        """One supervised scheduling step. Applies replica-tier faults,
        then pumps the engine (SimClock mode) or just observes its
        progress (threaded mode). Returns retired-token count (0 while
        crashed or inside an injected hang window)."""
        if self.crashed:
            return 0
        plan = (self._fault_plan if self._fault_plan is not None
                else global_plan())
        if plan is not None:
            verdict = plan.maybe_replica_fault(self.index)
            if verdict is not None:
                kind, arg = verdict
                if kind == "crash":
                    self.crash()
                    return 0
                if kind == "hang":
                    self._hang_until = self.clock.now() + float(arg)
                elif kind == "slow" and not isinstance(self.clock, SimClock):
                    time.sleep(float(arg) / 1e3)
        if self._hang_until is not None:
            if self.clock.now() < self._hang_until:
                # frozen forward: no engine pump, no progress — exactly
                # what the watchdog is built to notice
                return 0
            self._hang_until = None
        if getattr(self.engine, "_thread", None) is not None:
            self.observe_progress(self.clock.now())
            return 0
        n = self.engine.pump()
        self.observe_progress(self.clock.now())
        return n


# ---------------------------------------------------------------------------
# per-stream state the router owns across replica deaths


class RouterHandle:
    """Fleet-level streaming view + completion future.

    Mirrors GenerationHandle's surface (`tokens_so_far`, `result`,
    `ttft_ms`, `rid`) but survives the replica it is decoding on: the
    router re-attaches it across failovers, stitching tokens harvested
    from dead replicas (`_prefix`) ahead of the live attachment's
    stream. The future resolves with the full np.int32 array — by greedy
    determinism, identical to an uninterrupted single-engine run."""

    def __init__(self, prompt: np.ndarray, max_new_tokens: int,
                 eos_token_id: Optional[int], slo: str, tenant: str,
                 rid: str, seq: int, deadline_abs: Optional[float],
                 sampling: Optional[SamplingParams] = None,
                 logprobs: bool = False,
                 adapter: Optional[str] = None):
        self.prompt = prompt
        self.max_new_tokens = max_new_tokens
        self.eos_token_id = eos_token_id
        self.slo = slo
        self.tenant = tenant
        self.rid = rid
        self.sampling = sampling            # per-request seeded sampling
        #                                     params (ISSUE 18); carried
        #                                     across failovers unchanged
        self.adapter = adapter              # LoRA adapter id (ISSUE 20);
        #                                     carried across failovers so
        #                                     the survivor decodes through
        #                                     the same bank row
        self.future: Future = Future()
        self.ttft_ms: Optional[float] = None
        self.failovers = 0                  # replica deaths survived
        self.weight_version: Optional[str] = None   # pinned at placement;
        #                                     FROZEN once any token was
        #                                     emitted — a stream is never
        #                                     stitched across two weight
        #                                     sets (ISSUE 16)
        self.want_logprobs = bool(logprobs)   # per-token logprob surface
        self._seq = seq                     # router submit order
        self._deadline_abs = deadline_abs
        self._prefix = np.empty(0, np.int32)   # harvested off dead replicas
        self._logprobs: List[Optional[float]] = []   # stitched with _prefix
        self._inner = None                  # live GenerationHandle or None
        self._replica: Optional[InProcessReplica] = None
        # prefill→decode disaggregation (ISSUE 19): the exported KV row +
        # sampling lane ride the handle until the stream completes, so a
        # decode replica crashing right after a handoff re-places the
        # same payload instead of re-prefilling. _resume_args drops them
        # once stale (tokens emitted since the export).
        self._staged_kv: Optional[dict] = None
        self._staged_lane: Optional[dict] = None
        self._handoff_src: Optional[str] = None   # set export→first place
        self._handoff_t0: Optional[float] = None

    def tokens_so_far(self) -> List[int]:
        live = self._inner.tokens_so_far() if self._inner is not None else []
        return [int(t) for t in self._prefix] + list(live)

    def logprobs_so_far(self) -> List[Optional[float]]:
        """Per-emitted-token logprobs, stitched across failovers and
        handoffs exactly like `tokens_so_far` (index-aligned with it).
        All-None unless the stream was submitted with logprobs=True."""
        live = (self._inner.logprobs_so_far()
                if self._inner is not None else [])
        return list(self._logprobs) + list(live)

    def result(self, timeout: Optional[float] = None) -> np.ndarray:
        return self.future.result(timeout)

    # -- router internals --

    def _absorb_inner(self):
        """Pull everything the current attachment emitted into the
        stitched prefix and detach. Safe on a dead replica: tokens
        stream into the handle as decode iterations retire, so the list
        is exactly what was produced before the failure froze it."""
        if self._inner is None:
            return
        toks = np.asarray(self._inner.tokens_so_far(),
                          np.int32).reshape(-1)
        if toks.size:
            self._prefix = np.concatenate([self._prefix, toks])
            self._logprobs.extend(
                self._inner.logprobs_so_far()[:toks.size])
        if self.ttft_ms is None:
            self.ttft_ms = self._inner.ttft_ms
        self._inner = None
        self._replica = None

    def _finished(self) -> bool:
        if self._prefix.size >= self.max_new_tokens:
            return True
        return (self.eos_token_id is not None and self._prefix.size > 0
                and int(self._prefix[-1]) == self.eos_token_id)

    def _resume_args(self, now: float) -> dict:
        """submit() kwargs that continue this stream on a survivor:
        re-prefill prompt+emitted, decode only the remaining budget.

        Speculative decoding (ISSUE 17): the engine only ever surfaces
        VERIFIED tokens on its handles — unverified draft tokens live in
        the dead replica's draft pool, never in tokens_so_far() — so a
        stream killed mid-draft-window resumes from exactly the accepted
        stream here, and the survivor (spec-enabled or not) re-enters
        draft mode from a clean committed length. Greedy determinism then
        keeps the resumed stream bit-identical to an uninterrupted one.

        Seeded sampling (ISSUE 18): determinism across failover now also
        requires restoring the RNG-lane counter — `sample_offset` tells
        the survivor that `_prefix.size` stream tokens were already
        drawn, so its first emission uses stream index `_prefix.size`
        of lane `(seed, ·)`, exactly the draw the dead replica would
        have made next. The engine re-derives the grammar DFA state by
        walking the resumed prompt's emitted tail host-side, so a
        constrained stream resumes mid-object without ever re-emitting
        or skipping a token."""
        prompt = (np.concatenate([self.prompt, self._prefix])
                  if self._prefix.size else self.prompt)
        deadline_ms = None
        if self._deadline_abs is not None:
            deadline_ms = max(1.0, (self._deadline_abs - now) * 1e3)
        args = dict(prompt=prompt,
                    max_new_tokens=self.max_new_tokens - self._prefix.size,
                    eos_token_id=self.eos_token_id,
                    deadline_ms=deadline_ms, slo=self.slo,
                    tenant=self.tenant, rid=self.rid,
                    sampling=self.sampling,
                    sample_offset=int(self._prefix.size),
                    logprobs=self.want_logprobs,
                    adapter=self.adapter)
        # disaggregation (ISSUE 19): attach the staged KV row when it
        # still covers exactly prompt'.size - 1 tokens (the one-token-
        # prefill invariant); anything else means tokens were emitted
        # since the export and the ordinary re-prefill path takes over.
        if self._staged_kv is not None:
            if int(self._staged_kv["length"]) == int(prompt.size) - 1:
                args["kv_row"] = self._staged_kv
            else:
                self._staged_kv = None
                self._staged_lane = None
        lane = self._staged_lane
        if (lane is not None
                and int(lane.get("next_index", -1)) == self._prefix.size):
            args["lane"] = lane
        return args


class _ReplicaState:
    """Router-side supervision record for one replica."""
    __slots__ = ("failures", "quarantined", "next_probe", "backoff_level")

    def __init__(self):
        self.failures = 0          # consecutive watchdog strikes
        self.quarantined = False
        self.next_probe = 0.0      # clock instant of next re-admission try
        self.backoff_level = 0


@dataclass
class RouterConfig:
    hung_timeout_s: float = 30.0   # no dispatch progress with work queued
    quarantine_threshold: int = 3  # consecutive watchdog strikes to trip
    backoff_base_s: float = 1.0    # first re-admission probe delay
    backoff_max_s: float = 60.0    # backoff ladder cap
    retry_after_s: float = 1.0     # backpressure hint on router-level sheds
    poll_interval_s: float = 0.005   # supervision loop period (live mode)
    degraded_shed_fraction: float = 0.5   # quarantined fraction at which
    #                                       best_effort sheds at the door

    def __post_init__(self):
        if self.hung_timeout_s <= 0:
            raise ValueError(
                f"hung_timeout_s must be > 0, got {self.hung_timeout_s}")
        if self.quarantine_threshold < 1:
            raise ValueError(f"quarantine_threshold must be >= 1, got "
                             f"{self.quarantine_threshold}")
        if self.backoff_base_s <= 0 or self.backoff_max_s < self.backoff_base_s:
            raise ValueError("need 0 < backoff_base_s <= backoff_max_s")
        if not (0.0 < self.degraded_shed_fraction <= 1.0):
            raise ValueError(f"degraded_shed_fraction must be in (0, 1], got "
                             f"{self.degraded_shed_fraction}")


class ReplicaRouter:
    """Front-of-fleet router: prefix/load-aware placement, breaker-aware
    supervision with quarantine + backoff re-admission, and failover
    re-prefill that never drops an admitted stream.

    Threading mirrors the engine: under SimClock the harness advances
    the clock and calls `pump()`; under MonotonicClock `start()` runs
    the same pump from a supervision thread while each replica engine
    runs its own scheduler thread."""

    def __init__(self, replicas: List[InProcessReplica],
                 config: Optional[RouterConfig] = None,
                 metrics: Optional[RouterMetrics] = None):
        if not replicas:
            raise ValueError("ReplicaRouter needs at least one replica")
        names = [r.name for r in replicas]
        if len(set(names)) != len(names):
            raise ValueError(f"replica names must be unique, got {names}")
        # distinct MonotonicClock instances all read the same wall; only
        # scripted SimClocks must literally be the same object
        if any(isinstance(r.clock, SimClock) for r in replicas) and \
                len({id(r.clock) for r in replicas}) != 1:
            raise ValueError(
                "SimClock replicas must share one clock instance")
        self.replicas = replicas
        self.clock: Clock = replicas[0].clock
        self.config = config or RouterConfig()
        self.metrics = metrics or RouterMetrics()
        self._lock = threading.RLock()
        self._state: Dict[str, _ReplicaState] = {
            r.name: _ReplicaState() for r in replicas}
        self._inflight: Dict[str, RouterHandle] = {}   # rid -> handle
        self._pending: List[RouterHandle] = []   # awaiting (re)placement
        self._seq = 0
        self._thread: Optional[threading.Thread] = None
        self._stop_event = threading.Event()
        self._stopped = False

    # ---- admission ----

    def submit(self, prompt, max_new_tokens: Optional[int] = None,
               eos_token_id: Optional[int] = None,
               deadline_ms: Optional[float] = None,
               slo: Optional[str] = None,
               tenant: Optional[str] = None,
               rid: Optional[str] = None,
               sampling: Optional[SamplingParams] = None,
               logprobs: bool = False,
               adapter: Optional[str] = None) -> RouterHandle:
        """Admit one prompt to the fleet. Raises RejectedError with
        reason `fleet_unavailable` when every replica is quarantined,
        `shed` when the fleet is degraded past the shed fraction and the
        request is best_effort, or the chosen replica's own reject when
        every healthy replica refuses admission. `sampling` (ISSUE 18)
        rides the handle across failovers: re-placements resubmit the
        same params plus the emitted-token count as `sample_offset`, so
        a seeded stream stays bit-identical across replica deaths.
        `logprobs` (ISSUE 19) surfaces the model's per-token logprob for
        every emitted token on `logprobs_so_far()`, stitched across
        failovers and handoffs like the tokens themselves. `adapter`
        (ISSUE 20) decodes the stream through that LoRA bank row on
        whichever replica accepts it — the id rides the handle, so a
        failover resubmits it and the survivor restores the adapter."""
        if sampling is not None:
            sampling.validate()
        ecfg = self.replicas[0].engine.config
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size < 1:
            raise ValueError("prompt must contain at least one token")
        mnt = (ecfg.max_new_tokens if max_new_tokens is None
               else int(max_new_tokens))
        if mnt < 1:
            raise ValueError(f"max_new_tokens must be >= 1, got {mnt}")
        slo = ecfg.default_slo if slo is None else slo
        if slo not in SLO_CLASSES:
            raise ValueError(f"slo must be one of {SLO_CLASSES}, got {slo!r}")
        tenant = ecfg.default_tenant if tenant is None else tenant
        rid = rid or new_request_id()
        eos = ecfg.eos_token_id if eos_token_id is None else eos_token_id
        now = self.clock.now()
        deadline_abs = None if deadline_ms is None else now + deadline_ms / 1e3
        with self._lock:
            if self._stopped:
                raise RejectedError("router is stopped; request rejected",
                                    reason="draining")
            self.metrics.on_submit()
            down = sum(1 for r in self.replicas
                       if self._state[r.name].quarantined
                       or r.health() != "ok")
            if down == len(self.replicas):
                self.metrics.on_reject("fleet_unavailable")
                flight_recorder().record("router_reject", rid=rid,
                                         reason="fleet_unavailable")
                raise RejectedError(
                    "every replica is quarantined or unhealthy; fleet "
                    "unavailable", reason="fleet_unavailable",
                    retry_after_s=self.config.retry_after_s)
            if (down / len(self.replicas)
                    >= self.config.degraded_shed_fraction
                    and slo == "best_effort"):
                # graceful degradation: with half the fleet gone the
                # survivors' headroom belongs to interactive/batch SLOs —
                # shed best_effort at the router's own door
                self.metrics.on_reject("shed")
                flight_recorder().record("router_reject", rid=rid,
                                         reason="shed", degraded=down)
                raise RejectedError(
                    f"fleet degraded ({down}/{len(self.replicas)} replicas "
                    "down); best_effort shed at router", reason="shed",
                    retry_after_s=self.config.retry_after_s)
            handle = RouterHandle(prompt, mnt, eos, slo, tenant, rid,
                                  self._seq, deadline_abs,
                                  sampling=sampling, logprobs=logprobs,
                                  adapter=adapter)
            self._seq += 1
            replica, last_exc = self._place_locked(handle, now)
            if replica is None:
                reason = getattr(last_exc, "reason", "fleet_unavailable") \
                    if last_exc is not None else "fleet_unavailable"
                self.metrics.on_reject(reason)
                flight_recorder().record("router_reject", rid=rid,
                                         reason=reason)
                if last_exc is not None:
                    raise last_exc
                raise RejectedError(
                    "no replica accepted the request",
                    reason="fleet_unavailable",
                    retry_after_s=self.config.retry_after_s)
            self._inflight[rid] = handle
        return handle

    def generate(self, prompt, max_new_tokens: Optional[int] = None,
                 eos_token_id: Optional[int] = None,
                 deadline_ms: Optional[float] = None,
                 timeout: Optional[float] = None,
                 slo: Optional[str] = None,
                 tenant: Optional[str] = None,
                 sampling: Optional[SamplingParams] = None) -> np.ndarray:
        """Synchronous convenience: submit + wait (live mode only —
        under SimClock nothing pumps while you block)."""
        return self.submit(prompt, max_new_tokens=max_new_tokens,
                           eos_token_id=eos_token_id,
                           deadline_ms=deadline_ms, slo=slo,
                           tenant=tenant,
                           sampling=sampling).result(timeout)

    # ---- routing policy ----

    def _candidates_locked(self) -> List[InProcessReplica]:
        return [r for r in self.replicas
                if not self._state[r.name].quarantined
                and r.health() == "ok"]

    def _place_locked(self, handle: RouterHandle, now: float
                      ) -> Tuple[Optional[InProcessReplica],
                                 Optional[Exception]]:
        """Route + admit: candidates ranked by longest block-aligned
        prefix match, then lightest in-flight token load, then index.
        Tries the ranked list in order so one replica's queue_full does
        not fail an admission another replica could take.

        Version-skew safety (ISSUE 16): once a stream has emitted tokens
        its pinned `weight_version` is frozen, and only same-version
        replicas qualify — resuming the emitted prefix under different
        weights would stitch two weight sets into one stream. A stream
        with no tokens yet may re-pin (there is nothing to stitch).

        Role preference (ISSUE 19): a stream carrying staged handoff KV
        prefers decode > mixed > prefill; a stream that must (re)prefill
        prefers prefill > mixed > decode. The preference ranks AHEAD of
        the prefix probe but never filters: every healthy same-version
        replica stays a candidate, so an all-mixed fleet ranks exactly
        as before and a role-specialized fleet still places everything
        somewhere rather than dropping a stream.
        Returns the accepting replica, or (None, last_reject)."""
        args = handle._resume_args(now)
        pinned = (handle.weight_version
                  if handle._prefix.size > 0 else None)
        staged = "kv_row" in args

        def role_rank(r: InProcessReplica) -> int:
            if r.role == "mixed":
                return 1
            if staged:
                return 0 if r.role == "decode" else 2
            return 0 if r.role == "prefill" else 2

        ranked = sorted(
            ((role_rank(r),
              -(r.prefix_probe(args["prompt"], tenant=handle.tenant,
                               adapter=handle.adapter)),
              r.inflight_tokens(), r.index, r)
             for r in self._candidates_locked()
             if pinned is None or r.weight_version == pinned),
            key=lambda t: t[:4])
        last_exc: Optional[Exception] = None
        for _rank, neg_match, _, _, r in ranked:
            try:
                inner = r.submit(**args)
            except RejectedError as e:
                last_exc = e
                continue
            handle._inner = inner
            handle._replica = r
            handle.weight_version = r.weight_version
            self.metrics.on_route(r.name, prefix_hit=neg_match < 0)
            if handle._handoff_src is not None:
                src = handle._handoff_src
                handle._handoff_src = None
                if staged:
                    t0 = (handle._handoff_t0
                          if handle._handoff_t0 is not None else now)
                    ms = max(0.0, (now - t0) * 1e3)
                    self.metrics.on_handoff(src, r.name, ms)
                    flight_recorder().record(
                        "router_handoff", rid=handle.rid, src=src,
                        dst=r.name, ms=round(ms, 3),
                        kv_tokens=int(args["kv_row"]["length"]))
                else:
                    # staged KV went stale before a destination accepted
                    # the stream: it re-prefilled instead (still bit-
                    # identical, just not a KV handoff)
                    self.metrics.on_handoff_failed()
                    flight_recorder().record(
                        "router_handoff", rid=handle.rid, src=src,
                        dst=r.name, fallback="re_prefill")
            return r, None
        return None, last_exc

    # ---- supervision ----

    def _quarantine_locked(self, r: InProcessReplica, st: _ReplicaState,
                           reason: str, now: float):
        st.quarantined = True
        st.failures = 0
        st.backoff_level = 0
        st.next_probe = now + self.config.backoff_base_s
        self.metrics.on_quarantine(r.name)
        flight_recorder().record("router_quarantine", replica=r.name,
                                 reason=reason,
                                 next_probe_s=round(st.next_probe - now, 3))
        _log.warning("router: quarantined %s (%s)", r.name, reason)
        self._failover_locked(r, now, reason)

    def _failover_locked(self, r: InProcessReplica, now: float, reason: str):
        """Zero dropped streams: every in-flight stream the dead replica
        owned is harvested (emitted tokens -> stitched prefix) and
        queued for re-prefill on a survivor, in submit order."""
        victims = sorted(
            (h for h in self._inflight.values() if h._replica is r),
            key=lambda h: h._seq)
        resumed = []
        for h in victims:
            h._absorb_inner()
            h.failovers += 1
            if h._finished():
                # the dead replica had already emitted the full stream;
                # nothing to resume — resolve from the harvest
                h.future.set_result(h._prefix.copy())
                self.metrics.on_complete()
                del self._inflight[h.rid]
            else:
                self._pending.append(h)
                resumed.append(h)
        for h in resumed:
            flight_recorder().record(
                "router_failover", replica=r.name, rid=h.rid,
                reason=reason, emitted=int(h._prefix.size),
                remaining=int(h.max_new_tokens - h._prefix.size))
        if victims:
            self.metrics.on_failover(r.name, len(resumed))
            flight_recorder().try_dump(reason=f"router_failover:{r.name}")

    def _supervise_locked(self, now: float):
        cfg = self.config
        for r in self.replicas:
            st = self._state[r.name]
            r.observe_progress(now)
            if st.quarantined:
                if now < st.next_probe:
                    continue
                # re-admission probe: health must read ok, and (SimClock
                # mode) one probe pump must show actual forward progress
                # — a hung replica reads "ok" the whole time it is
                # frozen, and re-admitting it would just restart the
                # watchdog ladder and flap traffic
                ok = r.health() == "ok"
                if ok and getattr(r.engine, "_thread", None) is None:
                    before = r.engine._dispatch_idx
                    r.pump()
                    ok = (r.health() == "ok"
                          and (r.engine._dispatch_idx != before
                               or not r.engine.has_work()))
                if ok:
                    st.quarantined = False
                    st.failures = 0
                    st.backoff_level = 0
                    r.last_progress = now   # a fresh watchdog epoch
                    self.metrics.on_readmit(r.name)
                    flight_recorder().record("router_readmit",
                                             replica=r.name)
                    _log.info("router: re-admitted %s", r.name)
                else:
                    st.backoff_level += 1
                    delay = min(cfg.backoff_base_s * (2 ** st.backoff_level),
                                cfg.backoff_max_s)
                    st.next_probe = now + delay
                continue
            h = r.health()
            if h != "ok":
                if (r.deploy_state != "serving" and not r.crashed
                        and not r.engine.broken
                        and not r.engine.draining):
                    # controller-owned deploy lifecycle, NOT a fault:
                    # placement already skips the replica; its streams
                    # either moved at drain time or are finishing in
                    # place. Quarantining would stop pumping it and
                    # freeze those streams mid-decode.
                    st.failures = 0
                    continue
                self._quarantine_locked(r, st, reason=h, now=now)
                continue
            hung = (r.engine.has_work()
                    and (now - r.last_progress) > cfg.hung_timeout_s)
            if hung:
                st.failures += 1
                if st.failures >= cfg.quarantine_threshold:
                    self._quarantine_locked(r, st, reason="hung", now=now)
            else:
                st.failures = 0

    def _place_pending_locked(self, now: float):
        still: List[RouterHandle] = []
        for h in self._pending:
            if h._deadline_abs is not None and now >= h._deadline_abs:
                h.future.set_exception(DeadlineExceededError(
                    f"request {h.rid} deadline passed while awaiting "
                    "failover placement"))
                self.metrics.on_fail()
                self._inflight.pop(h.rid, None)
                continue
            replica, _ = self._place_locked(h, now)
            if replica is None:
                still.append(h)   # zero dropped: keep trying every pump
        self._pending = still

    def _handoff_locked(self, now: float):
        """Prefill/decode disaggregation (ISSUE 19): any stream that has
        finished prefill on a prefill-role replica (its handle shows
        emitted tokens but the stream is still live) exports its KV row
        + sampling lane in one atomic engine call, absorbs the emitted
        tokens into the stitched prefix, and is re-placed decode-first
        with the staged payload. A stream that cannot place right now
        goes to `_pending` with the payload intact — zero dropped
        streams, the handoff just completes on a later pump."""
        if all(r.role != "prefill" for r in self.replicas):
            return
        for h in list(self._inflight.values()):
            r = h._replica
            if (r is None or r.role != "prefill" or r.crashed
                    or h._inner is None or h._inner.future.done()):
                continue
            try:
                payload = r.export_stream(h.rid)
            except (ValueError, RuntimeError):
                continue   # mid-prefill (or replica just died): next pump
            h._handoff_src = r.name
            h._handoff_t0 = now
            h._absorb_inner()
            h._staged_kv = payload["kv_row"]
            h._staged_lane = payload["lane"]
            if h._finished():
                # prefill emitted everything the budget allowed (e.g.
                # max_new_tokens == 1): nothing to hand off
                h._handoff_src = None
                h.future.set_result(h._prefix.copy())
                self.metrics.on_complete()
                del self._inflight[h.rid]
                continue
            replica, _ = self._place_locked(h, now)
            if replica is None:
                self._pending.append(h)

    def _harvest_locked(self, now: float):
        for rid, h in list(self._inflight.items()):
            inner = h._inner
            if inner is None:
                continue
            if h.ttft_ms is None and inner.ttft_ms is not None:
                h.ttft_ms = inner.ttft_ms
            if not inner.future.done():
                continue
            exc = inner.future.exception()
            if exc is not None:
                r = h._replica
                if r is not None and (r.crashed or r.health() != "ok"):
                    # replica-scoped failure (breaker trip flushed its
                    # actives, crash, drain): supervision will quarantine
                    # and fail the stream over — not a stream error
                    continue
                h.future.set_exception(exc)
                if isinstance(exc, RejectedError):
                    self.metrics.on_reject(getattr(exc, "reason", "rejected"))
                else:
                    self.metrics.on_fail()
                del self._inflight[rid]
            else:
                toks = np.asarray(inner.future.result(),
                                  np.int32).reshape(-1)
                full = (np.concatenate([h._prefix, toks])
                        if h._prefix.size else toks)
                h.future.set_result(full)
                self.metrics.on_complete()
                del self._inflight[rid]

    def _update_gauges_locked(self):
        for r in self.replicas:
            st = self._state[r.name]
            state = "quarantined" if st.quarantined else r.health()
            inflight = 0 if r.crashed else r.engine.inflight_tokens()
            self.metrics.set_replica(r.name, state, inflight,
                                     weight_version=r.weight_version,
                                     role=r.role)

    # ---- rolling-deploy lifecycle (ISSUE 16) ----

    def _replica_by_name(self, name: str) -> InProcessReplica:
        for r in self.replicas:
            if r.name == name:
                return r
        raise ValueError(f"no replica named {name!r} "
                         f"(fleet: {[r.name for r in self.replicas]})")

    def drain_replica(self, name: str) -> int:
        """Deploy-drain one replica: exclude it from placement, then move
        its in-flight streams — failover re-prefill, zero dropped — onto
        survivors IF a same-version healthy destination exists. When the
        draining replica is the last of its version (the final replica of
        a rollout), its streams are deliberately left attached to finish
        in place: the replica keeps being pumped while placement-
        excluded, which is the only way to honor both zero-drop and the
        never-stitch-versions invariant at once. Returns streams moved;
        the DeploymentController evacuates the engine's orphaned rows
        iff > 0."""
        with self._lock:
            r = self._replica_by_name(name)
            r.drain()
            dest = [c for c in self._candidates_locked()
                    if c.weight_version == r.weight_version]
            in_place = sum(1 for h in self._inflight.values()
                           if h._replica is r)
            moved = 0
            if dest:
                victims = sorted(
                    (h for h in self._inflight.values()
                     if h._replica is r),
                    key=lambda h: h._seq)
                for h in victims:
                    h._absorb_inner()
                    h.failovers += 1
                    if h._finished():
                        h.future.set_result(h._prefix.copy())
                        self.metrics.on_complete()
                        del self._inflight[h.rid]
                    else:
                        self._pending.append(h)
                        moved += 1
                        flight_recorder().record(
                            "router_failover", replica=r.name, rid=h.rid,
                            reason="deploy_drain",
                            emitted=int(h._prefix.size),
                            remaining=int(h.max_new_tokens
                                          - h._prefix.size))
                in_place = 0
                if moved:
                    self.metrics.on_failover(r.name, moved)
            flight_recorder().record(
                "deploy_drain", replica=r.name, moved=moved,
                finish_in_place=in_place, version=r.weight_version)
            return moved

    def readmit_replica(self, name: str):
        """Return a replica from the deploy lifecycle to placement, on a
        fresh watchdog epoch (swap + canary time must not count as hung
        time) and cleared of any quarantine."""
        with self._lock:
            r = self._replica_by_name(name)
            st = self._state[r.name]
            r.readmit()
            st.failures = 0
            st.quarantined = False
            st.backoff_level = 0
            r.last_progress = self.clock.now()
            self.metrics.on_readmit(r.name)
            flight_recorder().record("router_readmit", replica=r.name,
                                     deploy=True,
                                     version=r.weight_version)

    def retire_version(self, version: str) -> int:
        """Rollback cleanup: a pending stream pinned to `version` that
        has already emitted tokens can never resume once the fleet rolled
        back — resuming it under the restored weights would stitch two
        weight sets. Fail those few streams with a typed, retryable
        error (the client re-submits and gets a clean run on the restored
        version); pinned-but-empty streams just lose their pin and place
        normally. Returns streams retired."""
        with self._lock:
            kept: List[RouterHandle] = []
            retired = 0
            for h in self._pending:
                if h.weight_version == version and h._prefix.size > 0:
                    h.future.set_exception(RejectedError(
                        f"stream {h.rid} is pinned to retired weight "
                        f"version {version}; resubmit to run on the "
                        "restored version", reason="version_retired",
                        retry_after_s=self.config.retry_after_s))
                    self.metrics.on_reject("version_retired")
                    self._inflight.pop(h.rid, None)
                    retired += 1
                    flight_recorder().record(
                        "deploy_retire_stream", rid=h.rid,
                        version=version, emitted=int(h._prefix.size))
                else:
                    if h.weight_version == version:
                        h.weight_version = None
                    kept.append(h)
            self._pending = kept
            return retired

    # ---- the pump ----

    def pump(self) -> int:
        """One router step: supervise health, re-place failed-over and
        pending streams, pump live replicas, harvest completions.
        Returns tokens retired across the fleet this step."""
        now = self.clock.now()
        with self._lock:
            self._supervise_locked(now)
            self._place_pending_locked(now)
            live = [r for r in self.replicas
                    if not self._state[r.name].quarantined]
        # engine pumps run OUTSIDE the router lock: replicas decode
        # independently, and a slow forward on one must not block
        # admissions or another replica's harvest
        n = 0
        for r in live:
            n += r.pump()
        with self._lock:
            self._handoff_locked(self.clock.now())
            self._harvest_locked(self.clock.now())
            self._update_gauges_locked()
        return n

    def has_work(self) -> bool:
        with self._lock:
            return bool(self._inflight) or bool(self._pending)

    def healthz(self) -> dict:
        """Fleet health summary (`RouterServer` serves this verbatim)."""
        with self._lock:
            states = {}
            for r in self.replicas:
                st = self._state[r.name]
                states[r.name] = "quarantined" if st.quarantined \
                    else r.health()
            down = sum(1 for s in states.values() if s != "ok")
            status = ("unavailable" if down == len(self.replicas)
                      else "degraded" if down else "ok")
            out = {"status": status, "replicas": states,
                   "quarantined": sorted(
                       n for n, st in self._state.items()
                       if st.quarantined),
                   "weight_versions": {
                       r.name: r.weight_version for r in self.replicas}}
            # speculative decoding (ISSUE 17): per-replica window accept
            # rate (None: crashed, or no windows yet) — the fleet-level
            # view the accept-rate runbook in docs/serving.md watches.
            # Only advertised when some replica actually carries a draft.
            # disaggregation (ISSUE 19): advertise roles only when the
            # fleet is actually specialized (all-mixed is the default
            # topology and needs no extra healthz surface)
            if any(r.role != "mixed" for r in self.replicas):
                out["roles"] = {r.name: r.role for r in self.replicas}
            if any(getattr(r.engine, "draft_model", None) is not None
                   for r in self.replicas):
                out["spec_accept_rates"] = {
                    r.name: (None if r.crashed else
                             r.engine.metrics.snapshot()
                             .get("spec_accept_rate"))
                    for r in self.replicas}
            return out

    # ---- lifecycle (live mode) ----

    def start(self) -> "ReplicaRouter":
        if isinstance(self.clock, SimClock):
            raise RuntimeError(
                "ReplicaRouter.start() requires a real clock; under "
                "SimClock the harness drives pump() itself")
        for r in self.replicas:
            if getattr(r.engine, "_thread", None) is None:
                r.engine.start()
        self._stop_event.clear()
        self._thread = threading.Thread(
            target=self._supervise_main, name="pdtpu-router", daemon=True)
        self._thread.start()
        return self

    def _supervise_main(self):
        while not self._stop_event.is_set():
            try:
                self.pump()
            except Exception:
                _log.exception("router: pump failed")
            self._stop_event.wait(self.config.poll_interval_s)

    def stop(self, drain: bool = True, timeout: Optional[float] = None):
        """Stop the fleet: drain every live replica (finishing admitted
        streams), run a final harvest, and fail anything still awaiting
        placement — explicitly, never silently."""
        with self._lock:
            if self._stopped:
                return
            self._stopped = True
        if self._thread is not None:
            self._stop_event.set()
            self._thread.join(timeout=30.0)
            self._thread = None
        for r in self.replicas:
            if not r.crashed:
                try:
                    r.engine.stop(drain=drain, timeout=timeout)
                except Exception:
                    _log.exception("router: stopping %s failed", r.name)
        with self._lock:
            self._harvest_locked(self.clock.now())
            leftovers = list(self._pending)
            self._pending = []
            for h in leftovers:
                self._inflight.pop(h.rid, None)
            for rid, h in list(self._inflight.items()):
                h._absorb_inner()
                if h._finished():
                    h.future.set_result(h._prefix.copy())
                    self.metrics.on_complete()
                else:
                    leftovers.append(h)
                del self._inflight[rid]
            for h in leftovers:
                if not h.future.done():
                    h.future.set_exception(RejectedError(
                        f"router stopped before {h.rid} could be resumed",
                        reason="draining"))
                    self.metrics.on_fail()
            self._update_gauges_locked()


# ---------------------------------------------------------------------------
# HTTP front end


# the engine's retryable set plus the router's own back-off-and-retry words
_ROUTER_RETRYABLE = frozenset({"queue_full", "token_budget", "shed",
                               "tenant_quota", "fleet_unavailable",
                               "replica_down", "version_retired"})

_TENANT_ID_RE = re.compile(r"^[A-Za-z0-9][A-Za-z0-9._-]{0,63}$")


class RouterServer:
    """HTTP face of the fleet: the same /generate contract as
    ServingServer (429 + Retry-After on retryable rejects, 503 on
    terminal ones, 504 on deadline), fleet-level /healthz, and
    pdtpu_router_* metrics (per-replica health, quarantines, failovers,
    prefix-affinity hit rate) on /metrics."""

    def __init__(self, router: ReplicaRouter, host: str = "127.0.0.1",
                 port: int = 0, request_timeout_s: float = 60.0):
        self.router = router
        self.request_timeout_s = float(request_timeout_s)
        self._deploy_controller = None   # built on first POST /deploy
        self._deploy_lock = threading.Lock()
        outer = self

        class _Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):
                pass

            def _reply(self, code: int, body: bytes,
                       ctype: str = "application/json", headers=None):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def _reply_json(self, code: int, obj, headers=None):
                self._reply(code, json.dumps(obj).encode(), headers=headers)

            def do_GET(self):
                if self.path == "/healthz":
                    health = outer.router.healthz()
                    code = 503 if health["status"] == "unavailable" else 200
                    self._reply_json(code, health)
                elif self.path == "/metrics":
                    text = outer.router.metrics.render()
                    ctrl = outer._deploy_controller
                    if ctrl is not None:
                        # pdtpu_deploy_* families ride the same scrape
                        text += ctrl.metrics.render()
                    self._reply(200, text.encode(),
                                ctype="text/plain; version=0.0.4")
                elif self.path == "/debug/flightrecorder":
                    self._reply_json(200, flight_recorder().snapshot())
                elif self.path == "/debug/deploy":
                    ctrl = outer._deploy_controller
                    self._reply_json(
                        200, ctrl.status() if ctrl is not None
                        else {"state": "idle", "history": []})
                else:
                    self._reply_json(404, {"error": "not found"})

            def do_POST(self):
                if self.path == "/deploy":
                    self._deploy()
                    return
                if self.path != "/generate":
                    self._reply_json(404, {"error": "not found"})
                    return
                from ..distributed.fleet.utils.http_server import \
                    read_request_body
                body = read_request_body(self)
                if body is None:
                    return
                try:
                    payload = json.loads(body or b"{}")
                    prompt = np.asarray(payload["input_ids"],
                                        dtype=np.int32).reshape(-1)
                    if prompt.size < 1:
                        raise ValueError("input_ids must be non-empty")
                    slo = payload.get("slo")
                    if slo is not None and slo not in SLO_CLASSES:
                        raise ValueError(
                            f"slo must be one of {list(SLO_CLASSES)}, "
                            f"got {slo!r}")
                    tenant = self.headers.get("X-Tenant-Id")
                    if tenant is not None \
                            and not _TENANT_ID_RE.match(tenant):
                        raise ValueError(
                            "malformed X-Tenant-Id (want 1-64 chars of "
                            "[A-Za-z0-9._-], starting alphanumeric), got "
                            f"{tenant!r}")
                    # sampling fields (ISSUE 18): temperature / top_k /
                    # top_p / seed / grammar; absent → greedy (None)
                    sampling = SamplingParams.from_payload(payload)
                    if sampling is not None:
                        sampling.validate()
                    # per-token logprobs (ISSUE 19): strictly boolean —
                    # a truthy 1 / "yes" is a malformed request, not a
                    # lenient opt-in
                    want_lp = payload.get("logprobs", False)
                    if not isinstance(want_lp, bool):
                        raise ValueError(
                            f"logprobs must be a boolean, got "
                            f"{want_lp!r}")
                except (ValueError, KeyError, TypeError) as e:
                    self._reply_json(400, {"error": f"bad request: {e}"})
                    return
                rid = (ingest_traceparent(self.headers.get("traceparent"))
                       or new_request_id())
                try:
                    handle = outer.router.submit(
                        prompt,
                        max_new_tokens=payload.get("max_new_tokens"),
                        eos_token_id=payload.get("eos_token_id"),
                        deadline_ms=payload.get("deadline_ms"),
                        slo=slo, tenant=tenant, rid=rid,
                        sampling=sampling, logprobs=want_lp)
                    toks = handle.result(timeout=outer.request_timeout_s)
                except RejectedError as e:
                    reason = getattr(e, "reason", "rejected")
                    if reason in _ROUTER_RETRYABLE:
                        retry_s = getattr(e, "retry_after_s", None) or 1.0
                        self._reply_json(
                            429, {"error": str(e), "reason": reason},
                            headers={"Retry-After": f"{retry_s:g}"})
                    else:
                        self._reply_json(
                            503, {"error": str(e), "reason": reason})
                    return
                except DeadlineExceededError as e:
                    self._reply_json(504, {"error": str(e)})
                    return
                except Exception as e:  # model/decode failure
                    self._reply_json(
                        500, {"error": f"{type(e).__name__}: {e}"})
                    return
                resp = {
                    "tokens": np.asarray(toks).tolist(),
                    "ttft_ms": handle.ttft_ms,
                    "rid": rid,
                    "failovers": handle.failovers,
                }
                if want_lp:
                    resp["logprobs"] = handle.logprobs_so_far()
                self._reply_json(200, resp)

            def _deploy(self):
                """POST /deploy {"directory", "version", "wait"?}: start
                (or, with wait=true, run to completion) a rolling deploy
                of the certified weight set. 412 on uncertified weights,
                409 when a rollout is already in progress."""
                from ..distributed.fleet.utils.http_server import \
                    read_request_body
                body = read_request_body(self)
                if body is None:
                    return
                from ..checkpoint import (UncertifiedWeightsError,
                                          WeightSet)
                try:
                    payload = json.loads(body or b"{}")
                    ws = WeightSet(payload["directory"],
                                   payload["version"])
                    wait = bool(payload.get("wait", False))
                except (ValueError, KeyError, TypeError) as e:
                    self._reply_json(400, {"error": f"bad request: {e}"})
                    return
                try:
                    status = outer.deploy(ws, wait=wait)
                except UncertifiedWeightsError as e:
                    self._reply_json(412, {
                        "error": str(e),
                        "reason": getattr(e, "reason", "uncertified")})
                    return
                except RuntimeError as e:   # rollout already in progress
                    self._reply_json(409, {"error": str(e)})
                    return
                self._reply_json(
                    202 if status.get("state") in ("rolling",
                                                   "rolling_back")
                    else 200, status)

        _Handler.timeout = self.request_timeout_s + 30.0
        self._server = ThreadingHTTPServer((host, port), _Handler)
        self._server.daemon_threads = False
        self._server.block_on_close = True
        self.host, self.port = self._server.server_address[:2]
        self._thread: Optional[threading.Thread] = None

    def deploy(self, weightset, config=None, wait: bool = False) -> dict:
        """Roll `weightset` across the fleet. wait=False starts the
        rollout on the controller's background thread and returns the
        initial status; wait=True blocks until the rollout completes or
        rolls back and returns the final record. One controller instance
        is kept for the server's lifetime so /debug/deploy keeps
        history."""
        with self._deploy_lock:
            if self._deploy_controller is None:
                from .deploy import DeploymentController
                self._deploy_controller = DeploymentController(
                    self.router, config=config)
            ctrl = self._deploy_controller
        if wait:
            return ctrl.run(weightset)
        ctrl.spawn(weightset)
        return ctrl.status()

    def start(self) -> "RouterServer":
        self.router.start()
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            name="pdtpu-router-http", daemon=True)
        self._thread.start()
        return self

    def serve_forever(self):
        """Foreground serve (subprocess fixtures): SIGTERM drains the
        fleet, finishes every admitted stream, and exits 0."""
        import signal

        def _sigterm(signum, frame):
            threading.Thread(target=self.stop, daemon=True).start()

        signal.signal(signal.SIGTERM, _sigterm)
        signal.signal(signal.SIGINT, _sigterm)
        try:
            self._server.serve_forever()
        finally:
            self._server.server_close()

    def stop(self, drain: bool = True):
        self.router.stop(drain=drain)
        self._server.shutdown()
        if self._thread is not None:
            self._server.server_close()
            self._thread.join(timeout=30.0)
            self._thread = None
