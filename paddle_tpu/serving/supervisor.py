"""Engine supervision: hung-dispatch watchdog + circuit breaker (ISSUE 6).

The training side survives a hung or failing step because ResilientTrainer
wraps every step in a watchdog and an escalation ladder
(distributed/resilient.py). This module is the serving analogue: every
jitted prefill/decode/predict dispatch runs through
`EngineSupervisor.run()`, which

- converts any exception the dispatch raises into a typed
  `DispatchFailedError` (so engines route a *classified* failure to the
  implicated futures instead of a bare model exception),
- bounds the dispatch's wall time with a deadline thread when
  `dispatch_timeout_s` is set — a dispatch that never returns becomes a
  `DispatchHungError` after the budget, and the worker thread is
  abandoned (XLA offers no safe way to interrupt a device computation;
  the daemon thread dies with the process, which the circuit breaker is
  about to recycle anyway),
- keeps the engine-level circuit breaker: `record_failure()` counts
  CONSECUTIVE engine-level failures (a whole failure protocol exhausting
  its retries, not a single raised dispatch); at `breaker_threshold` the
  breaker opens — terminally, there is no half-open probe, because the
  contract is "flip /healthz to 503 and drain so the supervisor replaces
  the process". `absolve()` resets the count when a failure was
  attributed to one request (quarantine): a poisoned request must never
  take the engine down with it.

Determinism: injected hangs (`dispatch_hang@N` in utils/fault_injection)
arrive as `InjectedDispatchHang` and are mapped onto the same
`DispatchHungError` path without any real sleeping, so SimClock tests
prove the watchdog protocol threadlessly; the deadline thread itself is
exercised by wall-clock tests with a deliberately slow callable.
"""
from __future__ import annotations

import logging
import threading
from typing import Callable, Dict, Optional

from ..obs.flight_recorder import flight_recorder
from ..utils.fault_injection import InjectedDispatchHang

_log = logging.getLogger("paddle_tpu.serving")


class DispatchFailedError(RuntimeError):
    """A supervised dispatch raised. `reason` classifies it for metrics
    and HTTP mapping: "raise" (the dispatch errored), "hang" (watchdog
    fired), "poisoned" (failure attributed to one request after retries),
    "engine" (engine-level protocol exhaustion failed this request)."""

    def __init__(self, msg: str, reason: str = "raise"):
        super().__init__(msg)
        self.reason = reason


class DispatchHungError(DispatchFailedError):
    """The dispatch exceeded the watchdog budget and was abandoned."""

    def __init__(self, msg: str):
        super().__init__(msg, reason="hang")


class EngineSupervisor:
    """Per-engine dispatch watchdog + consecutive-failure circuit breaker.

    `run(fn, label)` executes one dispatch attempt under supervision.
    `record_failure()` / `record_success()` / `absolve()` drive the
    breaker at *protocol* granularity (the engine decides what counts as
    an engine-level failure). `on_trip` fires exactly once, from whichever
    thread tripped the breaker — wire it to a drain that runs on its OWN
    thread (the scheduler thread cannot join itself).
    """

    def __init__(self, dispatch_timeout_s: Optional[float] = None,
                 breaker_threshold: int = 3,
                 on_trip: Optional[Callable[[], None]] = None,
                 name: str = "engine"):
        if breaker_threshold < 1:
            raise ValueError(
                f"breaker_threshold must be >= 1, got {breaker_threshold}")
        self.dispatch_timeout_s = dispatch_timeout_s
        self.breaker_threshold = int(breaker_threshold)
        self.on_trip = on_trip
        self.name = name
        self._lock = threading.Lock()
        self._consecutive = 0
        self._open = False
        self.stats: Dict[str, int] = {
            "dispatch_failures": 0, "watchdog_fires": 0,
            "breaker_trips": 0, "quarantines": 0, "exempt_failures": 0,
        }

    # ---- supervised dispatch ----
    def run(self, fn: Callable, label: str = "dispatch",
            exempt: bool = False):
        """One supervised dispatch attempt. Returns fn()'s result or
        raises DispatchFailedError / DispatchHungError — never the raw
        model exception, and never blocks past the watchdog budget.

        `exempt=True` marks a best-effort auxiliary dispatch (ISSUE 17:
        speculative-draft proposals): its failures are still typed and
        recorded, but they land in the separate "exempt_failures" stat so
        health checks and breaker-adjacent accounting built on
        "dispatch_failures" never see an optimization's faults — blame
        stays chunk-granular, a poisoned draft cannot charge the target
        engine."""
        try:
            if self.dispatch_timeout_s is None:
                return fn()
            return self._run_deadlined(fn, label)
        except DispatchFailedError:
            raise
        except InjectedDispatchHang as e:
            with self._lock:
                self.stats["watchdog_fires"] += 1
            flight_recorder().record(
                "dispatch_hang", engine=self.name, label=label,
                seconds=e.seconds, exempt=exempt)
            budget = (f"{self.dispatch_timeout_s:.1f}s watchdog budget"
                      if self.dispatch_timeout_s is not None
                      else "no watchdog configured — a real hang would "
                           "block forever")
            raise DispatchHungError(
                f"{self.name} {label} dispatch hung "
                f"(injected {e.seconds:.1f}s; {budget})") from e
        except Exception as e:
            with self._lock:
                self.stats["exempt_failures" if exempt
                           else "dispatch_failures"] += 1
            flight_recorder().record(
                "dispatch_failure", engine=self.name, label=label,
                error=f"{type(e).__name__}: {e}", exempt=exempt)
            raise DispatchFailedError(
                f"{self.name} {label} dispatch failed: "
                f"{type(e).__name__}: {e}") from e

    def _run_deadlined(self, fn: Callable, label: str):
        """Run fn on a deadline thread, mirroring ResilientTrainer's
        hung-step watchdog. On timeout the worker is abandoned (daemon:
        it can never outlive the process the breaker is recycling)."""
        box: dict = {}
        done = threading.Event()

        def worker():
            try:
                box["value"] = fn()
            except BaseException as e:   # delivered to the caller below
                box["error"] = e
            finally:
                done.set()

        t = threading.Thread(target=worker, daemon=True,
                             name=f"pdtpu-{self.name}-dispatch")
        t.start()
        if not done.wait(self.dispatch_timeout_s):
            with self._lock:
                self.stats["watchdog_fires"] += 1
            flight_recorder().record(
                "dispatch_hang", engine=self.name, label=label,
                seconds=self.dispatch_timeout_s)
            raise DispatchHungError(
                f"{self.name} {label} dispatch exceeded the "
                f"{self.dispatch_timeout_s:.1f}s watchdog budget; "
                "abandoning the worker thread")
        if "error" in box:
            raise box["error"]
        return box["value"]

    # ---- circuit breaker (engine-level failure accounting) ----
    def record_failure(self) -> bool:
        """One engine-level failure (a whole protocol exhausted its
        retries). Returns True when this call tripped the breaker open."""
        tripped = False
        with self._lock:
            self._consecutive += 1
            if not self._open and self._consecutive >= self.breaker_threshold:
                self._open = True
                self.stats["breaker_trips"] += 1
                tripped = True
        if tripped:
            _log.error(
                "%s circuit breaker OPEN after %d consecutive engine-level "
                "failures; engine stops admitting and should be drained",
                self.name, self.breaker_threshold)
            # black-box dump BEFORE the drain callback: the postmortem must
            # capture the failure run-up even if the drain itself wedges
            fr = flight_recorder()
            fr.record("breaker_open", engine=self.name,
                      threshold=self.breaker_threshold)
            fr.try_dump(reason=f"breaker_open:{self.name}")
            if self.on_trip is not None:
                try:
                    self.on_trip()
                except Exception:
                    _log.exception("%s on_trip callback failed", self.name)
        return tripped

    def record_success(self):
        with self._lock:
            self._consecutive = 0

    def absolve(self):
        """The failure streak was attributed to one request (quarantined):
        reset the breaker — a poisoned request is not an engine fault."""
        with self._lock:
            self.stats["quarantines"] += 1
            self._consecutive = 0
        flight_recorder().record("breaker_absolved", engine=self.name)

    @property
    def open(self) -> bool:
        with self._lock:
            return self._open

    def snapshot(self) -> dict:
        with self._lock:
            return {**self.stats, "circuit_open": self._open,
                    "consecutive_failures": self._consecutive}
