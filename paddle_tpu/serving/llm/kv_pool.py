"""Slot-paged static KV cache pool (ISSUE 5 tentpole).

A fixed pool of `num_slots` cache slots backed by one static slab per
layer: `[num_slots, Hkv, block_len * n_blocks, D]` (exactly the model's
`init_cache(num_slots, capacity)` layout, so the pool, one-shot
`generate()` and the training-side cached forward share one cache
format). Slots are the unit of admission — a sequence owns one slot from
prefill to eviction — and blocks are the unit of *accounting*: the
per-slot block table tracks which `block_len`-sized stripes of the slab a
sequence's KV actually occupies, which is what slot-occupancy metrics and
defrag hygiene reason about (Ragged Paged Attention keeps the same split:
static shapes for the compiler, block tables for the scheduler).

All device writes stay static-shape: rows are filled via
`dynamic_update_slice` (per-row vmapped in the decode hot path), never a
dynamic-extent scatter, so ONE mixed prefill+decode executable serves
every request mix. The pool is host-side bookkeeping (numpy tables +
stats); the slabs it owns are jax arrays threaded through the engine's
jitted calls.

ISSUE 7: the block tables are additionally exposed as padded DEVICE
arrays — `device_block_table() [num_slots, n_blocks]` and
`device_seq_lens() [num_slots]` — consumed directly by the ragged paged
attention kernel. Uploads are version-gated and incremental: the table
holds each slot's identity stripe (slot*n_blocks + i) and is uploaded
once (rows change only via `set_block_row`, e.g. future prefix sharing),
while seq_lens re-uploads lazily only when some length actually changed
since the last fetch — never a host-side rebuild per iteration.
`pad_tokens` extends each slab past the addressable capacity so chunked
prefill's fixed-width `dynamic_update_slice` writes near the capacity
edge land in scratch columns instead of clamping back onto valid KV;
block tables never address the pad region.
"""
from __future__ import annotations

from typing import Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np


class SlotsExhaustedError(RuntimeError):
    """allocate() found no free slot — every slot is decoding. The engine
    maps this to queueing (and ultimately RejectedError admission control),
    never to a dynamic reallocation: pool size is a compile-time shape."""


class SlotPagedKVPool:
    """Fixed pool of KV cache slots with block/length accounting.

    init_cache_fn(batch, max_len) must return the model's cache pytree — a
    list of (k, v) arrays shaped [batch, Hkv, max_len, D] — and is called
    once with batch=num_slots, max_len=block_len*n_blocks. Models enforce
    their own limits here (GPT refuses capacity beyond its learned
    position table).
    """

    def __init__(self, init_cache_fn: Callable, num_slots: int,
                 block_len: int, n_blocks: int, dtype=None,
                 pad_tokens: int = 0):
        if num_slots < 1:
            raise ValueError(f"num_slots must be >= 1, got {num_slots}")
        if block_len < 1 or n_blocks < 1:
            raise ValueError(
                f"block_len/n_blocks must be >= 1, got "
                f"{block_len}/{n_blocks}")
        if pad_tokens < 0:
            raise ValueError(f"pad_tokens must be >= 0, got {pad_tokens}")
        self.num_slots = int(num_slots)
        self.block_len = int(block_len)
        self.n_blocks = int(n_blocks)
        self.capacity = self.block_len * self.n_blocks  # tokens per slot
        # slab columns past `capacity` are write-scratch for fixed-width
        # chunked-prefill stripes; never addressed by any block table
        self.pad_tokens = int(pad_tokens)
        self.slab_len = self.capacity + self.pad_tokens
        kwargs = {} if dtype is None else {"dtype": dtype}
        self.slabs: List[Tuple[jnp.ndarray, jnp.ndarray]] = [
            (k, v) for k, v in init_cache_fn(self.num_slots, self.slab_len,
                                             **kwargs)]
        self.lengths = np.zeros((self.num_slots,), np.int32)
        self.active = np.zeros((self.num_slots,), bool)
        # freed-but-not-scrubbed slots: their blocks still hold stale KV
        # until defrag() zeroes them (hygiene, not correctness — prefill
        # overwrites the whole row on reuse)
        self.dirty = np.zeros((self.num_slots,), bool)
        # slot -> global block ids backing its current length (contiguous
        # within the slot's stripe: slot*n_blocks + i)
        self.block_table: Dict[int, List[int]] = {}
        self.stats = {"allocs": 0, "frees": 0, "reuses": 0,
                      "alloc_failures": 0, "defrags": 0, "peak_active": 0}
        self._scrub = None   # lazily-jitted defrag kernel
        # device-array mirrors for the ragged kernel: identity stripes
        # (slot s owns global pages s*n_blocks..s*n_blocks+n_blocks-1);
        # version counters gate re-upload so the hot loop pays a transfer
        # only when something actually changed
        self._host_table = (
            np.arange(self.num_slots, dtype=np.int32)[:, None]
            * self.n_blocks
            + np.arange(self.n_blocks, dtype=np.int32)[None, :])
        self._table_version = 1
        self._table_uploaded = 0
        self._dev_table: Optional[jnp.ndarray] = None
        self._lens_version = 1
        self._lens_uploaded = 0
        self._dev_lens: Optional[jnp.ndarray] = None

    # ---- allocation ----
    def allocate(self, need_tokens: int) -> int:
        """Claim a free slot for a sequence that will grow to
        `need_tokens` (prompt + max_new_tokens). Raises ValueError when the
        request can never fit and SlotsExhaustedError when the pool is
        momentarily full."""
        if need_tokens > self.capacity:
            raise ValueError(
                f"sequence needs {need_tokens} tokens but slot capacity is "
                f"{self.capacity} (block_len={self.block_len} x "
                f"n_blocks={self.n_blocks})")
        free = np.flatnonzero(~self.active)
        if free.size == 0:
            self.stats["alloc_failures"] += 1
            raise SlotsExhaustedError(
                f"all {self.num_slots} slots active")
        slot = int(free[0])
        self.active[slot] = True
        if self.dirty[slot]:
            self.stats["reuses"] += 1
            self.dirty[slot] = False
        if self.lengths[slot] != 0:
            self._lens_version += 1
        self.lengths[slot] = 0
        self.block_table[slot] = []
        self.stats["allocs"] += 1
        self.stats["peak_active"] = max(self.stats["peak_active"],
                                        int(self.active.sum()))
        return slot

    def free(self, slot: int):
        if not self.active[slot]:
            raise ValueError(f"slot {slot} is not active")
        self.active[slot] = False
        self.dirty[slot] = True
        if self.lengths[slot] != 0:
            self._lens_version += 1
        self.lengths[slot] = 0
        self.block_table.pop(slot, None)
        self.stats["frees"] += 1

    def set_length(self, slot: int, length: int):
        """Record `length` valid tokens in `slot`, growing its block table
        to ceil(length / block_len) blocks."""
        if not self.active[slot]:
            raise ValueError(f"slot {slot} is not active")
        if length > self.capacity:
            raise ValueError(
                f"length {length} exceeds slot capacity {self.capacity}")
        if int(self.lengths[slot]) != int(length):
            self._lens_version += 1
        self.lengths[slot] = length
        blocks = -(-int(length) // self.block_len)
        self.block_table[slot] = [slot * self.n_blocks + i
                                  for i in range(blocks)]

    def set_block_row(self, slot: int, blocks: List[int]):
        """Point `slot`'s device-table row at an explicit page list
        (incremental update — only this row changes; padding pages past
        len(blocks) are don't-cares masked by seq_lens). The escape hatch
        for non-identity layouts: defragged pools in tests today, prefix
        sharing tomorrow."""
        if len(blocks) > self.n_blocks:
            raise ValueError(
                f"slot row holds at most {self.n_blocks} pages, got "
                f"{len(blocks)}")
        row = np.zeros((self.n_blocks,), np.int32)
        row[:len(blocks)] = np.asarray(blocks, np.int32)
        if not np.array_equal(self._host_table[slot], row):
            self._host_table[slot] = row
            self._table_version += 1

    # ---- device mirrors (ragged paged attention inputs) ----
    def device_block_table(self) -> jnp.ndarray:
        """[num_slots, n_blocks] int32 page ids, uploaded lazily on
        version change (identity stripes → effectively uploaded once)."""
        if self._dev_table is None \
                or self._table_uploaded != self._table_version:
            self._dev_table = jnp.asarray(self._host_table)
            self._table_uploaded = self._table_version
        return self._dev_table

    def device_seq_lens(self) -> jnp.ndarray:
        """[num_slots] int32 committed lengths, uploaded lazily only when
        some set_length() actually changed a value."""
        if self._dev_lens is None \
                or self._lens_uploaded != self._lens_version:
            self._dev_lens = jnp.asarray(self.lengths)
            self._lens_uploaded = self._lens_version
        return self._dev_lens

    # ---- views ----
    def free_slots(self) -> int:
        return int((~self.active).sum())

    def active_slots(self) -> int:
        return int(self.active.sum())

    def occupancy(self) -> float:
        return self.active_slots() / self.num_slots

    def used_blocks(self) -> int:
        return sum(len(b) for b in self.block_table.values())

    def dirty_blocks(self) -> int:
        return int(self.dirty.sum()) * self.n_blocks

    def lengths_array(self) -> jnp.ndarray:
        return jnp.asarray(self.lengths)

    def fragmentation_ratio(self) -> float:
        """Fraction of allocated block tokens not holding valid KV:
        1 - sum(lengths) / (used_blocks * block_len). 0.0 when idle —
        exported as the LLMMetrics fragmentation gauge."""
        used = self.used_blocks()
        if used == 0:
            return 0.0
        return 1.0 - float(self.lengths.sum()) / (used * self.block_len)

    def snapshot(self) -> dict:
        return {
            **self.stats,
            "num_slots": self.num_slots,
            "active_slots": self.active_slots(),
            "capacity_tokens": self.capacity,
            "used_blocks": self.used_blocks(),
            "dirty_blocks": self.dirty_blocks(),
            "total_blocks": self.num_slots * self.n_blocks,
        }

    def check_balance(self) -> bool:
        """Slot-accounting invariant the fault matrix proves: every slot
        ever allocated was either freed or is still active —
        `allocs == frees + active_slots` — i.e. no failure path leaked a
        slot. Raises AssertionError with the ledger on violation."""
        allocs = self.stats["allocs"]
        frees = self.stats["frees"]
        active = self.active_slots()
        if allocs != frees + active:
            raise AssertionError(
                f"KV pool slot ledger out of balance: allocs={allocs} != "
                f"frees={frees} + active={active} "
                f"(leaked {allocs - frees - active})")
        return True

    # ---- hygiene ----
    def defrag(self) -> int:
        """Scrub stale KV out of freed slots (one jitted masked multiply
        over each slab) and return the number of blocks reclaimed. Purely
        hygienic — correctness never depends on it because prefill
        overwrites a slot's whole stripe on reuse — but it keeps dirty
        blocks from aging in HBM snapshots/checkpoints and makes the
        free-block gauge mean 'zeroed and ready'."""
        reclaimed = int(self.dirty.sum()) * self.n_blocks
        if reclaimed == 0:
            return 0
        if self._scrub is None:
            self._scrub = jax.jit(
                lambda slab, keep: slab * keep[:, None, None, None])
        keep = jnp.asarray(~self.dirty)
        self.slabs = [(self._scrub(k, keep.astype(k.dtype)),
                       self._scrub(v, keep.astype(v.dtype)))
                      for k, v in self.slabs]
        self.dirty[:] = False
        self.stats["defrags"] += 1
        return reclaimed
