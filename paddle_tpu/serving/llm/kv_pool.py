"""Slot-paged static KV cache pool (ISSUE 5 tentpole; ISSUE 8 shared
block pool).

A fixed pool of `num_slots` cache slots backed by one static slab per
layer: `[num_slots, Hkv, block_len * n_blocks (+ pad), D]` (exactly the
model's `init_cache(num_slots, capacity)` layout, so the pool, one-shot
`generate()` and the training-side cached forward share one cache
format). Slots are the unit of admission — a sequence owns one slot from
prefill to eviction — and blocks are the unit of *accounting and
sharing*: the per-slot block table tracks which `block_len`-sized pages
of the slabs back a sequence's KV.

All device writes stay static-shape: rows are filled via
`dynamic_update_slice` (per-row vmapped in the decode hot path), never a
dynamic-extent scatter, so ONE mixed prefill+decode executable serves
every request mix. The pool is host-side bookkeeping (numpy tables +
stats); the slabs it owns are jax arrays threaded through the engine's
jitted calls.

ISSUE 7: the block tables are additionally exposed as padded DEVICE
arrays — `device_block_table() [num_slots, n_blocks]` and
`device_seq_lens() [num_slots]` — consumed directly by the ragged paged
attention kernel. Uploads are version-gated and incremental. `pad_tokens`
extends each slab past the addressable capacity so chunked prefill's
fixed-width writes near the capacity edge land in scratch columns; block
tables never address the pad region.

ISSUE 8 — the shared block pool under the prefix cache. The KV write
path (`ops/attention.update_kv_cache`) always lands a dispatch row's new
KV in that row's own slab stripe at its logical column offset, so a
slot's OWN page for logical block j is invariably the physical page
`slot * n_blocks + j`; only the READ side (the ragged kernel's block
table) redirects. Prefix sharing is therefore expressed as:

- `attach_blocks(slot, pages)` points a slot's leading logical blocks at
  pages physically living in OTHER rows (the row of the slot that
  originally prefilled them), refcounting every shared page;
- `cow_copy(src_page, dst_slot)` copies one shared *partial* block into
  the slot's own page so the suffix can diverge in place (copy-on-write);
- a prefix cache pins pages via `register_cached`/`release_cached`; rows
  holding pinned pages are never handed out by `allocate` (a fresh
  prefill would overwrite the cached KV) — under pressure `allocate`
  invokes the `on_pressure` hook so the cache can evict refcount-0
  entries LRU-first, and pages with live readers are structurally
  un-evictable;
- `defrag` is refcount-aware at PAGE granularity: it scrubs the stale
  columns of freed rows while leaving cached pages bit-intact;
- the ledger extends from slots to blocks: every page ever claimed is
  freed, active, or cached — `check_balance()` proves both ledgers.

Ownership: a page claimed by a slot counts as *active* while the slot
lives. When the slot frees, each own page either transfers to the cache
(it was registered: now *cached*) or is *freed*. Evicting a cache-owned
page frees it. `blocks_allocated == blocks_freed + blocks_active +
blocks_cached` at every quiescent point.
"""
from __future__ import annotations

from typing import Callable, Dict, List, Optional, Set, Tuple

import jax
import jax.numpy as jnp
import numpy as np


class SlotsExhaustedError(RuntimeError):
    """allocate() found no usable free slot — every row is decoding or
    pinned by cached blocks with live readers. The engine maps this to
    queueing (and ultimately RejectedError admission control), never to a
    dynamic reallocation: pool size is a compile-time shape."""


class SlotPagedKVPool:
    """Fixed pool of KV cache slots with block/length accounting and a
    shared, refcounted block pool for prefix sharing.

    init_cache_fn(batch, max_len) must return the model's cache pytree — a
    list of (k, v) arrays shaped [batch, Hkv, max_len, D] — and is called
    once with batch=num_slots, max_len=block_len*n_blocks (+pad). Models
    enforce their own limits here (GPT refuses capacity beyond its
    learned position table).
    """

    def __init__(self, init_cache_fn: Callable, num_slots: int,
                 block_len: int, n_blocks: int, dtype=None,
                 pad_tokens: int = 0):
        if num_slots < 1:
            raise ValueError(f"num_slots must be >= 1, got {num_slots}")
        if block_len < 1 or n_blocks < 1:
            raise ValueError(
                f"block_len/n_blocks must be >= 1, got "
                f"{block_len}/{n_blocks}")
        if pad_tokens < 0:
            raise ValueError(f"pad_tokens must be >= 0, got {pad_tokens}")
        self.num_slots = int(num_slots)
        self.block_len = int(block_len)
        self.n_blocks = int(n_blocks)
        self.capacity = self.block_len * self.n_blocks  # tokens per slot
        # slab columns past `capacity` are write-scratch for fixed-width
        # chunked-prefill stripes; never addressed by any block table
        self.pad_tokens = int(pad_tokens)
        self.slab_len = self.capacity + self.pad_tokens
        kwargs = {} if dtype is None else {"dtype": dtype}
        self.slabs: List[Tuple[jnp.ndarray, jnp.ndarray]] = [
            (k, v) for k, v in init_cache_fn(self.num_slots, self.slab_len,
                                             **kwargs)]
        self.lengths = np.zeros((self.num_slots,), np.int32)
        self.active = np.zeros((self.num_slots,), bool)
        # freed-but-not-scrubbed rows: their non-cached pages still hold
        # stale KV until defrag() zeroes them (hygiene, not correctness —
        # prefill overwrites the written range on reuse)
        self.dirty = np.zeros((self.num_slots,), bool)
        # slot -> global page ids backing its current length: leading
        # entries may be attached (shared) pages in other rows, the rest
        # are the slot's own identity pages (slot*n_blocks + j)
        self.block_table: Dict[int, List[int]] = {}
        # ---- shared-block state (ISSUE 8) ----
        self._attached: Dict[int, List[int]] = {}   # slot -> shared pages
        self._own_claimed: Dict[int, int] = {}      # slot -> own pages
        self.refcount: Dict[int, int] = {}          # page -> live readers
        self.cached: Set[int] = set()               # pages pinned by cache
        self._cache_owned: Set[int] = set()         # cached, owner freed
        # cache-pressure hook: called by allocate() when free rows exist
        # but every one is pinned; the prefix cache wires its LRU
        # eviction here and returns the number of pages released
        self.on_pressure: Optional[Callable[[], int]] = None
        self.stats = {"allocs": 0, "frees": 0, "reuses": 0,
                      "alloc_failures": 0, "defrags": 0, "peak_active": 0,
                      "blocks_allocated": 0, "blocks_freed": 0,
                      "cow_copies": 0}
        self._scrub = None   # lazily-jitted defrag kernel (page mask)
        self._cow = None     # lazily-jitted copy-on-write block copy
        # device-array mirrors for the ragged kernel: identity stripes
        # (slot s owns global pages s*n_blocks..s*n_blocks+n_blocks-1)
        # until attach_blocks redirects a row; version counters gate
        # re-upload so the hot loop pays a transfer only on change
        self._host_table = self._identity_table()
        self._table_version = 1
        self._table_uploaded = 0
        self._dev_table: Optional[jnp.ndarray] = None
        self._lens_version = 1
        self._lens_uploaded = 0
        self._dev_lens: Optional[jnp.ndarray] = None

    def _identity_table(self) -> np.ndarray:
        return (np.arange(self.num_slots, dtype=np.int32)[:, None]
                * self.n_blocks
                + np.arange(self.n_blocks, dtype=np.int32)[None, :])

    def _identity_row(self, slot: int) -> List[int]:
        return [slot * self.n_blocks + j for j in range(self.n_blocks)]

    def _row_pinned(self, row: int) -> bool:
        """A row holding ANY cached page cannot be handed to a fresh
        sequence: its prefill would overwrite shared KV in place."""
        base = row * self.n_blocks
        return any((base + j) in self.cached for j in range(self.n_blocks))

    def has_allocatable_row(self) -> bool:
        return any(not self.active[r] and not self._row_pinned(r)
                   for r in range(self.num_slots))

    # ---- allocation ----
    def allocate(self, need_tokens: int) -> int:
        """Claim a free, unpinned slot for a sequence that will grow to
        `need_tokens` (prompt + max_new_tokens). Raises ValueError when
        the request can never fit and SlotsExhaustedError when the pool
        is momentarily full. When every free row is pinned by cached
        blocks, the `on_pressure` hook (the prefix cache's LRU eviction)
        gets one chance to release refcount-0 entries before the
        exhaustion verdict — pages with live readers are never touched."""
        if need_tokens > self.capacity:
            raise ValueError(
                f"sequence needs {need_tokens} tokens but slot capacity is "
                f"{self.capacity} (block_len={self.block_len} x "
                f"n_blocks={self.n_blocks})")
        free = np.flatnonzero(~self.active)
        if free.size == 0:
            self.stats["alloc_failures"] += 1
            raise SlotsExhaustedError(
                f"all {self.num_slots} slots active")
        slot = next((int(r) for r in free if not self._row_pinned(r)), None)
        if slot is None and self.on_pressure is not None:
            self.on_pressure()
            slot = next((int(r) for r in free if not self._row_pinned(r)),
                        None)
        if slot is None:
            self.stats["alloc_failures"] += 1
            raise SlotsExhaustedError(
                f"every free slot is pinned by cached blocks with live "
                f"readers ({free.size} free of {self.num_slots})")
        self.active[slot] = True
        if self.dirty[slot]:
            self.stats["reuses"] += 1
            self.dirty[slot] = False
        if self.lengths[slot] != 0:
            self._lens_version += 1
        self.lengths[slot] = 0
        self.block_table[slot] = []
        self._attached[slot] = []
        self._own_claimed[slot] = 0
        self.set_block_row(slot, self._identity_row(slot))
        self.stats["allocs"] += 1
        self.stats["peak_active"] = max(self.stats["peak_active"],
                                        int(self.active.sum()))
        return slot

    def free(self, slot: int):
        """Release a slot: drop the refcount it held on every attached
        (shared) page, and settle its OWN pages' ledger — pages the cache
        registered transfer ownership to the cache, the rest are freed."""
        if not self.active[slot]:
            raise ValueError(f"slot {slot} is not active")
        for p in self._attached.get(slot, ()):
            self.release_block(p)
        n_att = len(self._attached.get(slot, ()))
        for j in range(n_att, n_att + self._own_claimed.get(slot, 0)):
            p = slot * self.n_blocks + j
            if p in self.cached:
                self._cache_owned.add(p)
            else:
                self.stats["blocks_freed"] += 1
        self._attached.pop(slot, None)
        self._own_claimed.pop(slot, None)
        self.active[slot] = False
        self.dirty[slot] = True
        if self.lengths[slot] != 0:
            self._lens_version += 1
        self.lengths[slot] = 0
        self.block_table.pop(slot, None)
        self.stats["frees"] += 1

    def set_length(self, slot: int, length: int):
        """Record `length` valid tokens in `slot`, growing its block
        table to ceil(length / block_len) pages: the attached shared
        prefix first, then the slot's own identity pages. Newly-claimed
        own pages charge the block ledger."""
        if not self.active[slot]:
            raise ValueError(f"slot {slot} is not active")
        if length > self.capacity:
            raise ValueError(
                f"length {length} exceeds slot capacity {self.capacity}")
        if int(self.lengths[slot]) != int(length):
            self._lens_version += 1
        self.lengths[slot] = length
        blocks = -(-int(length) // self.block_len)
        attached = self._attached.get(slot, [])
        own_needed = max(0, blocks - len(attached))
        claimed = self._own_claimed.get(slot, 0)
        if own_needed > claimed:
            self.stats["blocks_allocated"] += own_needed - claimed
            self._own_claimed[slot] = own_needed
        self.block_table[slot] = (
            attached[:blocks]
            + [slot * self.n_blocks + j
               for j in range(len(attached), blocks)])

    def rewind_length(self, slot: int, length: int):
        """Shrink `slot`'s committed length to `length`, returning own
        pages past the new block count to the ledger (ISSUE 17
        speculative decoding: a draft window commits K tokens of KV
        optimistically; rejected positions must give their pages back so
        `check_balance()` keeps holding). Cache-registered own pages stay
        claimed — the prefix cache owns their lifetime, and `_own_claimed`
        is a contiguous count, so the scan un-claims from the top down and
        stops at the first cached page. Attached (shared) pages are never
        touched: they back the prefix below any rewind point. Growing is
        `set_length`'s job; a larger `length` raises."""
        if not self.active[slot]:
            raise ValueError(f"slot {slot} is not active")
        length = int(length)
        cur = int(self.lengths[slot])
        if length > cur:
            raise ValueError(
                f"rewind_length can only shrink: {length} > committed "
                f"{cur} (use set_length to grow)")
        if length < 0:
            raise ValueError(f"length must be >= 0, got {length}")
        if length == cur:
            return
        self._lens_version += 1
        self.lengths[slot] = length
        blocks = -(-length // self.block_len)
        attached = self._attached.get(slot, [])
        own_needed = max(0, blocks - len(attached))
        claimed = self._own_claimed.get(slot, 0)
        new_claimed = claimed
        for j in range(len(attached) + claimed - 1,
                       len(attached) + own_needed - 1, -1):
            if slot * self.n_blocks + j in self.cached:
                break
            new_claimed -= 1
        if new_claimed != claimed:
            self.stats["blocks_freed"] += claimed - new_claimed
            self._own_claimed[slot] = new_claimed
        self.block_table[slot] = (
            attached[:blocks]
            + [slot * self.n_blocks + j
               for j in range(len(attached), blocks)])

    # ---- prefix sharing (ISSUE 8) ----
    def attach_blocks(self, slot: int, pages: List[int]):
        """Point `slot`'s leading logical blocks at shared pages computed
        by other slots, taking a refcount on each for this slot's
        lifetime. Every shared page must be cache-registered and must sit
        at its logical block offset (`page % n_blocks == j` — the write
        path guarantees a slot's block j is physically at column j of its
        own row, so cached pages always satisfy this)."""
        if not self.active[slot]:
            raise ValueError(f"slot {slot} is not active")
        if len(pages) > self.n_blocks:
            raise ValueError(
                f"cannot attach {len(pages)} pages to a "
                f"{self.n_blocks}-block slot")
        for j, p in enumerate(pages):
            if p not in self.cached:
                raise ValueError(
                    f"page {p} is not cache-registered; only cached "
                    "blocks can be shared")
            if p % self.n_blocks != j:
                raise ValueError(
                    f"page {p} lives at block offset {p % self.n_blocks}, "
                    f"cannot back logical block {j}")
        for p in pages:
            self.refcount[p] = self.refcount.get(p, 0) + 1
        self._attached[slot] = list(pages)
        self.set_block_row(
            slot, list(pages) + [slot * self.n_blocks + j
                                 for j in range(len(pages), self.n_blocks)])

    def release_block(self, page: int):
        """Drop one reader's refcount on a shared page."""
        n = self.refcount.get(page, 0)
        if n <= 1:
            self.refcount.pop(page, None)
        else:
            self.refcount[page] = n - 1

    def cow_copy(self, src_page: int, dst_slot: int):
        """Copy-on-write: copy one shared (partial) block's KV into
        `dst_slot`'s own page at the same logical offset, so the slot can
        append divergent tokens into it. One jitted two-op copy
        (dynamic_slice + dynamic_update_slice) per slab; traced row/col
        offsets keep it a single executable per slab shape."""
        if not self.active[dst_slot]:
            raise ValueError(f"slot {dst_slot} is not active")
        block_idx = src_page % self.n_blocks
        src_row = src_page // self.n_blocks
        if src_row == dst_slot:
            return
        if self._cow is None:
            blk_len = self.block_len

            def _cow(slab, src_r, dst_r, c0):
                blk = jax.lax.dynamic_slice(
                    slab, (src_r, 0, c0, 0),
                    (1, slab.shape[1], blk_len, slab.shape[3]))
                return jax.lax.dynamic_update_slice(
                    slab, blk, (dst_r, 0, c0, 0))

            self._cow = jax.jit(_cow)
        sr = jnp.int32(src_row)
        dr = jnp.int32(dst_slot)
        c0 = jnp.int32(block_idx * self.block_len)
        self.slabs = [(self._cow(k, sr, dr, c0), self._cow(v, sr, dr, c0))
                      for k, v in self.slabs]
        self.stats["cow_copies"] += 1

    def register_cached(self, page: int):
        """Pin a page on behalf of the prefix cache: its row leaves the
        allocatable set and defrag will never scrub its columns."""
        if not (0 <= page < self.num_slots * self.n_blocks):
            raise ValueError(f"page {page} out of range")
        if page in self.cached:
            raise ValueError(f"page {page} already cache-registered")
        self.cached.add(page)

    def release_cached(self, page: int):
        """Cache eviction: unpin a page. Refuses while readers hold it.
        A cache-owned page (its slot freed) settles to the freed side of
        the block ledger; its row becomes scrub-eligible again."""
        if page not in self.cached:
            raise ValueError(f"page {page} is not cache-registered")
        if self.refcount.get(page, 0) > 0:
            raise ValueError(
                f"page {page} has {self.refcount[page]} live reader(s); "
                "evicting it would corrupt active streams")
        self.cached.discard(page)
        if page in self._cache_owned:
            self._cache_owned.discard(page)
            self.stats["blocks_freed"] += 1
        row = page // self.n_blocks
        if not self.active[row]:
            self.dirty[row] = True

    def set_block_row(self, slot: int, blocks: List[int]):
        """Point `slot`'s device-table row at an explicit page list
        (incremental update — only this row changes; padding pages past
        len(blocks) are don't-cares masked by seq_lens). The mechanism
        under attach_blocks, and the escape hatch for non-identity
        layouts in tests."""
        if len(blocks) > self.n_blocks:
            raise ValueError(
                f"slot row holds at most {self.n_blocks} pages, got "
                f"{len(blocks)}")
        row = np.zeros((self.n_blocks,), np.int32)
        row[:len(blocks)] = np.asarray(blocks, np.int32)
        if not np.array_equal(self._host_table[slot], row):
            self._host_table[slot] = row
            self._table_version += 1

    # ---- device mirrors (ragged paged attention inputs) ----
    def device_block_table(self) -> jnp.ndarray:
        """[num_slots, n_blocks] int32 page ids, uploaded lazily on
        version change (identity stripes → effectively uploaded once for
        cold traffic; attach/restore bump the version per changed row)."""
        if self._dev_table is None \
                or self._table_uploaded != self._table_version:
            self._dev_table = jnp.asarray(self._host_table)
            self._table_uploaded = self._table_version
        return self._dev_table

    def device_seq_lens(self) -> jnp.ndarray:
        """[num_slots] int32 committed lengths, uploaded lazily only when
        some set_length() actually changed a value."""
        if self._dev_lens is None \
                or self._lens_uploaded != self._lens_version:
            self._dev_lens = jnp.asarray(self.lengths)
            self._lens_uploaded = self._lens_version
        return self._dev_lens

    # ---- views ----
    def free_slots(self) -> int:
        return int((~self.active).sum())

    def active_slots(self) -> int:
        return int(self.active.sum())

    def occupancy(self) -> float:
        return self.active_slots() / self.num_slots

    def used_blocks(self) -> int:
        return sum(len(b) for b in self.block_table.values())

    def blocks_active(self) -> int:
        """Own pages claimed by currently-active slots (shared attached
        pages are accounted by their owner or the cache, never twice)."""
        return sum(n for s, n in self._own_claimed.items()
                   if self.active[s])

    def blocks_cached(self) -> int:
        """Pages whose owning slot freed while the cache held them: the
        cache is now the owner of record."""
        return len(self._cache_owned)

    def cached_blocks(self) -> int:
        """Every page currently pinned by the prefix cache (owner active
        or not)."""
        return len(self.cached)

    def dirty_blocks(self) -> int:
        """Scrubable pages: pages of freed rows NOT pinned by the cache."""
        total = 0
        for r in np.flatnonzero(self.dirty):
            base = int(r) * self.n_blocks
            total += sum(1 for j in range(self.n_blocks)
                         if (base + j) not in self.cached)
        return total

    def lengths_array(self) -> jnp.ndarray:
        return jnp.asarray(self.lengths)

    def fragmentation_ratio(self) -> float:
        """Fraction of allocated block tokens not holding valid KV:
        1 - sum(lengths) / (used_blocks * block_len). 0.0 when idle —
        exported as the LLMMetrics fragmentation gauge."""
        used = self.used_blocks()
        if used == 0:
            return 0.0
        return 1.0 - float(self.lengths.sum()) / (used * self.block_len)

    def snapshot(self) -> dict:
        return {
            **self.stats,
            "num_slots": self.num_slots,
            "active_slots": self.active_slots(),
            "capacity_tokens": self.capacity,
            "used_blocks": self.used_blocks(),
            "dirty_blocks": self.dirty_blocks(),
            "total_blocks": self.num_slots * self.n_blocks,
            "blocks_active": self.blocks_active(),
            "blocks_cached": self.blocks_cached(),
            "cached_pages": self.cached_blocks(),
        }

    def check_balance(self) -> bool:
        """The two accounting invariants the fault matrix proves after
        every scenario. Slots: every slot ever allocated was freed or is
        still active (`allocs == frees + active_slots`). Blocks: every
        page ever claimed is freed, active in a living slot, or owned by
        the cache (`blocks_allocated == blocks_freed + blocks_active +
        blocks_cached`) — i.e. no failure path leaked a slot OR a page.
        Raises AssertionError with the offending ledger on violation."""
        allocs = self.stats["allocs"]
        frees = self.stats["frees"]
        active = self.active_slots()
        if allocs != frees + active:
            raise AssertionError(
                f"KV pool slot ledger out of balance: allocs={allocs} != "
                f"frees={frees} + active={active} "
                f"(leaked {allocs - frees - active})")
        b_alloc = self.stats["blocks_allocated"]
        b_freed = self.stats["blocks_freed"]
        b_active = self.blocks_active()
        b_cached = self.blocks_cached()
        if b_alloc != b_freed + b_active + b_cached:
            raise AssertionError(
                f"KV pool block ledger out of balance: "
                f"blocks_allocated={b_alloc} != blocks_freed={b_freed} + "
                f"blocks_active={b_active} + blocks_cached={b_cached} "
                f"(leaked {b_alloc - b_freed - b_active - b_cached})")
        return True

    # ---- row serialization (ISSUE 14: KV handoff groundwork) ----
    def export_rows(self, slots: List[int]) -> dict:
        """Serialize the committed KV of active `slots` to host numpy:
        per slot, its valid length and per-layer [Hkv, length, D] K/V
        arrays assembled page-by-page through the block table (attached
        shared pages read from their physical row, exactly as the ragged
        kernel would). The payload is self-describing enough for
        `import_rows` on ANOTHER pool with the same slab geometry — the
        groundwork for prefill/decode-disaggregated KV handoff. KV alone
        is not enough to resume a SAMPLED stream bit-identically: pair
        this payload with `LLMEngine.export_sampling_lanes` (ISSUE 18),
        which carries each slot's RNG-lane index and grammar DFA state."""
        rows: Dict[int, dict] = {}
        for slot in slots:
            slot = int(slot)
            if not self.active[slot]:
                raise ValueError(f"slot {slot} is not active")
            length = int(self.lengths[slot])
            pages = list(self.block_table.get(slot, []))
            layers = []
            for k, v in self.slabs:
                # ISSUE 19: length-trimmed fetch — slice each occupied
                # page's columns on DEVICE and fetch only those, instead
                # of materializing the whole [num_slots, Hkv, slab_len, D]
                # slab on the host per layer. Spill/handoff copies scale
                # with the row's committed length, not the pool size; the
                # payload is bit-identical to the untrimmed path (pinned
                # in tests/test_router.py).
                kparts, vparts = [], []
                for j, p in enumerate(pages):
                    prow = p // self.n_blocks
                    c0 = (p % self.n_blocks) * self.block_len
                    w = min(self.block_len, length - j * self.block_len)
                    kparts.append(np.asarray(k[prow, :, c0:c0 + w, :]))
                    vparts.append(np.asarray(v[prow, :, c0:c0 + w, :]))
                if kparts:
                    layers.append((np.concatenate(kparts, axis=1),
                                   np.concatenate(vparts, axis=1)))
                else:
                    hkv, d = k.shape[1], k.shape[3]
                    empty = np.zeros((hkv, 0, d), dtype=k.dtype)
                    layers.append((empty, empty.copy()))
            rows[slot] = {"length": length, "layers": layers}
        return {"block_len": self.block_len, "capacity": self.capacity,
                "rows": rows}

    def export_page(self, page: int,
                    width: Optional[int] = None) -> List[Tuple[np.ndarray,
                                                               np.ndarray]]:
        """Fetch ONE page's occupied KV columns to host numpy: per layer
        an owned ([Hkv, width, D] K, same-shape V) pair, sliced on device
        so the transfer is exactly `width` tokens. This is the spill unit
        the host tier (HostKVPool, ISSUE 19) stores; `width` defaults to
        the full block."""
        if not (0 <= page < self.num_slots * self.n_blocks):
            raise ValueError(f"page {page} out of range")
        w = self.block_len if width is None else int(width)
        if not (0 < w <= self.block_len):
            raise ValueError(
                f"width must be in 1..{self.block_len}, got {w}")
        prow = page // self.n_blocks
        c0 = (page % self.n_blocks) * self.block_len
        return [(np.asarray(k[prow, :, c0:c0 + w, :]),
                 np.asarray(v[prow, :, c0:c0 + w, :]))
                for k, v in self.slabs]

    def import_page(self, slot: int, block_idx: int,
                    layers: List[Tuple[np.ndarray, np.ndarray]]):
        """Land one spilled page's KV into `slot`'s OWN identity page at
        logical block `block_idx` (the write-path invariant: a slot's
        block j is physically at column j of its own row, so the identity
        block table already covers it). Inverse of `export_page`, bitwise.
        Ledger accounting rides the normal path: the engine's next
        `set_length` past this block claims the own page."""
        if not self.active[slot]:
            raise ValueError(f"slot {slot} is not active")
        if not (0 <= block_idx < self.n_blocks):
            raise ValueError(f"block_idx {block_idx} out of range "
                             f"0..{self.n_blocks - 1}")
        if len(layers) != len(self.slabs):
            raise ValueError(
                f"payload has {len(layers)} layers, pool has "
                f"{len(self.slabs)}")
        c0 = block_idx * self.block_len
        new_slabs = []
        for (k, v), (ke, ve) in zip(self.slabs, layers):
            if ke.shape[1] > self.block_len:
                raise ValueError(
                    f"page payload holds {ke.shape[1]} tokens, block_len "
                    f"is {self.block_len}")
            ku = jnp.asarray(ke, dtype=k.dtype)[None]
            vu = jnp.asarray(ve, dtype=v.dtype)[None]
            k = jax.lax.dynamic_update_slice(k, ku, (slot, 0, c0, 0))
            v = jax.lax.dynamic_update_slice(v, vu, (slot, 0, c0, 0))
            new_slabs.append((k, v))
        self.slabs = new_slabs

    def import_rows(self, exported: dict) -> Dict[int, int]:
        """Materialize `export_rows` payload rows into THIS pool: each
        exported row allocates a fresh slot, commits its length (own
        identity pages — attachment structure is not preserved, the KV
        bytes are), and lands the K/V columns bitwise via
        dynamic_update_slice. Returns {source_slot: destination_slot}."""
        if int(exported["block_len"]) != self.block_len:
            raise ValueError(
                f"block_len mismatch: exported {exported['block_len']} "
                f"vs pool {self.block_len}")
        mapping: Dict[int, int] = {}
        for src in sorted(exported["rows"]):
            row = exported["rows"][src]
            length = int(row["length"])
            if length > self.capacity:
                raise ValueError(
                    f"row {src} holds {length} tokens but this pool's "
                    f"capacity is {self.capacity}")
            dst = self.allocate(length)
            self.set_length(dst, length)
            if length > 0:
                new_slabs = []
                for (k, v), (ke, ve) in zip(self.slabs, row["layers"]):
                    ku = jnp.asarray(ke, dtype=k.dtype)[None]
                    vu = jnp.asarray(ve, dtype=v.dtype)[None]
                    k = jax.lax.dynamic_update_slice(k, ku, (dst, 0, 0, 0))
                    v = jax.lax.dynamic_update_slice(v, vu, (dst, 0, 0, 0))
                    new_slabs.append((k, v))
                self.slabs = new_slabs
            mapping[int(src)] = dst
        return mapping

    # ---- hygiene ----
    def defrag(self) -> int:
        """Scrub stale KV out of freed rows (one jitted masked multiply
        over each slab) and return the number of pages reclaimed.
        Refcount-aware at PAGE granularity: a freed row whose pages the
        prefix cache still pins keeps those pages' columns bit-intact —
        shared blocks are never scrubbed — while the rest of the row is
        zeroed. Purely hygienic — correctness never depends on it because
        prefill overwrites the written range on reuse — but it keeps
        dirty blocks from aging in HBM snapshots and makes the free-block
        gauge mean 'zeroed and ready'."""
        rows = np.flatnonzero(self.dirty)
        if rows.size == 0:
            return 0
        keep = np.ones((self.num_slots, self.slab_len), np.float32)
        reclaimed = 0
        for r in rows:
            keep[r, :] = 0.0
            base = int(r) * self.n_blocks
            for j in range(self.n_blocks):
                if (base + j) in self.cached:
                    keep[r, j * self.block_len:(j + 1) * self.block_len] = 1.0
                else:
                    reclaimed += 1
        if reclaimed == 0:
            return 0
        if self._scrub is None:
            self._scrub = jax.jit(
                lambda slab, keep: slab * keep[:, None, :, None])
        keep_j = jnp.asarray(keep)
        self.slabs = [(self._scrub(k, keep_j.astype(k.dtype)),
                       self._scrub(v, keep_j.astype(v.dtype)))
                      for k, v in self.slabs]
        self.dirty[:] = False
        self.stats["defrags"] += 1
        return reclaimed
