"""Continuous-batching LLM decode engine over a slot-paged static KV
cache (ISSUE 5): the autoregressive counterpart of the stateless
BatchingEngine.

    model = GPTForCausalLM(PRESETS["gpt2-tiny"])
    engine = serving.llm.LLMEngine(
        model, serving.llm.LLMEngineConfig(num_slots=8, eos_token_id=2))
    engine.start()
    handle = engine.submit(prompt_ids, max_new_tokens=64)
    tokens = handle.result(timeout=30)      # or handle.tokens_so_far()

Deterministic scheduler testing (no threads, no sleeps):

    engine = LLMEngine(model, cfg, clock=serving.SimClock())
    while engine.has_work():
        engine.pump()               # decode iterations are countable facts

See docs/serving.md (LLM decode engine section) for slot-pool sizing and
block_len tradeoffs.
"""
from .host_kv import HostKVPool  # noqa: F401
from .kv_pool import SlotPagedKVPool, SlotsExhaustedError  # noqa: F401
from .llm_engine import (DispatchFailedError,  # noqa: F401
                         DispatchHungError, GenerationHandle, LLMEngine,
                         LLMEngineConfig, WeightSwapError)
from .prefix_cache import AttachPlan, PrefixCache  # noqa: F401
from .sampling import (SamplingParams, SlotSamplingTable,  # noqa: F401
                       TokenDFA, compile_grammar)
