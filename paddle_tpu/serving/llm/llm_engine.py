"""Continuous-batching LLM decode engine over the slot-paged KV pool
(ISSUE 5 tentpole; ISSUE 6 supervision + overload control; ISSUE 7
ragged paged attention + chunked prefill; ISSUE 8 prefix-sharing radix
KV cache + multi-tenant scheduling; ISSUE 17 speculative decoding).

Prefix sharing (ISSUE 8): admission consults a per-tenant radix
`PrefixCache` — a prompt hitting a cached prefix attaches the donor's
refcounted KV pages (partial blocks copy-on-write into the slot's own
page) and chunk-prefills only the suffix, so N requests sharing a prefix
pay ~one prefill total and a full hit's TTFT is one chunk-wide step.
Chunk-invariance (PR 7) makes warm streams bit-identical to cold ones.
Multi-tenancy: requests carry a tenant id; dequeue is tenant-fair within
each SLO class, an optional per-tenant in-flight token quota rejects
with reason "tenant_quota", and tenants never share cached KV.

The batch-locked `models.generation.generate()` loop makes every sequence
enter together, share one prompt length and pay the batch's full
`max_new_tokens` — one long request holds the whole batch's KV slabs
hostage. This engine schedules the same numeric path (the
`make_decoder_fns` prefill builder routed through the ragged
paged-attention kernel, so outputs are bit-identical per row) as a
continuously-batched service:

- ONE unified mixed-row dispatch per pump iteration (`_step_once`): every
  slot contributes a fixed-width `[prefill_chunk]` row — a prompt chunk
  for prefilling requests, `[last_tok, 0, ...]` for decoding requests,
  zeros for free slots — and the single jitted executable writes all KV
  stripes, runs ragged paged attention over the pool's block tables +
  per-row target lengths, and emits each row's next greedy token. No
  per-pow2-bucket prefill executable zoo, no bucket padding FLOPs: the
  engine compiles exactly one step program for its lifetime;
- **chunked prefill**: prompts longer than `prefill_chunk` are admitted
  as fixed-size chunks interleaved with the decode loop, so a short
  prompt's TTFT is bounded by a couple of chunk-width steps instead of a
  long neighbor's whole-prompt prefill. A row's first token is emitted by
  the step that lands its final chunk (TTFT ends there);
- between iterations the scheduler admits queued requests into freed
  slots and evicts finished rows (EOS / per-request max-tokens /
  deadline — queued, mid-prefill and mid-decode alike), so a short
  request never waits for a long one;
- admission control reuses the serving vocabulary: bounded queue →
  `RejectedError`, absolute deadlines → `DeadlineExceededError`.

Supervision (ISSUE 6, chunk-granular under ISSUE 7): every jitted
dispatch runs through an `EngineSupervisor` — failures arrive as typed
`DispatchFailedError`s, a hung dispatch trips the watchdog
(`DispatchHungError`). The failure protocol keeps faults request-scoped
at CHUNK granularity: a failing step retries whole, then blame-probes
each active row in isolation (prefilling rows probe as "prefill" kind at
their current chunk offset, decoding rows as "decode") and quarantines
the implicated requests — a request poisoned in chunk k>0 is evicted
without touching co-scheduled decode rows, whose streams stay
bit-identical to a fault-free run because probe results are never
committed. Non-attributable failures fail the active rows and count
toward the engine circuit breaker, which opens after `breaker_threshold`
consecutive engine-level failures (admissions reject with reason
"circuit_open", /healthz flips to 503, the server drains).

Overload control (ISSUE 6): requests carry an SLO class —
`interactive` > `batch` > `best_effort` — admitted in strict priority
order from per-class queues. A full queue or an exceeded token budget
(`max_inflight_tokens`, estimated cost = prompt_len + max_new_tokens over
queued + active) sheds the NEWEST queued request of the lowest class
below the submitter (reason "shed") before rejecting; sustained queue
pressure enters brownout, capping newly-admitted `max_new_tokens` so the
backlog drains at interactive-friendly latency.

Speculative decoding (ISSUE 17): a `draft_model` (same vocab, own
`SlotPagedKVPool` + page-congruent "draft" `PrefixCache`) turns each
decode pump into draft-propose + single-dispatch verify. A chunk-wide
draft catch-up replays committed tokens the draft hasn't seen, a jitted
width-1 `lax.scan` proposes `spec_k` tokens per eligible slot (and
pre-writes the draft KV for the all-accept case), and the target scores
all `spec_k + 1` positions in the ONE existing unified dispatch
(`[last_tok, d1..dK]`, adv = K+1). Greedy acceptance takes the longest
draft prefix matching the target's per-position argmax plus the
target's corrective token — bit-identical to plain decode by
construction. Commit is `set_length(L + accepted + 1)`; rejected
columns need no KV scrub (garbage past the committed length IS the
rollback invariant) and the draft pool rewinds via `rewind_length`.
Draft dispatches are supervision-EXEMPT: a failed one triggers
draft-scoped solo probes, a blamed request loses only its draft
(spec_off, stream continues plain), unattributable failures walk a
failstreak to engine-wide `_spec_disabled` — the target breaker is
never charged.

Per-slot sampling + grammar-constrained decoding (ISSUE 18): every
request carries `SamplingParams` (temperature / top-k / top-p / seed /
JSON-schema grammar) that ride the ONE unified step as batched per-slot
ARRAYS — the engine still compiles exactly one step program for its
lifetime, whatever mix of greedy, sampled and constrained rows it
carries. A seeded request's token `i` is drawn on a per-request
threefry lane keyed by `(seed, i)` alone (`sampling.lane_key`), so
sampled streams are bit-identical across batch composition, engine
restart, and router failover re-prefill (the survivor resumes the lane
at `sample_offset = tokens already emitted`). Grammars compile to
token-level DFAs interned in a fixed-shape bank; the step applies the
per-slot state's legal-token mask on device and returns each row's
advanced DFA state. Speculative decoding composes by seeded replay:
the verify pass samples every window position on the same lanes, so
the longest-matching-prefix acceptance yields streams literally
identical to plain sampled decode (see sampling.py). Constrained slots
do not speculate.

Determinism: every decision is a pure function of `clock.now()` and the
queue/pool tables. Under a `SimClock` the engine runs threadless and a
test harness calls `pump()` directly — slot churn and decode-iteration
counts are provable facts, not timing accidents. Under the default
`MonotonicClock`, `start()` runs the same `pump()` from a scheduler
thread. Default decoding is greedy (argmax), bit-reproducible against
one-shot generate() for free; seeded sampling extends the same
guarantee to `(seed, params)`-keyed streams.
"""
from __future__ import annotations

import logging
import threading
from collections import deque
from concurrent.futures import Future
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ...obs.flight_recorder import flight_recorder
from ...obs.trace import RequestTrace, TimelineStore, new_request_id
from ..clock import Clock, MonotonicClock, SimClock
from ..engine import DeadlineExceededError, RejectedError
from ..metrics import LLMMetrics, SLO_CLASSES
from ..supervisor import (DispatchFailedError, DispatchHungError,  # noqa: F401
                          EngineSupervisor)
from .host_kv import HostKVPool
from .kv_pool import SlotPagedKVPool, SlotsExhaustedError
from .lora import AdapterBank, AdapterError
from .prefix_cache import PrefixCache
from .sampling import (GREEDY, SamplingParams, SlotSamplingTable,
                       compile_grammar, select_next, select_tokens)

_log = logging.getLogger("paddle_tpu.serving.llm")


class WeightSwapError(ValueError):
    """`replace_params` refused a hot swap: the engine still holds work,
    or the new tree's abstract signature (structure / leaf shapes /
    dtypes) differs from the serving params — a mismatched signature
    would recompile the unified step mid-fleet, which is exactly what a
    rolling deploy must never do."""


@dataclass
class LLMEngineConfig:
    num_slots: int = 4             # decode width == KV pool size
    block_len: int = 16            # tokens per accounting block
    n_blocks: int = 8              # blocks per slot (capacity = 128 tokens)
    max_queue_depth: int = 64      # pending-request cap (admission control)
    max_new_tokens: int = 32       # default per-request generation cap
    eos_token_id: Optional[int] = None   # per-request override wins
    default_deadline_ms: Optional[float] = None
    prefill_chunk: int = 16        # prompt tokens prefilled per step; also
    #                                the unified step's fixed row width, so
    #                                it bounds how long a long prompt can
    #                                stall its neighbors (TTFT knob)
    drain_timeout_s: float = 60.0
    cache_dtype: Optional[object] = None  # pool slab dtype override
    # ---- overload control (ISSUE 6) ----
    default_slo: str = "batch"     # SLO class when submit() names none
    max_inflight_tokens: Optional[int] = None  # token-budget admission:
    #                                  sum of (prompt + max_new_tokens) over
    #                                  queued + active requests (None: off)
    brownout_queue_depth: Optional[int] = None  # queued requests at/above
    #                                  this enter brownout (None: off);
    #                                  exits at half the threshold
    brownout_max_new_tokens: int = 8  # admission-time cap while browned out
    retry_after_s: float = 1.0     # backpressure hint on overload rejects
    # ---- prefix cache + multi-tenancy (ISSUE 8) ----
    enable_prefix_cache: bool = True   # radix KV prefix sharing on admission
    default_tenant: str = "default"    # tenant when submit() names none
    tenant_max_inflight_tokens: Optional[int] = None  # per-tenant quota:
    #                                  sum of (prompt + max_new_tokens) over
    #                                  one tenant's queued + active requests
    #                                  (None: off); exceeding it is a typed
    #                                  "tenant_quota" reject — shedding other
    #                                  tenants can never help, so it is
    #                                  checked before shed logic runs
    # ---- supervision (ISSUE 6) ----
    dispatch_timeout_s: Optional[float] = None  # hung-dispatch watchdog
    dispatch_retries: int = 2      # whole-step retries before blame/fail
    breaker_threshold: int = 3     # consecutive engine-level failures that
    #                                open the circuit breaker
    # ---- observability (ISSUE 9) ----
    trace_buffer: int = 256        # finished request timelines kept for
    #                                /debug/requests/<rid> (bounded LRU)
    # ---- serving economics (ISSUE 11) ----
    economics: bool = False        # arm the ServingLedger + SLOBurnMonitor;
    #                                off = one predicate per hook, no clock
    #                                reads, no extra device syncs
    slo_burn_budget: float = 0.05       # error budget (bad-outcome fraction)
    slo_burn_threshold: float = 14.4    # page when burn >= this multiple
    slo_burn_fast_window_s: float = 60.0
    slo_burn_slow_window_s: float = 300.0
    slo_burn_min_events: int = 10       # cold-start floor per window
    slo_burn_capture_s: float = 0.0     # >0: bounded profiler capture on fire
    slo_ttft_target_ms: Optional[Dict[str, float]] = None  # per-class TTFT
    #                                targets feeding the burn monitor; a
    #                                class absent from the dict counts every
    #                                prefill as a good outcome
    # ---- compile observatory (ISSUE 12) ----
    observatory: bool = False      # register every unified-step executable
    #                                (signature fingerprint + AOT cost/memory
    #                                analyses) with the process-global
    #                                CompileObservatory; off = one predicate
    # ---- rolling weight deployment (ISSUE 16) ----
    weight_version: str = "v0"     # version id of the params the engine
    #                                starts on; replace_params() advances it
    # ---- speculative decoding (ISSUE 17) ----
    spec_k: int = 4                # draft tokens proposed per verify window
    #                                (only meaningful when the engine is
    #                                built with a draft_model); the verify
    #                                window spans spec_k + 1 of the unified
    #                                step's prefill_chunk columns, so
    #                                spec_k + 1 <= prefill_chunk is enforced
    #                                at engine construction when a draft
    #                                model is present
    # ---- per-slot sampling + constrained decoding (ISSUE 18) ----
    max_grammars: int = 8          # distinct compiled grammars the fixed-
    #                                shape DFA bank holds; the bank's shape
    #                                is part of the unified step's traced
    #                                signature, so it is pre-allocated — a
    #                                request needing a 9th grammar rejects
    #                                instead of recompiling the step
    max_dfa_states: int = 128      # per-grammar token-DFA state ceiling
    #                                (same fixed-shape reasoning)
    # ---- tiered KV cache (ISSUE 19) ----
    host_kv_bytes: int = 0         # host-RAM spill tier byte budget: > 0
    #                                arms a bounded LRU HostKVPool that
    #                                captures refcount-0 prefix pages on
    #                                pressure eviction and re-onboards them
    #                                at admission instead of re-prefilling;
    #                                0 = device-only caching (prior behavior)
    # ---- multi-LoRA serving (ISSUE 20) ----
    max_adapters: int = 0          # > 0 arms the AdapterBank: that many
    #                                hot-swappable LoRA adapter rows ride
    #                                the ONE unified step through a
    #                                per-slot adapter_idx lane (bank row 0
    #                                is the all-zero base pass-through, so
    #                                adapter=None streams stay
    #                                bit-identical); 0 = no bank and the
    #                                step's operands/executable are
    #                                byte-identical to the pre-LoRA engine
    lora_rank: int = 8             # bank row rank — part of the step's
    #                                traced operand shapes, so fixed at
    #                                construction; loading an adapter of
    #                                any other rank is a typed refusal,
    #                                never a recompile
    lora_alpha: Optional[float] = None  # default scaling numerator for
    #                                rows loaded without an explicit
    #                                alpha (None = 2 * lora_rank)

    def __post_init__(self):
        if self.num_slots < 1:
            raise ValueError(f"num_slots must be >= 1, got {self.num_slots}")
        if self.max_queue_depth < 1:
            raise ValueError(
                f"max_queue_depth must be >= 1, got {self.max_queue_depth}")
        if self.max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {self.max_new_tokens}")
        if self.prefill_chunk < 1:
            raise ValueError(
                f"prefill_chunk must be >= 1, got {self.prefill_chunk}")
        if self.default_slo not in SLO_CLASSES:
            raise ValueError(
                f"default_slo must be one of {SLO_CLASSES}, got "
                f"{self.default_slo!r}")
        if self.brownout_max_new_tokens < 1:
            raise ValueError(
                f"brownout_max_new_tokens must be >= 1, got "
                f"{self.brownout_max_new_tokens}")
        if self.dispatch_retries < 0:
            raise ValueError("retry counts must be >= 0")
        if not self.default_tenant:
            raise ValueError("default_tenant must be a non-empty string")
        if (self.tenant_max_inflight_tokens is not None
                and self.tenant_max_inflight_tokens < 1):
            raise ValueError(
                f"tenant_max_inflight_tokens must be >= 1, got "
                f"{self.tenant_max_inflight_tokens}")
        if self.breaker_threshold < 1:
            raise ValueError(
                f"breaker_threshold must be >= 1, got "
                f"{self.breaker_threshold}")
        if self.trace_buffer < 1:
            raise ValueError(
                f"trace_buffer must be >= 1, got {self.trace_buffer}")
        if self.spec_k < 1:
            raise ValueError(f"spec_k must be >= 1, got {self.spec_k}")
        if self.max_grammars < 1:
            raise ValueError(
                f"max_grammars must be >= 1, got {self.max_grammars}")
        if self.max_dfa_states < 1:
            raise ValueError(
                f"max_dfa_states must be >= 1, got {self.max_dfa_states}")
        if self.host_kv_bytes < 0:
            raise ValueError(
                f"host_kv_bytes must be >= 0, got {self.host_kv_bytes}")
        if self.max_adapters < 0:
            raise ValueError(
                f"max_adapters must be >= 0, got {self.max_adapters}")
        if self.lora_rank < 1:
            raise ValueError(
                f"lora_rank must be >= 1, got {self.lora_rank}")
        if self.lora_alpha is not None and self.lora_alpha <= 0:
            raise ValueError(
                f"lora_alpha must be > 0, got {self.lora_alpha}")
        if not 0.0 < self.slo_burn_budget <= 1.0:
            raise ValueError(
                f"slo_burn_budget must be in (0, 1], got "
                f"{self.slo_burn_budget}")
        if self.slo_burn_threshold <= 0:
            raise ValueError(
                f"slo_burn_threshold must be > 0, got "
                f"{self.slo_burn_threshold}")
        if not (0.0 < self.slo_burn_fast_window_s
                <= self.slo_burn_slow_window_s):
            raise ValueError(
                "slo_burn windows must satisfy 0 < fast <= slow, got "
                f"fast={self.slo_burn_fast_window_s} "
                f"slow={self.slo_burn_slow_window_s}")
        if self.slo_burn_min_events < 1:
            raise ValueError(
                f"slo_burn_min_events must be >= 1, got "
                f"{self.slo_burn_min_events}")
        if self.slo_ttft_target_ms is not None:
            for cls, target in self.slo_ttft_target_ms.items():
                if cls not in SLO_CLASSES:
                    raise ValueError(
                        f"slo_ttft_target_ms keys must be SLO classes "
                        f"{SLO_CLASSES}, got {cls!r}")
                if target <= 0:
                    raise ValueError(
                        f"slo_ttft_target_ms[{cls!r}] must be > 0, got "
                        f"{target}")


class GenerationHandle:
    """Per-request streaming view + completion future.

    Tokens stream into `tokens_so_far()` as decode iterations retire them;
    `future` resolves with the full np.int32 array on EOS/max-tokens, or
    with DeadlineExceededError / RejectedError / DispatchFailedError on
    eviction (partial tokens stay readable off the handle either way)."""

    def __init__(self, prompt_len: int, max_new_tokens: int,
                 slo: str = "batch"):
        self.prompt_len = prompt_len
        self.max_new_tokens = max_new_tokens
        self.slo = slo
        self.future: Future = Future()
        self.ttft_ms: Optional[float] = None
        self.rid: Optional[str] = None       # request id (always assigned)
        self.trace: Optional[RequestTrace] = None   # when tracing opted in
        self._lock = threading.Lock()
        self._tokens: List[int] = []
        self._logprobs: List[Optional[float]] = []

    def _append(self, tok: int, lp: Optional[float] = None):
        with self._lock:
            self._tokens.append(int(tok))
            self._logprobs.append(None if lp is None else float(lp))

    def tokens_so_far(self) -> List[int]:
        with self._lock:
            return list(self._tokens)

    def logprobs_so_far(self) -> List[Optional[float]]:
        """Per-emitted-token log-probabilities (ISSUE 19): the model's raw
        (pre-temperature) log-softmax at each selected token, streamed in
        lockstep with `tokens_so_far()`. Entries are None when the request
        did not opt in via submit(logprobs=True)."""
        with self._lock:
            return list(self._logprobs)

    def result(self, timeout: Optional[float] = None) -> np.ndarray:
        return self.future.result(timeout)

    def timeline(self) -> Optional[dict]:
        """Structured timeline dict when the request was traced (complete
        once the future has resolved), else None."""
        return self.trace.to_dict() if self.trace is not None else None


class _GenRequest:
    __slots__ = ("prompt", "max_new_tokens", "eos_token_id", "arrival",
                 "deadline", "handle", "slot", "emitted", "last_tok",
                 "slo", "submit_idx", "cost", "chunk_off", "tenant",
                 "attached_pages", "rid", "trace", "draft_slot",
                 "spec_off", "draft_attached", "sampling",
                 "sample_offset", "gid", "dfa_state0",
                 "want_logprobs", "kv_row", "adapter")

    def __init__(self, prompt, max_new_tokens, eos_token_id, arrival,
                 deadline, slo, submit_idx, tenant="default"):
        self.prompt = prompt              # np.int32 [S]
        self.max_new_tokens = max_new_tokens
        self.eos_token_id = eos_token_id
        self.arrival = arrival            # clock seconds
        self.deadline = deadline          # absolute clock seconds or None
        self.slo = slo                    # SLO class name
        self.submit_idx = submit_idx      # lifetime admission index (fault
        #                                   injection keys poison on this)
        self.cost = len(prompt) + max_new_tokens  # token-budget estimate
        self.handle = GenerationHandle(len(prompt), max_new_tokens, slo)
        self.slot: Optional[int] = None
        self.emitted: List[int] = []
        self.last_tok: int = 0
        self.chunk_off: int = 0           # prompt tokens already prefilled;
        #                                   < len(prompt) means the request
        #                                   is still in chunked prefill —
        #                                   starts at attach_len on a prefix
        #                                   cache hit (those tokens' KV is
        #                                   attached/COW'd, never recomputed)
        self.tenant = tenant
        self.attached_pages: List[int] = []   # shared pages this request
        #                                       reads (refcounted in pool)
        self.rid: Optional[str] = None        # request id (always assigned)
        self.trace: Optional[RequestTrace] = None   # None unless the
        #                                       request opted into tracing —
        #                                       every hot-path hook guards on
        #                                       this ONE predicate
        # speculative decoding (ISSUE 17)
        self.draft_slot: Optional[int] = None  # row in the DRAFT pool; None
        #                                       when spec is off or the draft
        #                                       pool had no row to give
        self.spec_off: bool = False           # draft quarantined for THIS
        #                                       request (poisoned draft
        #                                       dispatch): stream continues
        #                                       as plain decode
        self.draft_attached: List[int] = []   # shared draft-pool pages this
        #                                       request attached (for the
        #                                       draft cache insert)
        # per-slot sampling + constrained decoding (ISSUE 18)
        self.sampling: Optional[SamplingParams] = None  # None == GREEDY
        self.sample_offset: int = 0           # stream index of this
        #                                       request's FIRST emitted
        #                                       token — 0 normally, the
        #                                       already-emitted count on a
        #                                       failover re-prefill (the
        #                                       RNG-lane counter restore)
        self.gid: int = 0                     # interned grammar id in the
        #                                       engine's DFA bank; 0 = the
        #                                       pass-through row
        self.dfa_state0: int = 0              # DFA state at first emission
        #                                       (walked over the resumed
        #                                       prompt tail on failover)
        # tiered KV + disaggregation (ISSUE 19)
        self.want_logprobs: bool = False      # surface per-token logprobs
        #                                       on the handle
        self.kv_row: Optional[dict] = None    # pre-computed KV for the
        #                                       prompt's first `length`
        #                                       tokens (a prefill→decode
        #                                       handoff import); admission
        #                                       uploads it instead of
        #                                       re-prefilling
        # multi-LoRA serving (ISSUE 20)
        self.adapter: Optional[str] = None    # AdapterBank id whose
        #                                       low-rank delta this stream
        #                                       decodes under; None = base
        #                                       model (bank row 0)


class LLMEngine:
    """submit() a prompt, get a GenerationHandle streaming greedy tokens.

    The model must implement the cached-decode contract
    (`init_cache` / `forward_with_cache`, e.g. GPTForCausalLM /
    LlamaForCausalLM); it is switched to eval mode and its functional
    state captured once at construction.

    `fault_plan` (None → the PDTPU_FAULTS-driven global plan) injects
    deterministic dispatch faults for the fault-matrix tests; `on_break`
    fires once when the circuit breaker opens (the server wires it to a
    drain on its own thread).
    """

    def __init__(self, model, config: Optional[LLMEngineConfig] = None,
                 clock: Optional[Clock] = None,
                 metrics: Optional[LLMMetrics] = None,
                 fault_plan=None,
                 on_break: Optional[Callable[[], None]] = None,
                 draft_model=None):
        from ...models.generation import make_decoder_fns, make_verify_fn
        self.model = model
        model.eval()
        self.config = config or LLMEngineConfig()
        self.clock = clock or MonotonicClock()
        self.metrics = metrics or LLMMetrics()
        self.params, self._prefill_fn, self._decode_fn = \
            make_decoder_fns(model)
        _, self._verify_fn = make_verify_fn(model)
        # per-slot sampling + grammar bank (ISSUE 18): sized off the
        # model's vocab — the DFA bank's last axis is a legal-token mask
        vocab_size = int(getattr(getattr(model, "config", None),
                                 "vocab_size", 0))
        if vocab_size < 1:
            raise ValueError(
                "model must expose config.vocab_size for the sampling "
                "subsystem's grammar mask")
        self.sampling_table = SlotSamplingTable(
            self.config.num_slots, vocab_size,
            max_grammars=self.config.max_grammars,
            max_dfa_states=self.config.max_dfa_states)
        # multi-LoRA bank (ISSUE 20): K stacked adapter trees + a per-slot
        # adapter_idx lane appended to the unified step's operands. None
        # unless armed, so an unarmed engine's step signature — and its
        # compiled executable — stays byte-identical to the pre-LoRA one.
        self.adapter_bank: Optional[AdapterBank] = None
        if self.config.max_adapters > 0:
            self.adapter_bank = AdapterBank(
                model, self.config.max_adapters, self.config.lora_rank,
                self.config.num_slots,
                default_alpha=self.config.lora_alpha)
        if not self.config.weight_version:
            raise ValueError("weight_version must be a non-empty string")
        self.weight_version = self.config.weight_version
        # pad_tokens=prefill_chunk: the fixed-width KV stripe written at a
        # row's position needs chunk-width scratch past the last
        # addressable block so near-capacity writes never clamp back onto
        # valid KV (block tables never point into the pad region)
        self.pool = SlotPagedKVPool(
            model.init_cache, self.config.num_slots, self.config.block_len,
            self.config.n_blocks, dtype=self.config.cache_dtype,
            pad_tokens=self.config.prefill_chunk)
        # host-RAM spill tier (ISSUE 19): a byte-budgeted LRU the prefix
        # cache spills refcount-0 pages into on pressure eviction; the
        # admission path re-onboards covered blocks instead of
        # re-prefilling them
        self.host_kv: Optional[HostKVPool] = (
            HostKVPool(self.config.host_kv_bytes, self.config.block_len)
            if self.config.host_kv_bytes > 0 else None)
        self._spill_booked = 0.0     # spill_seconds already booked to the
        #                              ledger's kv_spill phase (delta
        #                              accounting per pump)
        # radix prefix cache (ISSUE 8): wires itself as the pool's
        # on_pressure hook so pinned rows free up under allocation pressure
        self.prefix_cache: Optional[PrefixCache] = (
            PrefixCache(self.pool, host_pool=self.host_kv,
                        clock=self.clock.now)
            if self.config.enable_prefix_cache
            else None)
        # ---- speculative decoding (ISSUE 17) ----
        # a draft model arms spec mode: per decode pump a SINGLE draft
        # dispatch (an on-device lax.scan of spec_k+1 width-1 steps over
        # the draft's OWN slot-paged pool) proposes K tokens per eligible
        # row, and the target's unified step verifies all K+1 positions in
        # one dispatch; greedy acceptance = longest matching prefix + the
        # target's corrective token, so output is bit-identical to plain
        # decode. Rejected target columns need no rollback (committing
        # only the accepted length leaves them as the garbage-past-adv the
        # pool invariant already covers); the DRAFT pool rolls back via
        # rewind_length.
        self.draft_model = draft_model
        self.draft_pool: Optional[SlotPagedKVPool] = None
        self.draft_prefix_cache: Optional[PrefixCache] = None
        self._draft_params = None
        self._draft_verify_fn = None
        self._draft_prefill_fn = None
        self._draft_step_jit = None     # chunk-wide draft catch-up
        self._draft_propose_jit = None  # the single-dispatch K-token scan
        self._spec_disabled = False     # engine-wide draft kill switch
        self._draft_failstreak = 0      # consecutive unattributed draft
        #                                 dispatch failures (exempt from the
        #                                 engine breaker by design)
        self.spec_windows = 0           # lifetime verify windows committed
        self.spec_drafted = 0           # lifetime draft tokens verified
        self.spec_accepted = 0          # lifetime draft tokens accepted
        # tiered KV + disaggregation (ISSUE 19): lifetime counters the
        # bench's tiered phase and the tests read directly
        self.host_onboard_tokens = 0    # prompt tokens onboarded from the
        #                                 host spill tier (skipped prefill)
        self.kv_import_tokens = 0       # prompt tokens imported via a
        #                                 prefill→decode handoff kv_row
        if draft_model is not None:
            if self.config.spec_k + 1 > self.config.prefill_chunk:
                raise ValueError(
                    f"spec_k + 1 ({self.config.spec_k + 1}) exceeds the "
                    f"unified step width prefill_chunk "
                    f"({self.config.prefill_chunk}): the verify window "
                    "must fit one dispatch")
            draft_model.eval()
            self._draft_params, self._draft_verify_fn = \
                make_verify_fn(draft_model)
            # the propose scan samples its proposals on the SAME per-
            # request lanes as the target verify (seeded-replay
            # acceptance), so it needs raw draft logits, not argmaxes
            _, self._draft_prefill_fn, _ = make_decoder_fns(draft_model)
            self.draft_pool = SlotPagedKVPool(
                draft_model.init_cache, self.config.num_slots,
                self.config.block_len, self.config.n_blocks,
                dtype=self.config.cache_dtype,
                pad_tokens=self.config.prefill_chunk)
            if self.config.enable_prefix_cache:
                self.draft_prefix_cache = PrefixCache(self.draft_pool,
                                                      name="draft")
        self.metrics.set_slots(0, self.pool.num_slots)
        self._queues: Dict[str, deque] = {c: deque() for c in SLO_CLASSES}
        self._active: Dict[int, _GenRequest] = {}   # slot -> request
        self._cond = threading.Condition()
        self._draining = False
        self._stopped = False
        self._brownout = False
        self._thread: Optional[threading.Thread] = None
        self._step_jit = None        # the ONE unified step executable
        self.decode_iterations = 0   # lifetime steps carrying >=1 decode row
        self.prefill_dispatches = 0  # lifetime steps carrying ONLY prefill
        #                              rows — near-zero under mixed load,
        #                              which is what proves the per-bucket
        #                              prefill executable zoo is gone
        self.prefill_tokens = 0      # lifetime prompt tokens actually
        #                              prefilled (sum of committed chunk
        #                              widths) — the prefix-cache acceptance
        #                              observable: N shared-prefix requests
        #                              should pay ~1 prompt's worth
        self._submit_idx = 0         # lifetime admissions (poison keying)
        self._dispatch_idx = 0       # lifetime dispatch attempts (fault
        #                              clauses key on this index)
        # finished request timelines for /debug/requests/<rid> (ISSUE 9)
        self.timelines = TimelineStore(self.config.trace_buffer)
        # serving economics (ISSUE 11): both None unless armed, so every
        # hot-path hook costs exactly one predicate when disabled
        self.ledger = None
        self.burn = None
        if self.config.economics:
            from ...obs.serving_ledger import ServingLedger, SLOBurnMonitor
            self.ledger = ServingLedger(clock=self.clock.now)
            self.burn = SLOBurnMonitor(
                clock=self.clock.now,
                budget=self.config.slo_burn_budget,
                threshold=self.config.slo_burn_threshold,
                fast_window_s=self.config.slo_burn_fast_window_s,
                slow_window_s=self.config.slo_burn_slow_window_s,
                min_events=self.config.slo_burn_min_events,
                capture_s=self.config.slo_burn_capture_s)
        self.metrics.ledger = self.ledger
        self.metrics.burn = self.burn
        # compile observatory (ISSUE 12): None unless armed
        self.observatory = None
        if self.config.observatory:
            from ...obs.compile_observatory import compile_observatory
            self.observatory = compile_observatory().enable()
        if fault_plan is None:
            from ...utils.fault_injection import global_plan
            fault_plan = global_plan()
        self._fault_plan = fault_plan
        self.on_break = on_break
        self.supervisor = EngineSupervisor(
            dispatch_timeout_s=self.config.dispatch_timeout_s,
            breaker_threshold=self.config.breaker_threshold,
            on_trip=self._on_breaker_trip, name="llm")

    # ---- observability (ISSUE 9) ----
    def _conclude(self, req: _GenRequest, outcome: str,
                  now: Optional[float] = None):
        """Finalize a traced request's timeline on ANY terminal path
        (complete / evict / quarantine / shed / shutdown): close the
        phase spans, store the timeline for /debug/requests/<rid>, and
        emit the request's spans onto the chrome trace. One predicate
        when the request was not traced."""
        if req.trace is None:
            return
        tr = req.trace
        tr.finish(self.clock.now() if now is None else now, outcome)
        self.timelines.put(tr.rid, tr.to_dict())
        tr.emit_chrome()

    def _record_reject(self, reason: str, rid: Optional[str] = None,
                       tenant: Optional[str] = None):
        flight_recorder().record("reject", engine="llm", reason=reason,
                                 rid=rid, tenant=tenant)

    # ---- the one jitted executable ----
    def _step(self):
        """Unified mixed-row step: `toks [N, C]` carries each slot's chunk
        (prompt tokens for prefilling rows, [last_tok, d1..dk, 0...] for
        decoding rows — k > 0 when a draft window rides the row, ISSUE
        17 — zeros for free slots), `pos [N]` the row's committed length
        (= write offset), `adv [N]` how many of the C columns are real
        (chunk size / 1+k / 0). KV stripes are written at `pos` (garbage
        columns past `adv` land in cols the row's validity never reaches
        or in the slab's pad region, and are overwritten before any
        seq_len admits them — which is also what makes rejected draft
        positions rollback-free: only the accepted length is ever
        committed); ragged paged attention masks every row to
        `col <= pos+t` and `col < pos+adv`. The step returns the
        PER-POSITION selected tokens `[N, C]` plus each row's advanced
        grammar-DFA state `[N]` (ISSUE 18): selection is the vectorized
        per-row `_select_token` path — masked argmax for greedy rows
        (bit-identical to the old make_verify_fn step on unconstrained
        rows), seeded temperature/top-k/top-p draws on per-request
        `(seed, stream_index)` threefry lanes for sampling rows, with
        the grammar bank's legal-token mask applied BEFORE the filters.
        Column `adv-1` is the classic next token for prefill /
        plain-decode rows; columns 0..k score a spec row's whole verify
        window in this one dispatch (free rows emit harmless selections
        of fully-masked rows). All sampling inputs are traced [N]
        arrays + the fixed-shape DFA bank, so the mix of request params
        never changes the executable."""
        if self._step_jit is None:
            block_len = self.pool.block_len
            pages_per_row = self.pool.n_blocks
            prefill = self._prefill_fn

            def step(params, toks, pos, adv, table, slabs, temp, topk,
                     topp, samp, seed, ctr, dstate, gid, bank,
                     adapters=None):
                # `adapters` (ISSUE 20) is the AdapterBank's stacked LoRA
                # operand — (per-layer A/B banks, per-slot adapter_idx,
                # per-row scale). An unarmed engine never passes it, so
                # its traced signature is unchanged; an armed engine
                # passes a fixed-structure pytree whose leaf VALUES churn
                # as adapters load/swap — zero recompiles either way.
                seq_lens = (pos + adv).astype(jnp.int32)
                paged = (table, seq_lens, block_len, pages_per_row)
                logits, new_slabs = prefill(params, toks, slabs, pos,
                                            paged=paged, adapters=adapters)
                sel, new_state = select_tokens(
                    logits, adv, temp, topk, topp, samp, seed, ctr,
                    dstate, gid, bank)
                # per-token logprobs (ISSUE 19): the RAW model
                # distribution's log-softmax at each selected token —
                # pre-temperature/top-k/top-p, so it is a property of the
                # stream, not of the sampling filters. Computed
                # unconditionally (selection above is untouched, so token
                # streams stay bit-identical whether or not a request
                # reads them); float32 keeps the reduction stable under
                # low-precision cache dtypes.
                lp = jnp.take_along_axis(
                    jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1),
                    sel[..., None].astype(jnp.int32), axis=-1)[..., 0]
                return sel, lp, new_state, new_slabs

            self._step_jit = jax.jit(step)
        return self._step_jit

    def _sampling_args_locked(self, ctr):
        """The unified step's per-slot sampling operands: the live table
        rows plus this dispatch's stream-index base `ctr [N]` and the
        cached device DFA bank. Table arrays ride the device-args cache
        (invalidated on bind/clear/DFA commit) so the steady-state cost
        here is one [N] ctr upload."""
        tab = self.sampling_table
        temp, topk, topp, samp, seed, dstate, gid = tab.device_args()
        return (temp, topk, topp, samp, seed, jnp.asarray(ctr),
                dstate, gid, tab.device_bank())

    def _adapter_args_locked(self):
        """The unified step's adapter operand as a (possibly empty) args
        tail (ISSUE 20): () when no bank is armed — the step is then
        called with its pre-LoRA 15-arg signature — else the bank's
        cached device views, rebuilt only after a row load/swap or a
        slot bind (same invalidation idiom as the sampling table)."""
        if self.adapter_bank is None:
            return ()
        return (self.adapter_bank.device_args(),)

    def _draft_step(self):
        """Draft-pool analogue of `_step` (ISSUE 17): the chunk-wide
        catch-up executable that replays already-committed target tokens
        (prompt suffixes and corrective tokens) into the draft pool so
        its KV tracks the true stream. Output tokens are discarded — only
        the written KV stripes matter."""
        if self._draft_step_jit is None:
            block_len = self.draft_pool.block_len
            pages_per_row = self.draft_pool.n_blocks
            vfy = self._draft_verify_fn

            def step(params, toks, pos, adv, table, slabs):
                seq_lens = (pos + adv).astype(jnp.int32)
                paged = (table, seq_lens, block_len, pages_per_row)
                return vfy(params, toks, slabs, pos, paged=paged)

            self._draft_step_jit = jax.jit(step)
        return self._draft_step_jit

    def _draft_propose(self):
        """The single-dispatch draft proposal (ISSUE 17): an on-device
        `lax.scan` of spec_k+1 sequential width-1 draft steps. Step 0
        feeds each proposing row's last committed token at `pos`; each
        later step feeds the previous step's argmax, so the scan emits
        d1..dK autoregressively — ONE dispatch, not K. The final (K+1th)
        iteration feeds dK purely for its KV write: after an all-accept
        window the draft pool is then already caught up to the target's
        new committed length, so steady-state spec pays exactly two
        dispatches (propose + verify) per K+1 emitted tokens — that
        dispatch-count collapse is the batch-1 latency win. Rows with
        act=0 park at the slab pad position (same convention as free rows
        in `_build_rows_locked`) and advance nothing.

        Sampled rows (ISSUE 18): scan step j selects its proposal with
        `select_next` on the SAME per-request lane the target verify
        will use for stream index `ctr + j` — when draft and target
        logits agree the proposal IS the target's coin-fixed draw, so
        seeded-replay acceptance keeps the spec speedup for sampled
        requests. Greedy rows still argmax. Grammar-constrained rows
        never reach this scan (spec-ineligible)."""
        if self._draft_propose_jit is None:
            block_len = self.draft_pool.block_len
            pages_per_row = self.draft_pool.n_blocks
            K = self.config.spec_k
            dprefill = self._draft_prefill_fn

            def propose(params, tok0, pos, act, table, slabs, temp,
                        topk, topp, samp, seed, ctr):
                def body(carry, j):
                    tok, off, slabs_c = carry
                    seq_lens = (pos + off + act).astype(jnp.int32)
                    paged = (table, seq_lens, block_len, pages_per_row)
                    lg, slabs_c = dprefill(params, tok[:, None], slabs_c,
                                           pos + off, paged=paged)
                    nxt = select_next(lg[:, 0], temp, topk, topp, samp,
                                      seed, ctr + j)
                    return (nxt, off + act, slabs_c), nxt

                (_, _, slabs), drafts = jax.lax.scan(
                    body, (tok0, jnp.zeros_like(pos), slabs),
                    jnp.arange(K + 1, dtype=jnp.int32))
                # drafts [K+1, N]: rows 0..K-1 are d1..dK; row K is the
                # throwaway catch-up step (KV write only)
                return jnp.transpose(drafts[:K]), slabs

            self._draft_propose_jit = jax.jit(propose)
        return self._draft_propose_jit

    # ---- supervised dispatch ----
    def _run_dispatch(self, kinds, fn, args, exempt: bool = False):
        """One supervised jitted dispatch attempt. Every attempt — retries
        and blame probes included — consumes a dispatch index, which is
        what deterministic fault clauses key on. `kinds` is the ordered
        (kind, request_ids) pairs riding this dispatch — prefill rows
        announce first, then decode rows, both at the SAME index (a
        dispatch_raise clause fires once, at the first announcement;
        poison_request clauses match their kind; draft dispatches
        announce kind "draft", which is what lets a fault plan poison
        ONLY the draft). `exempt=True` marks a breaker-exempt dispatch
        (ISSUE 17: draft proposals are an optimization, so their failures
        must never charge the target engine's circuit breaker or
        dispatch-failure stats)."""
        idx = self._dispatch_idx
        self._dispatch_idx += 1
        plan = self._fault_plan
        label = "+".join(k for k, _ in kinds) or "step"

        def guarded():
            if plan is not None:
                for kind, rids in kinds:
                    plan.maybe_dispatch_fault(idx, kind=kind,
                                              request_ids=rids)
            return fn(*args)

        return self.supervisor.run(guarded, label=label, exempt=exempt)

    def _free_row_locked(self, req: "_GenRequest", slot: int):
        """Free a request's target-pool row AND its draft-pool row (ISSUE
        17) — every terminal path (finish, evict, quarantine, evacuate,
        shutdown) must release both or the draft pool's slot ledger
        diverges from the target's."""
        self.pool.free(slot)
        self.sampling_table.clear(slot)
        if self.adapter_bank is not None:
            self.adapter_bank.clear_slot(slot)
        if self.draft_pool is not None and req.draft_slot is not None:
            if self.draft_pool.active[req.draft_slot]:
                self.draft_pool.free(req.draft_slot)
            req.draft_slot = None

    # ---- lifecycle ----
    def start(self) -> "LLMEngine":
        """Run the scheduler on a background thread (production mode). Not
        needed under a SimClock — the harness calls pump() itself."""
        if isinstance(self.clock, SimClock):
            raise RuntimeError(
                "LLMEngine.start() with a SimClock would busy-spin: drive "
                "pump() from the simulation harness instead")
        with self._cond:
            if self._stopped:
                raise RuntimeError("engine already stopped")
            if self._thread is not None:
                return self
            self._thread = threading.Thread(
                target=self._scheduler_main, daemon=True,
                name="pdtpu-llm-scheduler")
            self._thread.start()
        return self

    def stop(self, drain: bool = True, timeout: Optional[float] = None):
        """Graceful drain: stop admissions (submit -> RejectedError), then
        finish EVERY admitted sequence — queued requests still get
        prefilled and decoded to completion — before stopping the
        scheduler. With drain=False, queued and decoding requests fail
        with RejectedError instead. A drain that cannot finish inside
        `timeout` (default config.drain_timeout_s) fails the stragglers
        with RejectedError(reason="drain_timeout") rather than joining
        forever on futures that can never resolve."""
        with self._cond:
            if self._stopped:
                return
            self._draining = True
            flight_recorder().record(
                "drain_begin", engine="llm", drain=bool(drain),
                queued=self._queue_len_locked(), active=len(self._active))
            if not drain:
                for q in self._queues.values():
                    while q:
                        req = q.popleft()
                        self._conclude(req, "rejected:shutdown")
                        req.handle.future.set_exception(
                            RejectedError("engine shut down before prefill",
                                          reason="shutdown"))
                        self.metrics.on_reject("shutdown")
                for slot, req in list(self._active.items()):
                    self._conclude(req, "rejected:shutdown")
                    req.handle.future.set_exception(
                        RejectedError("engine shut down mid-decode",
                                      reason="shutdown"))
                    self.metrics.on_reject("shutdown")
                    self._free_row_locked(req, slot)
                self._active.clear()
                self.metrics.set_queue_depth(0)
                self.metrics.set_slots(0, self.pool.num_slots)
            self._cond.notify_all()
            thread = self._thread
        if thread is not None:
            join_s = (timeout if timeout is not None
                      else self.config.drain_timeout_s)
            thread.join(join_s)
            if thread.is_alive():
                _log.warning(
                    "llm drain did not complete within %.1fs; failing "
                    "sequences still in flight", join_s)
        else:
            # threadless (sim) mode: run the scheduler inline to
            # completion, with a no-progress guard so a pump that can no
            # longer advance anything (e.g. breaker open mid-drain) falls
            # through to the stranded-future cleanup instead of spinning
            prev = None
            while True:
                with self._cond:
                    if not (self._queue_len_locked() or self._active):
                        break
                self.pump()
                state = (self._queue_len_locked(), len(self._active),
                         self._dispatch_idx)
                if state == prev:
                    break
                prev = state
        with self._cond:
            stranded = 0
            for q in self._queues.values():
                while q:
                    req = q.popleft()
                    self._conclude(req, "rejected:drain_timeout")
                    req.handle.future.set_exception(RejectedError(
                        "engine drain timed out before prefill",
                        reason="drain_timeout"))
                    self.metrics.on_reject("drain_timeout")
                    stranded += 1
            for slot, req in list(self._active.items()):
                self._conclude(req, "rejected:drain_timeout")
                req.handle.future.set_exception(RejectedError(
                    "engine drain timed out mid-decode",
                    reason="drain_timeout"))
                self.metrics.on_reject("drain_timeout")
                self._free_row_locked(req, slot)
                stranded += 1
            self._active.clear()
            if stranded:
                self.metrics.set_queue_depth(0)
                self.metrics.set_slots(0, self.pool.num_slots)
            self._stopped = True
            self._cond.notify_all()
        flight_recorder().record("drain_end", engine="llm",
                                 stranded=stranded)

    @property
    def draining(self) -> bool:
        return self._draining

    @property
    def broken(self) -> bool:
        """Circuit breaker open: repeated engine-level dispatch failures;
        admissions reject and /healthz reports 503."""
        return self.supervisor.open

    def _on_breaker_trip(self):
        """Repeated engine-level failures: admissions stop (submit ->
        "circuit_open"), queued requests fail now — their dispatches would
        only fail again — and the front end is notified so it can flip
        /healthz and drain on its own thread."""
        flushed = 0
        with self._cond:
            for q in self._queues.values():
                while q:
                    req = q.popleft()
                    self._conclude(req, "rejected:circuit_open")
                    req.handle.future.set_exception(RejectedError(
                        "engine circuit breaker open after repeated "
                        "dispatch failures", reason="circuit_open"))
                    self.metrics.on_reject("circuit_open")
                    flushed += 1
            self.metrics.set_queue_depth(0)
            self._cond.notify_all()
        flight_recorder().record("queue_flushed", engine="llm",
                                 reason="circuit_open", n=flushed)
        self.metrics.set_circuit_open(True)
        if self.on_break is not None:
            try:
                self.on_break()
            except Exception:
                _log.exception("llm on_break callback failed")

    # ---- rolling weight deployment (ISSUE 16) ----
    def evacuate(self, reason: str = "deploy_drain") -> int:
        """Deploy-drain eviction: fail every queued AND active request
        with a typed RejectedError(reason=...) and free their KV rows,
        WITHOUT entering the terminal stop() path — the engine keeps
        serving afterwards. The DeploymentController calls this only
        after the router has already re-queued the same streams for
        failover re-prefill on a survivor, so nothing observable is
        dropped: these engine-side rows are orphans whose handles are
        detached. Returns rows+requests evicted."""
        n = 0
        with self._cond:
            for q in self._queues.values():
                while q:
                    req = q.popleft()
                    self._conclude(req, f"rejected:{reason}")
                    if not req.handle.future.done():
                        req.handle.future.set_exception(RejectedError(
                            f"engine evacuated ({reason}) before prefill",
                            reason=reason))
                    self.metrics.on_reject(reason)
                    n += 1
            for slot, req in list(self._active.items()):
                self._conclude(req, f"rejected:{reason}")
                if not req.handle.future.done():
                    req.handle.future.set_exception(RejectedError(
                        f"engine evacuated ({reason}) mid-decode",
                        reason=reason))
                self.metrics.on_reject(reason)
                self._free_row_locked(req, slot)
                n += 1
            self._active.clear()
            self.metrics.set_queue_depth(0)
            self.metrics.set_slots(self.pool.active_slots(),
                                   self.pool.num_slots)
            self._cond.notify_all()
        if n:
            flight_recorder().record("deploy_evacuate", engine="llm",
                                     reason=reason, n=n)
        return n

    def export_sampling_lanes(self, slots) -> dict:
        """Serialize the sampling-lane state of active `slots` — the
        companion payload to `kv_pool.export_rows` (ISSUE 18): per slot,
        the request seed, the NEXT RNG stream index, the sampling params,
        and (for constrained rows) the grammar key plus current DFA
        state. A peer that imports the KV rows and rebinds these lanes
        (seed → `SamplingParams`, next_index → `sample_offset`,
        grammar_key → recompile + DFA fast-forward) continues the stream
        bit-identically to the uninterrupted run — the same contract the
        router's failover re-prefill exercises without KV transfer."""
        out: Dict[int, dict] = {}
        with self._cond:
            tab = self.sampling_table
            for slot in slots:
                slot = int(slot)
                req = self._active.get(slot)
                if req is None:
                    raise ValueError(f"slot {slot} has no active request")
                sp = req.sampling or GREEDY
                out[slot] = {
                    "seed": None if sp.seed is None else int(sp.seed),
                    "next_index": req.sample_offset + len(req.emitted),
                    "temperature": float(sp.temperature),
                    "top_k": int(sp.top_k),
                    "top_p": float(sp.top_p),
                    "grammar_key": (sp.grammar_key()
                                    if sp.constrained else None),
                    "dfa_state": int(tab.dfa_state[slot]),
                }
        return out

    def export_stream(self, rid: str) -> dict:
        """Export ONE active stream for a prefill→decode handoff (ISSUE
        19) and release its row — atomically, under a single lock
        acquisition, so no decode step can advance the stream between the
        snapshot and the release (the payload's emitted/KV/lane views are
        mutually consistent by construction).

        Requires the stream to have completed prefill (it has emitted at
        least one token): at that point the row's KV covers exactly
        ``len(prompt) + len(emitted) - 1`` tokens — the last emitted
        token's KV is written by the step that consumes it — so a peer
        that resubmits ``prompt + emitted`` with this payload's `kv_row`
        pays a ONE-token prefill and continues bit-identically
        (chunk-invariance + the bitwise export/import round trip).

        The engine-side handle is detached: its future is left unresolved
        (the receiving replica's handle carries the stream forward — the
        same convention as failover-abandoned handles) and the row is
        freed for new work. Raises ValueError when the rid is not active
        or still mid-prefill."""
        with self._cond:
            found = None
            for slot, req in self._active.items():
                if req.rid == rid:
                    found = (slot, req)
                    break
            if found is None:
                raise ValueError(f"no active stream with rid {rid!r}")
            slot, req = found
            if req.chunk_off < len(req.prompt) or not req.emitted:
                raise ValueError(
                    f"stream {rid!r} has not completed prefill: a handoff "
                    "exports post-prefill KV only")
            row = self.pool.export_rows([slot])["rows"][slot]
            # inline the lane dict (export_sampling_lanes takes _cond,
            # which is non-reentrant)
            sp = req.sampling or GREEDY
            lane = {
                "seed": None if sp.seed is None else int(sp.seed),
                "next_index": req.sample_offset + len(req.emitted),
                "temperature": float(sp.temperature),
                "top_k": int(sp.top_k),
                "top_p": float(sp.top_p),
                "grammar_key": (sp.grammar_key()
                                if sp.constrained else None),
                "dfa_state": int(self.sampling_table.dfa_state[slot]),
            }
            payload = {
                "rid": rid,
                "tenant": req.tenant,
                "prompt": np.asarray(req.prompt, np.int32).copy(),
                "emitted": list(req.emitted),
                "logprobs": (req.handle.logprobs_so_far()
                             if req.want_logprobs else None),
                "kv_row": {
                    "block_len": self.pool.block_len,
                    "length": int(row["length"]),
                    "layers": row["layers"],
                },
                "lane": lane,
                "weight_version": self.weight_version,
                "adapter": req.adapter,
            }
            self._conclude(req, "handoff")
            self._free_row_locked(req, slot)
            del self._active[slot]
            self.metrics.set_slots(self.pool.active_slots(),
                                   self.pool.num_slots)
            self._cond.notify_all()
        flight_recorder().record(
            "kv_export", engine="llm", rid=rid,
            tokens=int(payload["kv_row"]["length"]),
            emitted=len(payload["emitted"]))
        return payload

    def replace_params(self, new_params, version: str):
        """Hot in-place weight swap between pump iterations — NO
        recompile. The unified step executable keys on its arguments'
        abstract signature (shape/dtype tree), and `_step_once` reads
        `self.params` fresh on every dispatch, so rebinding the attribute
        with a signature-identical tree reuses the warm `_step_jit` —
        verified end to end by the compile observatory (no
        `compile_recompile` events for `llm/unified_step` across a
        deploy). Refuses (typed `WeightSwapError`) if the engine still
        holds queued/active work or if the new tree's structure, any leaf
        shape, or any leaf dtype differs. Also flushes the prefix cache:
        cached KV was computed under the OLD weights, and attaching it to
        a new-version prompt would stitch two weight sets inside one
        attention window."""
        if not version:
            raise ValueError("version must be a non-empty string")
        converted = jax.tree_util.tree_map(jnp.asarray, new_params)
        old_s = jax.tree_util.tree_structure(self.params)
        new_s = jax.tree_util.tree_structure(converted)
        if old_s != new_s:
            raise WeightSwapError(
                f"weight set {version!r} has a different tree structure "
                f"than the serving params ({new_s} vs {old_s})")
        old_leaves = jax.tree_util.tree_leaves_with_path(self.params)
        new_leaves = jax.tree_util.tree_leaves(converted)
        for (path, old), new in zip(old_leaves, new_leaves):
            if tuple(old.shape) != tuple(new.shape) \
                    or old.dtype != new.dtype:
                raise WeightSwapError(
                    f"weight set {version!r} leaf "
                    f"{jax.tree_util.keystr(path)} is "
                    f"{tuple(new.shape)}/{new.dtype}, serving params have "
                    f"{tuple(old.shape)}/{old.dtype} — abstract signature "
                    "must match exactly (swap without recompile)")
        with self._cond:
            if self._queue_len_locked() or self._active:
                raise WeightSwapError(
                    f"cannot swap to {version!r} with work in flight "
                    f"(queued={self._queue_len_locked()}, "
                    f"active={len(self._active)}): drain first")
            flushed = 0
            if self.prefix_cache is not None:
                flushed = self.prefix_cache.clear()
            if self.draft_prefix_cache is not None:
                # the draft's weights did not change, but keeping both
                # caches' lifecycles aligned across deploys is cheap and
                # removes a whole class of "stale draft prefix after
                # rollback" questions (draft KV is an optimization, never
                # a correctness input — acceptance re-verifies everything)
                flushed += self.draft_prefix_cache.clear()
            prior = self.weight_version
            self.params = converted
            self.weight_version = str(version)
            self._cond.notify_all()
        flight_recorder().record(
            "weight_swap", engine="llm", version=str(version),
            prior=prior, leaves=len(new_leaves), flushed_blocks=flushed)

    # ---- multi-LoRA adapter lifecycle (ISSUE 20) ----
    def _flush_adapter_kv(self, adapter_id: str):
        """Drop ONE adapter's `(tenant, adapter)` KV namespaces from both
        cache tiers: its cached KV was computed under the delta being
        replaced. Base and other-adapter namespaces stay warm."""
        suffix = f"\x00adapter:{adapter_id}"
        if self.prefix_cache is not None:
            # clears the matching host-tier namespaces too
            self.prefix_cache.clear(only=lambda ns: ns.endswith(suffix))
        elif self.host_kv is not None:
            self.host_kv.clear(only=lambda ns: ns.endswith(suffix))

    def _require_bank(self) -> AdapterBank:
        if self.adapter_bank is None:
            raise AdapterError(
                "engine built without an adapter bank "
                "(config.max_adapters=0)", reason="adapter_unavailable")
        return self.adapter_bank

    def register_adapter(self, adapter_id: str, tree,
                         alpha: Optional[float] = None):
        """Load — or hot-swap, when the id is already resident — one
        adapter into a bank row. Unlike `replace_params` this needs NO
        drain: the swap rewrites bank-row values between pump
        iterations while the step executable and every other row's
        streams are untouched (base weights included), which is what
        makes adapter rollout zero-downtime by construction. The tree
        is validated against the base-model signature first (typed
        AdapterError on rank/target/shape mismatch — never a
        recompile).

        Returns the PRIOR row snapshot (None for a fresh load) — the
        rollback token `rollback_adapter` restores when a post-swap
        canary fails."""
        bank = self._require_bank()
        prior = bank.snapshot_row(adapter_id)
        row = bank.load(adapter_id, tree, alpha=alpha)
        # flush the adapter's KV namespaces: cached pages were computed
        # under the OLD delta (same reasoning as replace_params, scoped
        # to one adapter's namespaces instead of the whole cache)
        if prior is not None:
            self._flush_adapter_kv(adapter_id)
        flight_recorder().record(
            "adapter_swap", engine="llm", adapter=str(adapter_id),
            row=row, update=prior is not None,
            bank_version=bank.version)
        self.metrics.on_adapter_swap()
        return prior

    def rollback_adapter(self, adapter_id: str, snapshot):
        """Restore a bank row to a `register_adapter` rollback token
        (None = the adapter was fresh: unload it). The canary-failed
        delta stops serving the instant the row is rewritten; in-flight
        streams on the row continue on the restored values — no drop,
        no drain."""
        bank = self._require_bank()
        bank.restore(adapter_id, snapshot)
        self._flush_adapter_kv(adapter_id)
        flight_recorder().record(
            "adapter_rollback", engine="llm", adapter=str(adapter_id),
            restored=snapshot is not None, bank_version=bank.version)
        self.metrics.on_adapter_rollback()

    def unregister_adapter(self, adapter_id: str):
        """Unload an adapter and zero its row. Typed refusal while any
        queued/active stream still decodes under it — unloading would
        silently flip those streams to a zero delta mid-sequence."""
        bank = self._require_bank()
        with self._cond:
            users = [r.rid for r in self._active.values()
                     if r.adapter == adapter_id]
            users += [r.rid for q in self._queues.values()
                      for r in q if r.adapter == adapter_id]
            if users:
                raise AdapterError(
                    f"adapter {adapter_id!r} still has {len(users)} "
                    f"in-flight stream(s) ({users[:4]}...): drain or "
                    "finish them first", reason="adapter_in_use")
            bank.unload(adapter_id)
        flight_recorder().record(
            "adapter_unload", engine="llm", adapter=str(adapter_id),
            bank_version=bank.version)

    def canary_probe(self, prompt, max_new_tokens: int = 4,
                     adapter: Optional[str] = None):
        """Golden-prompt canary: greedy-decode `max_new_tokens` tokens
        directly through the prefill/decode functions on the CONTIGUOUS
        cache path (paged=None — same kernel as the paged path at shared
        block size, so bit-identity across replicas is meaningful),
        checking every logits tensor for finiteness along the way.
        Runs outside the scheduler on purpose: the gate must work on a
        drained, placement-excluded replica before any traffic lands on
        the new weights. `adapter` (ISSUE 20) probes through that bank
        row's LoRA delta — the gate an adapter hot-swap must clear
        before its rows keep serving — and raises a typed AdapterError
        when the id is not loaded. Returns (tokens np.int32
        [max_new_tokens], logits_finite bool)."""
        prompt = np.asarray(prompt, dtype=np.int32).reshape(-1)
        if prompt.size == 0:
            raise ValueError("canary prompt must be non-empty")
        adapters = None
        if adapter is not None:
            if self.adapter_bank is None:
                raise AdapterError(
                    "engine built without an adapter bank "
                    "(config.max_adapters=0)", reason="adapter_unavailable")
            row = self.adapter_bank.row_of(adapter)
            if row is None:
                raise AdapterError(f"unknown adapter {adapter!r}",
                                   reason="unknown_adapter")
            adapters = self.adapter_bank.args_for_rows([row])
        total = int(prompt.size) + int(max_new_tokens)
        caches = self.model.init_cache(1, total)
        logits, caches = self._prefill_fn(
            self.params, jnp.asarray(prompt[None, :]), caches, 0,
            adapters=adapters)
        lg = np.asarray(logits)
        finite = bool(np.isfinite(lg).all())
        last = int(np.argmax(lg[0, -1]))
        toks = [last]
        pos = int(prompt.size)
        for _ in range(int(max_new_tokens) - 1):
            logits, caches = self._decode_fn(
                self.params, jnp.asarray([last], dtype=jnp.int32),
                pos, caches, adapters=adapters)
            lg = np.asarray(logits)
            finite = finite and bool(np.isfinite(lg).all())
            last = int(np.argmax(lg[0]))
            toks.append(last)
            pos += 1
        return np.asarray(toks, dtype=np.int32), finite

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop(drain=True)
        return False

    # ---- admission ----
    def _queue_len_locked(self) -> int:
        return sum(len(q) for q in self._queues.values())

    def _pop_next_locked(self) -> Optional[_GenRequest]:
        """Strict SLO-class priority, tenant-fair WITHIN a class: among
        the highest non-empty class's queue, dequeue the oldest request
        of the tenant with the least active token usage (sum of cost over
        its slot-holding requests), so one tenant's burst cannot starve
        another at equal priority. With a single tenant queued this
        degenerates to exact FIFO."""
        for cls in SLO_CLASSES:     # strict priority order
            q = self._queues[cls]
            if not q:
                continue
            if len({r.tenant for r in q}) <= 1:
                return q.popleft()
            usage: Dict[str, int] = {}
            for r in self._active.values():
                usage[r.tenant] = usage.get(r.tenant, 0) + r.cost
            best_i = 0
            best_u = None
            for i, r in enumerate(q):           # FIFO tie-break
                u = usage.get(r.tenant, 0)
                if best_u is None or u < best_u:
                    best_i, best_u = i, u
            req = q[best_i]
            del q[best_i]
            return req
        return None

    def _tenant_inflight_locked(self, tenant: str) -> int:
        return (sum(r.cost for q in self._queues.values()
                    for r in q if r.tenant == tenant)
                + sum(r.cost for r in self._active.values()
                      if r.tenant == tenant))

    def _inflight_tokens_locked(self) -> int:
        """Estimated token cost of everything admitted: queued + active.
        Recomputed from the tables (never incrementally maintained), so a
        failure path can never leak budget."""
        return (sum(r.cost for q in self._queues.values() for r in q)
                + sum(r.cost for r in self._active.values()))

    def _update_brownout_locked(self):
        if self.config.brownout_queue_depth is None:
            return
        depth = self._queue_len_locked()
        if not self._brownout and depth >= self.config.brownout_queue_depth:
            self._brownout = True
            self.metrics.set_brownout(True)
            _log.warning(
                "llm engine entering brownout at queue depth %d: capping "
                "admitted max_new_tokens to %d", depth,
                self.config.brownout_max_new_tokens)
        elif self._brownout and depth <= self.config.brownout_queue_depth // 2:
            self._brownout = False
            self.metrics.set_brownout(False)
            _log.info("llm engine exiting brownout at queue depth %d", depth)

    def _make_room_locked(self, slo: str, cost: int) -> Optional[str]:
        """Shed-lowest-first: while the queue or token budget blocks this
        admission, fail the NEWEST queued request of the lowest class
        strictly below `slo` (reason "shed"). Returns None when the
        request can be admitted, else the reject reason."""
        pri = SLO_CLASSES.index(slo)
        while True:
            depth_full = (self._queue_len_locked()
                          >= self.config.max_queue_depth)
            budget = self.config.max_inflight_tokens
            over_budget = (budget is not None
                           and self._inflight_tokens_locked() + cost > budget)
            if not depth_full and not over_budget:
                return None
            victim = None
            for cls in reversed(SLO_CLASSES):   # lowest class first
                if SLO_CLASSES.index(cls) <= pri:
                    break
                if self._queues[cls]:
                    victim = self._queues[cls].pop()   # newest of its class
                    break
            if victim is None:
                return "queue_full" if depth_full else "token_budget"
            self._conclude(victim, "shed")
            victim.handle.future.set_exception(RejectedError(
                f"shed ({victim.slo}) to admit {slo} traffic under "
                "overload", reason="shed",
                retry_after_s=self.config.retry_after_s))
            self.metrics.on_reject("shed", tenant=victim.tenant)
            self.metrics.on_shed(victim.slo)
            if self.burn is not None:
                self.burn.observe(victim.slo, False, outcome="shed")
            self._record_reject("shed", rid=victim.rid,
                                tenant=victim.tenant)

    def submit(self, prompt, max_new_tokens: Optional[int] = None,
               eos_token_id: Optional[int] = None,
               deadline_ms: Optional[float] = None,
               slo: Optional[str] = None,
               tenant: Optional[str] = None,
               rid: Optional[str] = None,
               trace: bool = False,
               sampling: Optional[SamplingParams] = None,
               sample_offset: int = 0,
               logprobs: bool = False,
               kv_row: Optional[dict] = None,
               lane: Optional[dict] = None,
               adapter: Optional[str] = None) -> GenerationHandle:
        """Admit one prompt (1-D int token ids). `slo` names the request's
        SLO class (config.default_slo when None); `tenant` its isolation
        domain (config.default_tenant when None) — tenants get fair
        dequeue within a class, an optional in-flight token quota, and a
        private prefix-cache namespace. `rid` is the request id (ingested
        from a traceparent header by the server, generated when None);
        `trace=True` accumulates a per-request timeline on the handle and
        in the engine's timeline store.

        `sampling` (ISSUE 18) carries the per-request sampling contract;
        None is greedy. `sample_offset` restores the request's RNG lane
        on a failover re-prefill: it is the stream index of the first
        token THIS admission will emit (= tokens already emitted on the
        dead replica, re-prefilled as the prompt's tail), so draw i of
        the logical stream stays keyed by `(seed, i)` across the
        failover. For a constrained request the same tail is walked
        through the grammar DFA host-side to restore the mask state.

        ISSUE 19: `logprobs=True` streams each emitted token's raw
        log-probability onto the handle (`logprobs_so_far()`). `kv_row`
        imports pre-computed KV for the prompt's first `kv_row["length"]`
        tokens at admission (a prefill→decode handoff: the exporting
        replica's `export_stream` payload), skipping their re-prefill.
        `lane` is the exported sampling-lane dict riding the same
        payload; when it matches this admission's `sample_offset`, a
        constrained request restores its DFA state directly from the
        lane instead of re-walking the resumed tail.

        ISSUE 20: `adapter` names a loaded AdapterBank row — the stream
        then decodes under that adapter's LoRA delta on the SAME unified
        step as its base/other-adapter neighbors. None rides bank row 0
        (all-zero delta) and is bit-identical to a pre-LoRA engine.
        Naming an adapter on an engine without a bank, or one that is
        not loaded, is a typed reject ("adapter_unavailable" /
        "unknown_adapter"), never a recompile.

        Raises RejectedError when the sequence can never fit a slot, the
        queue/token budget/tenant quota is exhausted and nothing
        lower-priority can be shed, the grammar bank is full, the engine
        is draining, or the circuit breaker is open."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size < 1:
            raise ValueError("prompt must contain at least one token")
        sample_offset = int(sample_offset)
        if sample_offset < 0:
            raise ValueError(
                f"sample_offset must be >= 0, got {sample_offset}")
        mnt = (self.config.max_new_tokens if max_new_tokens is None
               else int(max_new_tokens))
        if mnt < 1:
            raise ValueError(f"max_new_tokens must be >= 1, got {mnt}")
        slo = self.config.default_slo if slo is None else slo
        if slo not in SLO_CLASSES:
            raise ValueError(
                f"slo must be one of {SLO_CLASSES}, got {slo!r}")
        tenant = self.config.default_tenant if tenant is None else tenant
        if not isinstance(tenant, str) or not tenant:
            raise ValueError("tenant must be a non-empty string")
        rid = rid or new_request_id()
        if adapter is not None:
            if self.adapter_bank is None:
                self.metrics.on_reject("adapter_unavailable", tenant=tenant)
                self._record_reject("adapter_unavailable", rid=rid,
                                    tenant=tenant)
                raise RejectedError(
                    f"request names adapter {adapter!r} but the engine "
                    "was built without an adapter bank "
                    "(config.max_adapters=0)",
                    reason="adapter_unavailable")
            if self.adapter_bank.row_of(adapter) is None:
                self.metrics.on_reject("unknown_adapter", tenant=tenant)
                self._record_reject("unknown_adapter", rid=rid,
                                    tenant=tenant)
                raise RejectedError(
                    f"adapter {adapter!r} is not loaded "
                    f"(loaded: {self.adapter_bank.adapter_ids})",
                    reason="unknown_adapter")
        eos = (self.config.eos_token_id if eos_token_id is None
               else eos_token_id)
        gid, dstate0 = 0, 0
        if sampling is not None:
            sampling.validate()
            if sampling.grammar is not None:
                gkey = sampling.grammar_key()
                gid = self.sampling_table.lookup(gkey)
                if gid is None:
                    tg0 = self.clock.now()
                    dfa = compile_grammar(
                        sampling.grammar, self.sampling_table.vocab_size,
                        eos)
                    try:
                        gid = self.sampling_table.intern(gkey, dfa)
                    except ValueError as e:
                        # bank capacity is an admission-control condition,
                        # not a caller bug: typed reject, not ValueError
                        self.metrics.on_reject("grammar_capacity")
                        self._record_reject("grammar_capacity", rid=rid,
                                            tenant=tenant)
                        raise RejectedError(str(e),
                                            reason="grammar_capacity")
                    if self.ledger is not None:
                        self.ledger.book("sample_mask",
                                         self.clock.now() - tg0)
                    self.metrics.set_grammars(
                        self.sampling_table.grammars_compiled)
                if sample_offset and lane is not None \
                        and lane.get("grammar_key") == gkey \
                        and int(lane.get("next_index", -1)) == sample_offset:
                    # prefill→decode handoff (ISSUE 19): the exported lane
                    # carries the DFA state at exactly this admission's
                    # resume index — restore it directly, no re-walk
                    dstate0 = int(lane["dfa_state"])
                elif sample_offset:
                    # failover re-prefill: the prompt's tail IS the
                    # emitted-so-far constrained stream — walk it through
                    # the DFA so the mask resumes mid-grammar exactly
                    bank = self.sampling_table.bank[gid]
                    q = 0
                    for t in prompt[-min(sample_offset, prompt.size):]:
                        nq = int(bank[q, int(t)])
                        if nq < 0:
                            raise ValueError(
                                "failover resume tail violates the "
                                f"request grammar at token {int(t)}")
                        q = nq
                    dstate0 = q
        if kv_row is not None:
            if int(kv_row.get("block_len", -1)) != self.pool.block_len:
                raise ValueError(
                    f"kv_row block_len {kv_row.get('block_len')!r} does "
                    f"not match the pool's ({self.pool.block_len}): KV "
                    "pages are not portable across block geometries")
            klen = int(kv_row["length"])
            if not 0 < klen <= prompt.size - 1:
                raise ValueError(
                    f"kv_row length {klen} must cover 1..{prompt.size - 1} "
                    "prompt tokens (at least one token always prefills — "
                    "that step emits the first token's logits)")
        if prompt.size + mnt > self.pool.capacity:
            self.metrics.on_reject("prompt_too_long")
            self._record_reject("prompt_too_long", rid=rid, tenant=tenant)
            raise RejectedError(
                f"prompt ({prompt.size}) + max_new_tokens ({mnt}) exceeds "
                f"slot capacity ({self.pool.capacity} tokens)",
                reason="prompt_too_long")
        if deadline_ms is None:
            deadline_ms = self.config.default_deadline_ms
        now = self.clock.now()
        deadline = None if deadline_ms is None else now + deadline_ms / 1e3
        with self._cond:
            if self.supervisor.open:
                self.metrics.on_reject("circuit_open")
                self._record_reject("circuit_open", rid=rid, tenant=tenant)
                raise RejectedError(
                    "engine circuit breaker open after repeated dispatch "
                    "failures; request rejected", reason="circuit_open")
            if self._draining or self._stopped:
                self.metrics.on_reject("draining")
                self._record_reject("draining", rid=rid, tenant=tenant)
                raise RejectedError("engine is draining; request rejected",
                                    reason="draining")
            self._update_brownout_locked()
            if self._brownout and mnt > self.config.brownout_max_new_tokens:
                mnt = self.config.brownout_max_new_tokens
            quota = self.config.tenant_max_inflight_tokens
            if quota is not None and (
                    self._tenant_inflight_locked(tenant)
                    + prompt.size + mnt > quota):
                # checked BEFORE shed logic: shedding OTHER tenants'
                # requests cannot relieve this tenant's own quota
                self.metrics.on_reject("tenant_quota", tenant=tenant)
                self._record_reject("tenant_quota", rid=rid, tenant=tenant)
                raise RejectedError(
                    f"tenant {tenant!r} in-flight token quota exhausted "
                    f"({quota} tokens)", reason="tenant_quota",
                    retry_after_s=self.config.retry_after_s)
            reason = self._make_room_locked(slo, prompt.size + mnt)
            if reason is not None:
                self.metrics.on_reject(reason)
                self._record_reject(reason, rid=rid, tenant=tenant)
                detail = (f"queue at capacity ({self.config.max_queue_depth} "
                          "pending requests)" if reason == "queue_full" else
                          f"token budget exhausted "
                          f"({self.config.max_inflight_tokens} in-flight "
                          "tokens)")
                raise RejectedError(
                    f"{detail}; nothing below class {slo!r} to shed",
                    reason=reason,
                    retry_after_s=self.config.retry_after_s)
            req = _GenRequest(prompt, mnt, eos, now, deadline, slo,
                              self._submit_idx, tenant=tenant)
            req.rid = rid
            req.handle.rid = rid
            req.sampling = sampling
            req.sample_offset = sample_offset
            req.gid = gid
            req.dfa_state0 = dstate0
            req.want_logprobs = bool(logprobs)
            req.kv_row = kv_row
            req.adapter = adapter
            if trace:
                req.trace = RequestTrace(rid, now, slo=slo, tenant=tenant)
                req.trace.event("submitted", now, prompt_len=int(prompt.size),
                                max_new_tokens=mnt,
                                submit_idx=self._submit_idx)
                req.handle.trace = req.trace
            self._submit_idx += 1
            self._queues[slo].append(req)
            self.metrics.on_submit(self._queue_len_locked(), slo=slo,
                                   tenant=tenant)
            self.metrics.set_inflight_tokens(self._inflight_tokens_locked())
            self._cond.notify_all()
        return req.handle

    def generate(self, prompt, max_new_tokens: Optional[int] = None,
                 eos_token_id: Optional[int] = None,
                 deadline_ms: Optional[float] = None,
                 timeout: Optional[float] = None,
                 slo: Optional[str] = None,
                 tenant: Optional[str] = None,
                 sampling: Optional[SamplingParams] = None) -> np.ndarray:
        """Synchronous convenience: submit + wait for the full sequence."""
        return self.submit(prompt, max_new_tokens=max_new_tokens,
                           eos_token_id=eos_token_id,
                           deadline_ms=deadline_ms, slo=slo,
                           tenant=tenant, sampling=sampling).result(timeout)

    @staticmethod
    def _kv_ns(tenant: str, adapter: Optional[str]) -> str:
        """Prefix-cache/host-KV namespace for a stream (ISSUE 20): KV
        computed under an adapter's LoRA delta diverges from base KV
        after the first adapted layer, so each `(tenant, adapter)` pair
        gets its own radix namespace — adapter streams never attach base
        pages and vice versa. The composed key rides the existing
        string-tenant cache machinery unchanged (NUL cannot appear in a
        tenant id, so the composition is injective)."""
        return tenant if not adapter else f"{tenant}\x00adapter:{adapter}"

    def prefix_probe(self, prompt, tenant: Optional[str] = None,
                     adapter: Optional[str] = None) -> int:
        """Longest block-aligned cached-prefix match for `prompt` in this
        engine's radix cache, in tokens — 0 with the cache disabled.
        Read-only (no refcounts, ticks, or stats move): the replica
        router calls this on every candidate per admission to steer a
        request to the replica already holding its prefix KV, and a
        probe on a losing candidate must leave that replica's cache
        untouched. Surfaced over HTTP via /healthz `llm_prefix_probe`.

        ISSUE 19: the probe consults BOTH tiers — a replica whose device
        cache evicted a prefix into its host pool can still onboard it
        without re-prefilling, so for placement scoring it is exactly as
        warm as one still holding the pages in HBM.

        ISSUE 20: `adapter` probes that adapter's own `(tenant, adapter)`
        namespace — router placement is then warmth-aware per adapter,
        not just per tenant."""
        tenant = self.config.default_tenant if tenant is None else tenant
        ns = self._kv_ns(tenant, adapter)
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        host = (self.host_kv.probe(ns, prompt)
                if self.host_kv is not None else 0)
        if self.prefix_cache is None:
            return host
        return max(self.prefix_cache.probe(ns, prompt), host)

    def inflight_tokens(self) -> int:
        """Current admitted token cost (queued + active): the router's
        load tie-breaker."""
        with self._cond:
            return self._inflight_tokens_locked()

    # ---- scheduling ----
    def has_work(self) -> bool:
        with self._cond:
            return bool(self._queue_len_locked() or self._active)

    def next_event_time(self) -> Optional[float]:
        """Clock instant of the next scheduler action — `now` whenever any
        sequence is queued or decoding (decode/admission work is always
        immediately due), None when idle. The sim harness advances its
        clock here between scripted arrivals."""
        with self._cond:
            if self._queue_len_locked() or self._active:
                return self.clock.now()
            return None

    def pump(self) -> int:
        """One scheduler pass: drop expired queued requests, admit queued
        requests into free slots (bookkeeping only — no dispatch), then
        run ONE unified mixed prefill+decode step and retire
        finished/evicted rows. Returns the number of decode iterations
        executed (0 or 1; a step carrying only prefill chunks returns 0) —
        the quantity the continuous-batching tests count. This is THE
        scheduler: the background thread and the sim harness both call
        it.

        With economics armed (ISSUE 11) the whole pass runs inside the
        serving ledger's ``measure("host")`` frame; the successful
        dispatch's device span is booked out of it by `_step_once`, so
        host/compute/idle tile the pump's wall clock by construction."""
        led = self.ledger
        if led is None:
            return self._pump_inner()
        with led.measure("host"):
            return self._pump_inner()

    def _pump_inner(self) -> int:
        now = self.clock.now()
        # time-weighted slot occupancy (ISSUE 11 satellite): integrate the
        # level held since the previous pump pass, at pump granularity
        self.metrics.observe_occupancy(now)
        self._drop_expired_queued(now)
        self._admit()
        n = self._step_once()
        with self._cond:
            self.metrics.set_inflight_tokens(self._inflight_tokens_locked())
            per_tenant: Dict[str, int] = {}
            for q in self._queues.values():
                for r in q:
                    per_tenant[r.tenant] = \
                        per_tenant.get(r.tenant, 0) + r.cost
            for r in self._active.values():
                per_tenant[r.tenant] = per_tenant.get(r.tenant, 0) + r.cost
            self.metrics.set_tenant_inflight(per_tenant)
            self.metrics.set_sample_slots(
                self.sampling_table.mode_counts(self._active.keys()))
        if self.prefix_cache is not None:
            self.metrics.set_prefix_cache(
                self.prefix_cache.stats["cached_blocks"],
                self.prefix_cache.stats["evictions"],
                {t: s["cached_blocks"]
                 for t, s in self.prefix_cache.tenant_stats.items()})
        if self.host_kv is not None:
            self.metrics.set_host_kv(self.host_kv.snapshot())
            if self.ledger is not None and self.prefix_cache is not None:
                # spill work happens inside pool.allocate's pressure hook
                # (mid-_admit), so the cache accumulates its wall time and
                # the pump books the delta into the kv_spill phase here
                spill = self.prefix_cache.spill_seconds
                if spill > self._spill_booked:
                    self.ledger.book("kv_spill", spill - self._spill_booked)
                    self._spill_booked = spill
        self.metrics.set_fragmentation(self.pool.fragmentation_ratio())
        return n

    def _drop_expired_queued(self, now: float):
        with self._cond:
            expired = 0
            for cls, q in self._queues.items():
                if not q:
                    continue
                alive = deque()
                for r in q:
                    if r.deadline is not None and now >= r.deadline:
                        self._conclude(r, "expired:queued", now)
                        r.handle.future.set_exception(DeadlineExceededError(
                            f"deadline expired after "
                            f"{(now - r.arrival) * 1e3:.1f}ms in queue "
                            "(dropped before prefill)"))
                        if self.burn is not None:
                            self.burn.observe(r.slo, False,
                                              outcome="expired_queued")
                        expired += 1
                    else:
                        alive.append(r)
                if len(alive) != len(q):
                    self._queues[cls] = alive
            if expired:
                self.metrics.on_expire(expired)
                self.metrics.set_queue_depth(self._queue_len_locked())

    def _admit(self):
        """Move queued requests into free slots, highest SLO class first —
        pure bookkeeping (slot allocation + chunk_off=0); their prompt
        chunks ride the next unified step alongside everyone else's
        decode rows."""
        with self._cond:
            while True:
                self._update_brownout_locked()
                if self.supervisor.open or self.pool.free_slots() == 0:
                    return
                req = self._pop_next_locked()
                if req is None:
                    return
                self.metrics.set_queue_depth(self._queue_len_locked())
                try:
                    slot = self.pool.allocate(req.cost)
                except SlotsExhaustedError:
                    # every free row is pinned by cached blocks with live
                    # readers (pressure eviction couldn't help); requeue
                    # at the front and retry once readers drain
                    self._queues[req.slo].appendleft(req)
                    self.metrics.set_queue_depth(self._queue_len_locked())
                    return
                req.slot = slot
                req.chunk_off = 0
                req.attached_pages = []
                if req.trace is not None:
                    t_adm = self.clock.now()
                    req.trace.mark("admitted", t_adm)
                    req.trace.event(
                        "admitted", t_adm, slot=slot,
                        queue_wait_ms=(t_adm - req.arrival) * 1e3)
                if req.kv_row is not None:
                    # prefill→decode handoff import (ISSUE 19): upload the
                    # exported row into this slot's own identity pages and
                    # start chunked prefill past the covered span. No
                    # set_length here — the next chunk commit's
                    # set_length claims the own pages exactly as a cold
                    # prefill would, so check_balance holds without a
                    # special ledger path.
                    t0 = self.clock.now()
                    bl = self.pool.block_len
                    klen = int(req.kv_row["length"])
                    layers = req.kv_row["layers"]
                    for j in range(0, klen, bl):
                        w = min(bl, klen - j)
                        blk = [(k[:, j:j + w, :], v[:, j:j + w, :])
                               for k, v in layers]
                        self.pool.import_page(slot, j // bl, blk)
                    req.chunk_off = klen
                    self.kv_import_tokens += klen
                    if self.ledger is not None:
                        self.ledger.book("kv_onboard",
                                         self.clock.now() - t0)
                    flight_recorder().record(
                        "kv_import", engine="llm", rid=req.rid,
                        tokens=klen)
                    if req.trace is not None:
                        req.trace.event("kv_import", self.clock.now(),
                                        tokens=klen)
                elif self.prefix_cache is not None:
                    # cap at plen-1 so at least one prompt token always
                    # prefills (that step produces the first output
                    # token's logits); an over-cap full block degrades to
                    # a COW tail, so an exact-duplicate prompt still
                    # costs only a one-token prefill
                    plan = self.prefix_cache.acquire(
                        self._kv_ns(req.tenant, req.adapter), req.prompt,
                        max_tokens=len(req.prompt) - 1)
                    if plan.pages:
                        self.pool.attach_blocks(slot, plan.pages)
                        req.attached_pages = list(plan.pages)
                    if plan.tail_page is not None:
                        self.pool.cow_copy(plan.tail_page, slot)
                    req.chunk_off = plan.attach_len
                    # the slot now holds its own refs (attach_blocks) and
                    # its own copy of the tail — drop acquire's transient
                    # refcounts so eviction sees the true reader count
                    self.prefix_cache.release(plan)
                    self.metrics.on_prefix_lookup(
                        req.tenant, plan.attach_len, len(req.prompt))
                    if req.trace is not None:
                        req.trace.event(
                            "prefix_lookup", self.clock.now(),
                            attach_len=plan.attach_len,
                            prompt_len=len(req.prompt))
                # host-tier onboard (ISSUE 19): where the device radix
                # cache's coverage ends on a block boundary, keep walking
                # block-by-block through the host spill pool and upload
                # covered pages into the slot's own identity pages —
                # chunked prefill then starts past everything either tier
                # held. A COW tail (non-aligned chunk_off) ends the walk:
                # that block is already mid-copy. Onboarded blocks are
                # re-indexed into the device trie for free when the
                # completed prefill runs `prefix_cache.insert`.
                if (self.host_kv is not None and req.kv_row is None
                        and req.chunk_off % self.pool.block_len == 0):
                    bl = self.pool.block_len
                    t0 = self.clock.now()
                    j = req.chunk_off // bl
                    onboarded = 0
                    # same cap as the device acquire: at least one prompt
                    # token always prefills
                    while (j + 1) * bl <= len(req.prompt) - 1:
                        layers = self.host_kv.get(
                            self._kv_ns(req.tenant, req.adapter),
                            req.prompt[:(j + 1) * bl])
                        if layers is None:
                            break
                        self.pool.import_page(slot, j, layers)
                        j += 1
                        onboarded += 1
                    if onboarded:
                        req.chunk_off = j * bl
                        self.host_onboard_tokens += onboarded * bl
                        if self.ledger is not None:
                            self.ledger.book("kv_onboard",
                                             self.clock.now() - t0)
                        flight_recorder().record(
                            "kv_onboard", engine="llm", rid=req.rid,
                            blocks=onboarded, tokens=onboarded * bl)
                        if req.trace is not None:
                            req.trace.event(
                                "kv_onboard", self.clock.now(),
                                blocks=onboarded, tokens=onboarded * bl)
                # per-slot sampling state (ISSUE 18): bind the request's
                # params + grammar/DFA row for the slot's lifetime
                self.sampling_table.bind(slot, req.sampling or GREEDY,
                                         gid=req.gid,
                                         dfa_state=req.dfa_state0)
                # multi-LoRA lane (ISSUE 20): point the slot's
                # adapter_idx at the request's bank row. The adapter may
                # have been unloaded between submit and admit — that is
                # a typed reject here, never a wrong-delta decode.
                if self.adapter_bank is not None:
                    try:
                        self.adapter_bank.bind_slot(slot, req.adapter)
                    except AdapterError as e:
                        self._conclude(req, "rejected:unknown_adapter")
                        req.handle.future.set_exception(RejectedError(
                            f"adapter {req.adapter!r} was unloaded before "
                            f"admission ({e})", reason="unknown_adapter"))
                        self.metrics.on_reject("unknown_adapter",
                                               tenant=req.tenant)
                        self._record_reject("unknown_adapter", rid=req.rid,
                                            tenant=req.tenant)
                        self._free_row_locked(req, slot)
                        continue
                # speculative decoding (ISSUE 17): give the request a row
                # in the draft pool. Exhaustion is not an error — the
                # request simply runs spec-off (plain decode is always
                # available and always correct). Grammar-constrained
                # requests (ISSUE 18) never speculate — their one
                # emission column per step is masked by a DFA state the
                # draft cannot see ahead of — so they skip the draft row
                # instead of pinning one idle.
                if self.draft_pool is not None and not self._spec_disabled \
                        and req.gid == 0:
                    try:
                        dslot = self.draft_pool.allocate(req.cost)
                    except SlotsExhaustedError:
                        dslot = None
                    if dslot is not None:
                        req.draft_slot = dslot
                        if self.draft_prefix_cache is not None:
                            # same max_tokens cap as the target acquire:
                            # both pools share block_len, so draft and
                            # target attach page-congruent prefixes and a
                            # warm hit skips the SAME token span on both
                            # sides
                            dplan = self.draft_prefix_cache.acquire(
                                req.tenant, req.prompt,
                                max_tokens=len(req.prompt) - 1)
                            if dplan.pages:
                                self.draft_pool.attach_blocks(
                                    dslot, dplan.pages)
                                req.draft_attached = list(dplan.pages)
                            if dplan.tail_page is not None:
                                self.draft_pool.cow_copy(dplan.tail_page,
                                                         dslot)
                            if dplan.attach_len:
                                # attached/COW'd draft KV is immediately
                                # valid: the draft starts its catch-up
                                # from here, not from token 0
                                self.draft_pool.set_length(
                                    dslot, dplan.attach_len)
                            self.draft_prefix_cache.release(dplan)
                self._active[slot] = req
                self.metrics.set_slots(self.pool.active_slots(),
                                       self.pool.num_slots)

    # ---- speculative decoding (ISSUE 17) ----
    def _stream_token(self, req: _GenRequest, i: int) -> int:
        """Token i of the request's true committed stream
        (prompt + emitted) — what draft catch-up replays."""
        plen = len(req.prompt)
        return int(req.prompt[i]) if i < plen else int(req.emitted[i - plen])

    def _draft_phase(self) -> Dict[int, List[int]]:
        """The pump's draft work, run BEFORE the target's unified step:
        one chunk-wide catch-up dispatch for rows whose draft KV trails
        the target's committed stream (prompt suffixes after admission /
        failover re-prefill, gap tokens after partial windows), then ONE
        proposal dispatch — the spec_k+1-step on-device scan — over every
        caught-up decode-ready row. Returns {target_slot: [d1..dK]}, the
        verify windows `_build_rows_locked` stitches into the unified
        step. Both dispatches announce kind "draft" and run
        breaker-exempt: any failure degrades this pump to plain decode
        (and quarantines the implicated request's DRAFT on attribution),
        never the streams."""
        if self.draft_pool is None or self._spec_disabled:
            return {}
        C = self.config.prefill_chunk
        K = self.config.spec_k
        dpool = self.draft_pool
        pad_pos = dpool.n_blocks * dpool.block_len
        N = dpool.num_slots

        # -- catch-up: replay committed stream tokens into the draft pool
        with self._cond:
            toks = np.zeros((N, C), np.int32)
            pos = np.full((N,), pad_pos, np.int32)
            adv = np.zeros((N,), np.int32)
            catchup: List[Tuple[int, _GenRequest, int, int, int]] = []
            for slot, req in self._active.items():
                ds = req.draft_slot
                if ds is None or req.spec_off:
                    continue
                tlen = int(self.pool.lengths[slot])
                dlen = int(dpool.lengths[ds])
                if dlen >= tlen:
                    continue
                n = min(C, tlen - dlen)
                for j in range(n):
                    toks[ds, j] = self._stream_token(req, dlen + j)
                pos[ds] = dlen
                adv[ds] = n
                catchup.append((slot, req, ds, dlen, n))
        if catchup:
            rids = tuple(sorted(r.submit_idx for _, r, _, _, _ in catchup))
            fn = self._draft_step()
            args = (self._draft_params, jnp.asarray(toks), jnp.asarray(pos),
                    jnp.asarray(adv), dpool.device_block_table(),
                    dpool.slabs)
            tdc0 = self.clock.now() if self.ledger is not None else None
            try:
                out, new_slabs = self._run_dispatch(
                    (("draft", rids),), fn, args, exempt=True)
            except DispatchFailedError as e:
                self._draft_failure(
                    [(s, r) for s, r, _, _, _ in catchup], e, "catchup")
                return {}
            if self.ledger is not None:
                jax.block_until_ready(out)
                self.ledger.book_dispatch(
                    self.clock.now() - tdc0, prefill_positions=0,
                    decode_positions=0, total_positions=0,
                    owners=[(r.tenant, r.slo, n)
                            for _, r, _, _, n in catchup],
                    draft_positions=int(sum(n for *_, n in catchup)))
            dpool.slabs = new_slabs
            with self._cond:
                for slot, req, ds, dlen, n in catchup:
                    if self._active.get(slot) is not req \
                            or not dpool.active[ds]:
                        continue
                    dpool.set_length(ds, dlen + n)
                    plen = len(req.prompt)
                    if (self.draft_prefix_cache is not None
                            and dlen < plen <= dlen + n):
                        # the draft's prompt KV just completed: index it
                        # so shared-prefix siblings attach on the draft
                        # side too (page-congruent with the target cache)
                        self.draft_prefix_cache.insert(
                            req.tenant, req.prompt, ds, req.draft_attached)
            self._draft_failstreak = 0

        # -- proposal: ONE scan dispatch over caught-up decode-ready rows
        with self._cond:
            tok0 = np.zeros((N,), np.int32)
            ppos = np.full((N,), pad_pos, np.int32)
            act = np.zeros((N,), np.int32)
            # per-lane sampling operands, indexed by DRAFT slot (ISSUE
            # 18): the scan proposes on the same (seed, stream index)
            # lanes the target verify will draw on
            dtemp = np.ones((N,), np.float32)
            dtopk = np.zeros((N,), np.int32)
            dtopp = np.ones((N,), np.float32)
            dsamp = np.zeros((N,), bool)
            dseed = np.zeros((N,), np.int32)
            dctr = np.zeros((N,), np.int32)
            tab = self.sampling_table
            eligible: List[Tuple[int, _GenRequest, int, int]] = []
            for slot, req in self._active.items():
                ds = req.draft_slot
                if ds is None or req.spec_off or req.gid > 0:
                    continue
                if req.chunk_off < len(req.prompt):
                    continue            # still in chunked prefill
                L = int(self.pool.lengths[slot])
                if int(dpool.lengths[ds]) != L:
                    continue            # draft KV still catching up
                if req.max_new_tokens - len(req.emitted) < 2:
                    continue            # a window cannot beat one step
                if L + K + 1 > self.pool.capacity:
                    continue            # window would overrun the slot
                tok0[ds] = req.last_tok
                ppos[ds] = L
                act[ds] = 1
                dtemp[ds] = tab.temperature[slot]
                dtopk[ds] = tab.top_k[slot]
                dtopp[ds] = tab.top_p[slot]
                dsamp[ds] = tab.do_sample[slot]
                dseed[ds] = tab.seed[slot]
                dctr[ds] = req.sample_offset + len(req.emitted)
                eligible.append((slot, req, ds, L))
        if not eligible:
            return {}
        rids = tuple(sorted(r.submit_idx for _, r, _, _ in eligible))
        fn = self._draft_propose()
        args = (self._draft_params, jnp.asarray(tok0), jnp.asarray(ppos),
                jnp.asarray(act), dpool.device_block_table(), dpool.slabs,
                jnp.asarray(dtemp), jnp.asarray(dtopk), jnp.asarray(dtopp),
                jnp.asarray(dsamp), jnp.asarray(dseed), jnp.asarray(dctr))
        tdc0 = self.clock.now() if self.ledger is not None else None
        try:
            drafts_dev, new_slabs = self._run_dispatch(
                (("draft", rids),), fn, args, exempt=True)
        except DispatchFailedError as e:
            self._draft_failure([(s, r) for s, r, _, _ in eligible], e,
                                "propose")
            return {}
        if self.ledger is not None:
            jax.block_until_ready(drafts_dev)
            self.ledger.book_dispatch(
                self.clock.now() - tdc0, prefill_positions=0,
                decode_positions=0, total_positions=0,
                owners=[(r.tenant, r.slo, K + 1)
                        for _, r, _, _ in eligible],
                draft_positions=(K + 1) * len(eligible))
        dpool.slabs = new_slabs
        drafts = np.asarray(drafts_dev)
        spec: Dict[int, List[int]] = {}
        with self._cond:
            for slot, req, ds, L in eligible:
                if self._active.get(slot) is not req \
                        or not dpool.active[ds]:
                    continue
                # the scan wrote K+1 stripes: last_tok @ L and d1..dK at
                # L+1..L+K (the final iteration feeds dK for exactly this
                # write), so after an all-accept window (commit L+K+1)
                # the draft needs NO catch-up dispatch
                dpool.set_length(ds, L + K + 1)
                spec[slot] = [int(t) for t in drafts[ds]]
        self._draft_failstreak = 0
        return spec

    def _draft_failure(self, rows, err, stage: str):
        """A draft dispatch failed after supervision (retries are not
        worth a latency optimization — one failure degrades the pump to
        plain decode). Attribution mirrors `_blame_and_quarantine` at
        draft scope: solo-probe each riding request with a width-1
        draft-kind dispatch; a blamed request's DRAFT is quarantined
        (spec_off + draft row freed) while its target stream continues
        bit-identically. Probes commit nothing — slabs are immutable and
        never assigned here. Unattributable failures count an
        engine-wide failstreak that disables spec at breaker_threshold;
        the target breaker is NEVER charged on any draft path."""
        dpool = self.draft_pool
        fn = self._draft_propose()
        N = dpool.num_slots
        blamed = []
        for slot, req in rows:
            ds = req.draft_slot
            if ds is None:
                continue
            tok0 = np.zeros((N,), np.int32)
            act = np.zeros((N,), np.int32)
            tok0[ds] = req.last_tok
            act[ds] = 1
            # probe at pos=0: the result is discarded and never
            # committed, so clobber-free addressing is all that matters
            # — neutral greedy lanes keep the probe deterministic
            args = (self._draft_params, jnp.asarray(tok0),
                    jnp.asarray(np.zeros((N,), np.int32)),
                    jnp.asarray(act), dpool.device_block_table(),
                    dpool.slabs,
                    jnp.asarray(np.ones((N,), np.float32)),
                    jnp.asarray(np.zeros((N,), np.int32)),
                    jnp.asarray(np.ones((N,), np.float32)),
                    jnp.asarray(np.zeros((N,), bool)),
                    jnp.asarray(np.zeros((N,), np.int32)),
                    jnp.asarray(np.zeros((N,), np.int32)))
            try:
                self._run_dispatch((("draft", (req.submit_idx,)),), fn,
                                   args, exempt=True)
            except DispatchFailedError as probe_err:
                blamed.append((slot, req, probe_err))
                flight_recorder().record(
                    "solo_probe", engine="llm", rid=req.rid,
                    submit_idx=req.submit_idx, stage="draft",
                    outcome="failed")
            else:
                flight_recorder().record(
                    "solo_probe", engine="llm", rid=req.rid,
                    submit_idx=req.submit_idx, stage="draft", outcome="ok")
        if blamed and (len(blamed) < len(rows) or len(rows) == 1):
            with self._cond:
                for slot, req, probe_err in blamed:
                    if self._active.get(slot) is not req:
                        continue
                    req.spec_off = True
                    ds = req.draft_slot
                    if ds is not None and dpool.active[ds]:
                        dpool.free(ds)
                    req.draft_slot = None
                    self.metrics.on_draft_quarantine()
                    flight_recorder().record(
                        "draft_quarantine", engine="llm", rid=req.rid,
                        submit_idx=req.submit_idx, stage=stage,
                        reason="poisoned_draft", error=str(probe_err))
            _log.warning(
                "quarantined the DRAFT of %d request(s) after a poisoned "
                "%s dispatch; their streams continue as plain decode",
                len(blamed), stage)
            return
        self._draft_failstreak += 1
        flight_recorder().record(
            "draft_failure", engine="llm", stage=stage,
            failstreak=self._draft_failstreak, error=str(err))
        if self._draft_failstreak >= self.config.breaker_threshold:
            self._spec_disabled = True
            flight_recorder().record(
                "draft_disabled", engine="llm",
                failstreak=self._draft_failstreak)
            _log.error(
                "disabling speculative decoding after %d consecutive "
                "unattributable draft dispatch failures; the engine "
                "continues on plain decode", self._draft_failstreak)

    def _acceptance_locked(self, decode_slots, spec_drafts,
                           nxt) -> Dict[int, Tuple[List[int], int, int]]:
        """Greedy verification over the step's per-position tokens:
        for each decode row, walk the longest prefix of its draft window
        matching the target's own argmaxes, then take the target's one
        corrective token — truncated by the request's EOS / max-tokens
        caps exactly where sequential decode would stop. Returns
        {slot: (emit_tokens, accepted_draft_count, drafted_count)}; a
        plain decode row (no drafts) degenerates to ([next_token], 0, 0),
        which is precisely the pre-spec commit."""
        accept: Dict[int, Tuple[List[int], int, int]] = {}
        for slot in decode_slots:
            req = self._active.get(slot)
            if req is None:
                continue
            drafts = spec_drafts.get(slot, ())
            row = nxt[slot]
            k = len(drafts)
            a = 0
            while a < k and int(row[a]) == int(drafts[a]):
                a += 1
            emit_toks: List[int] = []
            for j in range(a + 1):
                tok = int(row[j])
                emit_toks.append(tok)
                if len(req.emitted) + len(emit_toks) >= req.max_new_tokens:
                    break
                if req.eos_token_id is not None \
                        and tok == req.eos_token_id:
                    break
            accept[slot] = (emit_toks, min(len(emit_toks), a), k)
        return accept

    def _build_rows_locked(self, spec_drafts=None):
        """Assemble the unified step's host-side row set from the active
        table: (toks [N, C], pos [N], adv [N], ctr [N], prefill_slots,
        decode_slots). Free slots stay all-zero (adv=0 → fully masked).
        A decode row with a draft window (ISSUE 17) carries
        [last_tok, d1..dk] at adv=1+k — the verify chunk; plain decode
        rows stay [last_tok] at adv=1.

        `ctr` (ISSUE 18) is each row's RNG-lane stream index for column
        0: decode rows sit at `sample_offset + emitted` (column t draws
        stream token index ctr+t); prefill rows back the base off by
        adv-1 so the emission column adv-1 lands exactly on the first
        emitted token's index — the earlier columns' draws are discarded
        with their logits, negative intermediate indices fold_in as
        harmless uint32 bit-casts."""
        N = self.pool.num_slots
        C = self.config.prefill_chunk
        toks = np.zeros((N, C), np.int32)
        ctr = np.zeros((N,), np.int32)
        # free rows still get a (discarded) C-wide KV stripe written at
        # their pos by the unified step; park it in the slab's pad region
        # (block tables never address cols >= n_blocks*block_len) so it
        # cannot clobber cached prefix pages living in freed rows
        pos = np.full((N,), self.pool.n_blocks * self.pool.block_len,
                      np.int32)
        adv = np.zeros((N,), np.int32)
        prefill_slots: List[int] = []
        decode_slots: List[int] = []
        for slot, req in self._active.items():
            plen = len(req.prompt)
            base = req.sample_offset + len(req.emitted)
            if req.chunk_off < plen:
                off = req.chunk_off
                n = min(C, plen - off)
                toks[slot, :n] = req.prompt[off:off + n]
                pos[slot] = off
                adv[slot] = n
                ctr[slot] = base - (n - 1)
                prefill_slots.append(slot)
            else:
                drafts = (spec_drafts.get(slot, ())
                          if spec_drafts else ())
                toks[slot, 0] = req.last_tok
                for j, d in enumerate(drafts):
                    toks[slot, 1 + j] = d
                pos[slot] = self.pool.lengths[slot]
                adv[slot] = 1 + len(drafts)
                ctr[slot] = base
                decode_slots.append(slot)
        return toks, pos, adv, ctr, prefill_slots, decode_slots

    def _kinds_of(self, prefill_slots, decode_slots) -> Tuple:
        """(kind, request_ids) announcement order for fault injection:
        prefill rows first, then decode rows, both at one dispatch idx.
        Rows riding an adapter (ISSUE 20) additionally announce kind
        "adapter" at the SAME index, so a `poison_request@rid:adapter`
        clause scopes a fault to one adapter's streams without touching
        co-scheduled base/other-adapter rows."""
        kinds = []
        if prefill_slots:
            kinds.append(("prefill", tuple(sorted(
                self._active[s].submit_idx for s in prefill_slots))))
        if decode_slots:
            kinds.append(("decode", tuple(sorted(
                self._active[s].submit_idx for s in decode_slots))))
        adapter_rows = [s for s in list(prefill_slots) + list(decode_slots)
                        if self._active[s].adapter]
        if adapter_rows:
            kinds.append(("adapter", tuple(sorted(
                self._active[s].submit_idx for s in adapter_rows))))
        return tuple(kinds)

    def _step_once(self) -> int:
        """Run ONE unified mixed prefill+decode dispatch over every slot
        and commit its results. Returns 1 when the committed step carried
        at least one decode row (the decode-iteration count the
        continuous-batching invariants pin), else 0.

        With a draft model attached (ISSUE 17) the pump first runs the
        draft phase: decode rows carry verify windows [last_tok, d1..dK]
        instead of a lone token, and the commit takes the longest
        target-matching draft prefix plus the corrective token — up to
        K+1 tokens per row from the SAME single dispatch, bit-identical
        to plain greedy decode. Quarantine retries reuse this pump's
        windows: a failed dispatch commits nothing, so the surviving
        rows' positions — and therefore their drafts — are unchanged."""
        spec_drafts = self._draft_phase()
        while True:
            with self._cond:
                if not self._active:
                    return 0
                toks, pos, adv, ctr, prefill_slots, decode_slots = \
                    self._build_rows_locked(spec_drafts)
                kinds = self._kinds_of(prefill_slots, decode_slots)
                # sampling-operand assembly (ISSUE 18) — per-slot params,
                # RNG-lane counters, DFA states and the grammar bank —
                # is the host-side cost of constrained/sampled decoding;
                # meter it so the mask-overhead ceiling row in bench has
                # a real signal behind it
                ts0 = self.clock.now()
                sargs = self._sampling_args_locked(ctr)
                mask_dt = self.clock.now() - ts0
                aargs = self._adapter_args_locked()
            self.metrics.on_mask_overhead(mask_dt * 1e3)
            if self.ledger is not None:
                self.ledger.book("sample_mask", mask_dt)
            t0 = self.clock.now()
            fn = self._step()
            args = (self.params, jnp.asarray(toks), jnp.asarray(pos),
                    jnp.asarray(adv), self.pool.device_block_table(),
                    self.pool.slabs) + sargs + aargs
            if self.observatory is not None:
                self.observatory.observe_call("llm/unified_step", fn, args)
            attempts = self.config.dispatch_retries + 1
            last_err = None
            nxt = None
            tc0 = None
            for attempt in range(attempts):
                if self.ledger is not None or self.observatory is not None:
                    # re-armed per attempt: a failed round's wall time
                    # stays in the host phase; only the successful
                    # dispatch's span is booked as compute
                    tc0 = self.clock.now()
                try:
                    nxt, lps, new_dstate, new_slabs = self._run_dispatch(
                        kinds, fn, args)
                except DispatchFailedError as e:
                    last_err = e
                    self.metrics.on_dispatch_failure(e.reason)
                    flight_recorder().record(
                        "dispatch_retry", engine="llm", attempt=attempt + 1,
                        attempts=attempts, reason=e.reason,
                        prefill_rows=len(prefill_slots),
                        decode_rows=len(decode_slots))
                    _log.warning(
                        "unified step dispatch failed over %d prefill + %d "
                        "decode row(s) (attempt %d/%d): %s",
                        len(prefill_slots), len(decode_slots), attempt + 1,
                        attempts, e)
                    continue
                self.pool.slabs = new_slabs
                if decode_slots:
                    # the breaker tracks ENGINE-level (decode-protocol)
                    # failures; prefill-only successes must not launder a
                    # failure streak between decode attempts
                    self.supervisor.record_success()
                break
            else:
                if self._blame_and_quarantine(fn, toks, pos, adv, ctr,
                                              last_err):
                    continue    # survivors retry on a rebuilt row set
                self._fail_all_active(attempts, last_err)
                self.supervisor.record_failure()
                return 0
            if self.ledger is not None or self.observatory is not None:
                # jit dispatch is async: block on the device result so the
                # measured span is execution, not launch; split it between
                # the compute phases by advanced positions and meter it to
                # the rows' tenants / SLO classes (ISSUE 11)
                jax.block_until_ready(nxt)
                tc1 = self.clock.now()
            nxt = np.asarray(nxt)   # [N, C] per-position selected tokens
            lps = np.asarray(lps)   # [N, C] per-position selected logprobs
            new_dstate = np.asarray(new_dstate)  # [N] advanced DFA states
            with self._cond:
                accept = self._acceptance_locked(decode_slots, spec_drafts,
                                                 nxt)
            if self.ledger is not None or self.observatory is not None:
                if self.ledger is not None:
                    with self._cond:
                        owners = [(self._active[s].tenant,
                                   self._active[s].slo, int(adv[s]))
                                  for s in prefill_slots
                                  if s in self._active]
                        adapter_owners = [
                            (self._active[s].adapter or "base", int(adv[s]))
                            for s in prefill_slots if s in self._active]
                        decode_useful = drafted = accepted = 0
                        for s in decode_slots:
                            req = self._active.get(s)
                            if req is None or s not in accept:
                                continue
                            emit_toks, acc, k = accept[s]
                            owners.append((req.tenant, req.slo,
                                           len(emit_toks)))
                            adapter_owners.append((req.adapter or "base",
                                                   len(emit_toks)))
                            decode_useful += len(emit_toks)
                            drafted += k
                            accepted += acc
                    # a verify row's rejected columns stay inside
                    # total_positions but out of the useful decode count:
                    # wasted draft positions surface as pad-waste in
                    # token_efficiency, exactly like prefill padding.
                    # adapter_owners (ISSUE 20) re-buckets the SAME
                    # per-row shares by adapter id, so per-adapter
                    # device-seconds sum exactly to the tenant total.
                    self.ledger.book_dispatch(
                        tc1 - tc0,
                        prefill_positions=int(sum(adv[s]
                                                  for s in prefill_slots)),
                        decode_positions=decode_useful,
                        total_positions=int(toks.size),
                        owners=owners,
                        drafted=drafted, draft_accepted=accepted,
                        adapter_owners=(adapter_owners
                                        if self.adapter_bank is not None
                                        else None))
                if self.observatory is not None:
                    # the span above already blocked on the result, so it
                    # is pure device execution — attribute it to this
                    # call site's latest executable (ISSUE 12)
                    self.observatory.note_device_seconds(
                        "llm/unified_step", tc1 - tc0)
            now = self.clock.now()
            with self._cond:
                n_decode = len(decode_slots)
                if n_decode:
                    self.decode_iterations += 1
                elif prefill_slots:
                    self.prefill_dispatches += 1
                for slot in prefill_slots:
                    # evacuate() (deploy drain) may have freed the slot
                    # between row build and commit in threaded mode
                    req = self._active.get(slot)
                    if req is None:
                        continue
                    n = int(adv[slot])
                    off = req.chunk_off
                    self.pool.set_length(slot, off + n)
                    req.chunk_off = off + n
                    self.prefill_tokens += n
                    if req.trace is not None:
                        req.trace.event("prefill_chunk", now, off=off, n=n)
                    if req.chunk_off >= len(req.prompt):
                        # final chunk landed: first token emitted, TTFT
                        # ends here
                        req.handle.ttft_ms = (now - req.arrival) * 1e3
                        if req.trace is not None:
                            # same instant as ttft_ms, so the trace's TTFT
                            # boundary reconciles with the handle exactly
                            req.trace.mark("first_token", now)
                        self.metrics.on_prefill(req.handle.ttft_ms,
                                                slo=req.slo)
                        if self.burn is not None:
                            target = (self.config.slo_ttft_target_ms
                                      or {}).get(req.slo)
                            self.burn.observe(
                                req.slo,
                                target is None
                                or req.handle.ttft_ms <= target,
                                outcome="ttft")
                        if self.prefix_cache is not None:
                            # index the completed prefill while the slot
                            # is still active: siblings queued behind it
                            # attach without waiting for it to finish
                            self.prefix_cache.insert(
                                self._kv_ns(req.tenant, req.adapter),
                                req.prompt, slot, req.attached_pages)
                        self._emit(req, int(nxt[slot, int(adv[slot]) - 1]),
                                   float(lps[slot, int(adv[slot]) - 1]))
                        if req.gid:
                            # first constrained emission: commit the DFA
                            # state advanced in-step past that token
                            self.sampling_table.set_dfa_state(
                                slot, int(new_dstate[slot]))
                        if self._finish_if_done(req, now):
                            del self._active[slot]
                        elif req.deadline is not None and now >= req.deadline:
                            self._evict_expired_locked(req, slot, now)
                    elif req.deadline is not None and now >= req.deadline:
                        # mid-prefill eviction: no tokens yet, but the slot
                        # must not keep absorbing chunk work
                        self._evict_expired_locked(req, slot, now)
                total_emitted = 0
                for slot in decode_slots:
                    req = self._active.get(slot)
                    if req is None or slot not in accept:
                        continue  # evacuated mid-step (deploy drain)
                    emit_toks, acc, k = accept[slot]
                    L = int(pos[slot])
                    # the verify wrote KV for every consumed column, but
                    # only the accepted prefix + corrective token is
                    # committed: lengths/block tables never cover the
                    # rejected tail, so the pool's garbage-past-length
                    # invariant IS the rollback
                    self.pool.set_length(slot, L + len(emit_toks))
                    if self.draft_pool is not None \
                            and req.draft_slot is not None \
                            and self.draft_pool.active[req.draft_slot]:
                        # the draft ran ahead on its own proposals; rewind
                        # its tables to the verified stream so the next
                        # window extends truth, not rejected speculation
                        dlen = int(self.draft_pool.lengths[req.draft_slot])
                        self.draft_pool.rewind_length(
                            req.draft_slot,
                            min(dlen, L + len(emit_toks)))
                    if req.trace is not None:
                        ev = dict(tok=int(emit_toks[-1]),
                                  n_active=len(decode_slots))
                        if k:
                            ev.update(drafted=k, accepted=acc)
                        req.trace.event("decode_step", now, **ev)
                    for j, tok in enumerate(emit_toks):
                        self._emit(req, tok, float(lps[slot, j]))
                    if req.gid:
                        # constrained rows never speculate (one emission
                        # per step), so the in-step advanced state is
                        # exactly the post-emission state
                        self.sampling_table.set_dfa_state(
                            slot, int(new_dstate[slot]))
                    total_emitted += len(emit_toks)
                    if k:
                        self.spec_windows += 1
                        self.spec_drafted += k
                        self.spec_accepted += acc
                        self.metrics.on_spec_window(k, acc)
                    if self._finish_if_done(req, now):
                        del self._active[slot]
                    elif req.deadline is not None and now >= req.deadline:
                        self._evict_expired_locked(req, slot, now)
                self.metrics.set_slots(self.pool.active_slots(),
                                       self.pool.num_slots)
            if n_decode:
                self.metrics.on_decode_step(n_decode, (now - t0) * 1e3,
                                            tokens=total_emitted)
                return 1
            return 0

    def _evict_expired_locked(self, req: _GenRequest, slot: int,
                              now: float):
        """Deadline eviction of an active row (mid-prefill or mid-decode):
        partial tokens stay readable on the handle; the future fails with
        the deadline error."""
        stage = ("mid-prefill" if req.chunk_off < len(req.prompt)
                 else "mid-decode")
        self._conclude(req, f"expired:{stage}", now)
        req.handle.future.set_exception(DeadlineExceededError(
            f"deadline expired after {len(req.emitted)} of "
            f"{req.max_new_tokens} tokens (evicted {stage})"))
        self.metrics.on_expire()
        if self.burn is not None:
            self.burn.observe(req.slo, False, outcome="deadline")
        self._free_row_locked(req, slot)
        del self._active[slot]

    def _blame_and_quarantine(self, fn, toks, pos, adv, ctr,
                              last_err) -> bool:
        """Step retries exhausted: probe each active request in ISOLATION
        — the same fixed-width dispatch with every other row masked to
        (toks=0, pos=0, adv=0), announced as that single request's kind
        ("prefill" for a row still in chunked prefill, "decode"
        otherwise) — and quarantine the rows whose solo presence
        reproduces the failure. Probe results are never committed (slabs
        are immutable jax arrays; only a successful full step assigns
        pool.slabs), so survivors' streams stay bit-identical to a
        fault-free run — including decode rows co-scheduled with a
        request poisoned in prefill chunk k>0, which lose nothing but the
        failed step's wall time.

        When EVERY probe of a multi-row batch fails, the failure is not
        attributable to any one request — that is an engine-level fault
        and the breaker, not quarantine, must own it. A single-row batch
        whose probe fails is quarantined: the dispatch contained exactly
        that request, which is as exact as attribution gets."""
        with self._cond:
            suspects = list(self._active.items())
        blamed = []
        for slot, req in suspects:
            solo_toks = np.zeros_like(toks)
            solo_pos = np.zeros_like(pos)
            solo_adv = np.zeros_like(adv)
            solo_ctr = np.zeros_like(ctr)
            solo_toks[slot] = toks[slot]
            solo_pos[slot] = pos[slot]
            solo_adv[slot] = adv[slot]
            solo_ctr[slot] = ctr[slot]
            kind = ("prefill" if req.chunk_off < len(req.prompt)
                    else "decode")
            with self._cond:
                # probe with the REAL sampling operands: a poisoning that
                # only reproduces under the row's grammar mask or sampled
                # lane must still be attributable — and (ISSUE 20) with
                # the REAL adapter operands, so an adapter-scoped fault
                # reproduces in isolation too
                sargs = self._sampling_args_locked(solo_ctr)
                aargs = self._adapter_args_locked()
            args = (self.params, jnp.asarray(solo_toks),
                    jnp.asarray(solo_pos), jnp.asarray(solo_adv),
                    self.pool.device_block_table(),
                    self.pool.slabs) + sargs + aargs
            probe_kinds = [(kind, (req.submit_idx,))]
            if req.adapter:
                # the solo probe must announce the same adapter kind the
                # full step did, or an adapter-keyed clause could not
                # reproduce and the fault would look unattributable
                probe_kinds.append(("adapter", (req.submit_idx,)))
            try:
                self._run_dispatch(tuple(probe_kinds), fn, args)
            except DispatchFailedError as e:
                blamed.append((slot, req, e))
                flight_recorder().record(
                    "solo_probe", engine="llm", rid=req.rid,
                    submit_idx=req.submit_idx, stage=kind,
                    outcome="failed")
            else:
                flight_recorder().record(
                    "solo_probe", engine="llm", rid=req.rid,
                    submit_idx=req.submit_idx, stage=kind, outcome="ok")
        if not blamed or (len(blamed) == len(suspects) and len(suspects) > 1):
            return False
        with self._cond:
            for slot, req, e in blamed:
                if slot not in self._active:
                    continue
                self._conclude(req, "quarantined")
                req.handle.future.set_exception(DispatchFailedError(
                    f"request {req.submit_idx} quarantined: its rows "
                    f"reproduce the decode failure in isolation ({e})",
                    reason="poisoned"))
                self.metrics.on_fail()
                self.metrics.on_quarantine()
                flight_recorder().record(
                    "quarantine", engine="llm", rid=req.rid,
                    submit_idx=req.submit_idx, reason="poisoned",
                    tokens_emitted=len(req.emitted))
                self._free_row_locked(req, slot)
                del self._active[slot]
            self.metrics.set_slots(self.pool.active_slots(),
                                   self.pool.num_slots)
        self.supervisor.absolve()
        _log.warning("quarantined %d poisoned request(s); retrying the "
                     "unified step with %d survivor(s)", len(blamed),
                     len(suspects) - len(blamed))
        return True

    def _fail_all_active(self, attempts: int, last_err):
        """Non-attributable step failure: fail every active request with
        a typed error (partial tokens stay readable), free their slots,
        and let the caller charge the circuit breaker."""
        with self._cond:
            n_failed = len(self._active)
            for slot, req in list(self._active.items()):
                self._conclude(req, "failed:engine")
                req.handle.future.set_exception(DispatchFailedError(
                    f"decode dispatch failed {attempts} consecutive times; "
                    f"{len(req.emitted)} of {req.max_new_tokens} tokens "
                    f"emitted ({last_err})", reason="engine"))
                self.metrics.on_fail()
                # observed BEFORE the caller charges the breaker, so a
                # burn-rate crossing lands in the flight ring ahead of
                # the breaker_open event it predicts
                if self.burn is not None:
                    self.burn.observe(req.slo, False,
                                      outcome="engine_failure")
                self._free_row_locked(req, slot)
            self._active.clear()
            self.metrics.set_slots(self.pool.active_slots(),
                                   self.pool.num_slots)
        flight_recorder().record(
            "engine_failure", engine="llm", failed=n_failed,
            attempts=attempts, error=str(last_err))

    def _emit(self, req: _GenRequest, tok: int,
              lp: Optional[float] = None):
        req.emitted.append(tok)
        req.last_tok = tok
        req.handle._append(tok, lp if req.want_logprobs else None)
        if req.gid > 0:
            self.metrics.on_sample_token("constrained")
        elif req.sampling is not None and req.sampling.do_sample:
            self.metrics.on_sample_token("sampled")
        if self.adapter_bank is not None:
            self.metrics.on_adapter_token(req.adapter or "base")

    def _finish_if_done(self, req: _GenRequest, now: float) -> bool:
        """Retire a request whose last emitted token ended it (EOS,
        max-tokens, or — for a grammar-constrained request — a terminal
        DFA state: accepting with no legal continuation, where the only
        in-grammar move left is stopping). Frees its slot when it held
        one."""
        done = (len(req.emitted) >= req.max_new_tokens
                or (req.eos_token_id is not None
                    and req.emitted[-1] == req.eos_token_id)
                or (req.gid > 0 and req.slot is not None
                    and self.sampling_table.is_terminal(
                        req.gid,
                        int(self.sampling_table.dfa_state[req.slot]))))
        if not done:
            return False
        # finalize the timeline BEFORE resolving the future: a waiter that
        # wakes on result() must see the completed trace
        self._conclude(req, "completed", now)
        req.handle.future.set_result(np.asarray(req.emitted, np.int32))
        self.metrics.on_complete((now - req.arrival) * 1e3, slo=req.slo,
                                 tenant=req.tenant)
        if req.slot is not None and self.pool.active[req.slot]:
            self._free_row_locked(req, req.slot)
        return True

    # ---- scheduler thread (production mode) ----
    def _scheduler_main(self):
        while True:
            with self._cond:
                while True:
                    if self._stopped or self.supervisor.open:
                        return
                    if (self._draining and not self._queue_len_locked()
                            and not self._active):
                        return          # drained: stop() joins us
                    if self._queue_len_locked() or self._active:
                        break
                    self.clock.wait(self._cond, None)
            try:
                self.pump()
            except Exception as e:
                # an unhandled pump exception is exactly what the black box
                # exists for: record + dump before carrying on
                fr = flight_recorder()
                fr.record("pump_exception", engine="llm",
                          error=f"{type(e).__name__}: {e}")
                fr.try_dump(reason="pump_exception:llm")
                _log.exception("llm scheduler pump failed; continuing")
