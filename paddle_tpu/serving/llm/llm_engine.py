"""Continuous-batching LLM decode engine over the slot-paged KV pool
(ISSUE 5 tentpole).

The batch-locked `models.generation.generate()` loop makes every sequence
enter together, share one prompt length and pay the batch's full
`max_new_tokens` — one long request holds the whole batch's KV slabs
hostage. This engine schedules the same numeric path (the
`make_decoder_fns` prefill/decode builders, so outputs are bit-identical
per row) as a continuously-batched service:

- `prefill_into_slot` — one jitted call per pow2 prompt bucket: runs the
  prompt through a fresh cache row, writes the row into the pool slab at
  the allocated slot, and emits the first greedy token (TTFT ends here);
- `decode_step` — ONE jitted fixed-width call over all `num_slots` rows
  (the active-slot gather is a host-side table; inactive rows decode a
  harmless token-0 at position 0 of their own free slot, which the next
  prefill overwrites wholesale). Per-row positions ride the [B]-vector
  `pos` support in the cached attention path;
- between decode iterations the scheduler admits queued requests into
  freed slots and evicts finished rows (EOS / per-request max-tokens /
  deadline), so a short request never waits for a long one;
- admission control reuses the serving vocabulary: bounded queue →
  `RejectedError`, absolute deadlines → `DeadlineExceededError` (queued
  requests are dropped before prefill; decoding rows are evicted
  mid-stream with their partial tokens still readable off the handle).

Determinism: every decision is a pure function of `clock.now()` and the
queue/pool tables. Under a `SimClock` the engine runs threadless and a
test harness calls `pump()` directly — slot churn and decode-iteration
counts are provable facts, not timing accidents. Under the default
`MonotonicClock`, `start()` runs the same `pump()` from a scheduler
thread. Decoding is greedy (argmax): that is what makes continuous
batching bit-reproducible against one-shot generate() for free; sampling
belongs to the one-shot API.
"""
from __future__ import annotations

import logging
import threading
from collections import deque
from concurrent.futures import Future
from dataclasses import dataclass
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..clock import Clock, MonotonicClock, SimClock
from ..engine import DeadlineExceededError, RejectedError
from ..metrics import LLMMetrics
from .kv_pool import SlotPagedKVPool, SlotsExhaustedError

_log = logging.getLogger("paddle_tpu.serving.llm")


@dataclass
class LLMEngineConfig:
    num_slots: int = 4             # decode width == KV pool size
    block_len: int = 16            # tokens per accounting block
    n_blocks: int = 8              # blocks per slot (capacity = 128 tokens)
    max_queue_depth: int = 64      # pending-request cap (admission control)
    max_new_tokens: int = 32       # default per-request generation cap
    eos_token_id: Optional[int] = None   # per-request override wins
    default_deadline_ms: Optional[float] = None
    prompt_bucket_pow2: bool = True  # pad prompts to pow2 buckets so the
    #                                  number of prefill executables stays
    #                                  logarithmic in slot capacity
    min_prompt_bucket: int = 8
    drain_timeout_s: float = 60.0
    cache_dtype: Optional[object] = None  # pool slab dtype override

    def __post_init__(self):
        if self.num_slots < 1:
            raise ValueError(f"num_slots must be >= 1, got {self.num_slots}")
        if self.max_queue_depth < 1:
            raise ValueError(
                f"max_queue_depth must be >= 1, got {self.max_queue_depth}")
        if self.max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {self.max_new_tokens}")


class GenerationHandle:
    """Per-request streaming view + completion future.

    Tokens stream into `tokens_so_far()` as decode iterations retire them;
    `future` resolves with the full np.int32 array on EOS/max-tokens, or
    with DeadlineExceededError / RejectedError on eviction (partial tokens
    stay readable off the handle either way)."""

    def __init__(self, prompt_len: int, max_new_tokens: int):
        self.prompt_len = prompt_len
        self.max_new_tokens = max_new_tokens
        self.future: Future = Future()
        self.ttft_ms: Optional[float] = None
        self._lock = threading.Lock()
        self._tokens: List[int] = []

    def _append(self, tok: int):
        with self._lock:
            self._tokens.append(int(tok))

    def tokens_so_far(self) -> List[int]:
        with self._lock:
            return list(self._tokens)

    def result(self, timeout: Optional[float] = None) -> np.ndarray:
        return self.future.result(timeout)


class _GenRequest:
    __slots__ = ("prompt", "max_new_tokens", "eos_token_id", "arrival",
                 "deadline", "handle", "slot", "emitted", "last_tok")

    def __init__(self, prompt, max_new_tokens, eos_token_id, arrival,
                 deadline):
        self.prompt = prompt              # np.int32 [S]
        self.max_new_tokens = max_new_tokens
        self.eos_token_id = eos_token_id
        self.arrival = arrival            # clock seconds
        self.deadline = deadline          # absolute clock seconds or None
        self.handle = GenerationHandle(len(prompt), max_new_tokens)
        self.slot: Optional[int] = None
        self.emitted: List[int] = []
        self.last_tok: int = 0


def _next_pow2(n: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return p


class LLMEngine:
    """submit() a prompt, get a GenerationHandle streaming greedy tokens.

    The model must implement the cached-decode contract
    (`init_cache` / `forward_with_cache`, e.g. GPTForCausalLM /
    LlamaForCausalLM); it is switched to eval mode and its functional
    state captured once at construction.
    """

    def __init__(self, model, config: Optional[LLMEngineConfig] = None,
                 clock: Optional[Clock] = None,
                 metrics: Optional[LLMMetrics] = None):
        from ...models.generation import make_decoder_fns
        self.model = model
        model.eval()
        self.config = config or LLMEngineConfig()
        self.clock = clock or MonotonicClock()
        self.metrics = metrics or LLMMetrics()
        self.params, self._prefill_fn, self._decode_fn = \
            make_decoder_fns(model)
        self.pool = SlotPagedKVPool(
            model.init_cache, self.config.num_slots, self.config.block_len,
            self.config.n_blocks, dtype=self.config.cache_dtype)
        self.metrics.set_slots(0, self.pool.num_slots)
        self._queue: deque = deque()
        self._active: Dict[int, _GenRequest] = {}   # slot -> request
        self._cond = threading.Condition()
        self._draining = False
        self._stopped = False
        self._thread: Optional[threading.Thread] = None
        self._prefill_jit: Dict[int, object] = {}   # prompt bucket -> fn
        self._decode_jit = None
        self.decode_iterations = 0   # lifetime decode_step dispatches

    # ---- jitted executables ----
    def _prefill_for_bucket(self, bucket: int):
        if bucket not in self._prefill_jit:
            slab_specs = [(k.shape, k.dtype, v.shape, v.dtype)
                          for k, v in self.pool.slabs]

            def prefill_into_slot(params, prompt, length, slot, slabs):
                # prompt [1, bucket] (zero-padded past `length`); a fresh
                # single-row cache is filled, then written over the slot's
                # WHOLE stripe (so stale KV from the previous occupant is
                # wiped) and the first greedy token read at length-1.
                rows = [(jnp.zeros((1,) + ks[1:], kd),
                         jnp.zeros((1,) + vs[1:], vd))
                        for ks, kd, vs, vd in slab_specs]
                logits, rows = self._prefill_fn(params, prompt, rows,
                                                jnp.int32(0))
                new_slabs = [
                    (jax.lax.dynamic_update_slice(ks, rk, (slot, 0, 0, 0)),
                     jax.lax.dynamic_update_slice(vs, rv, (slot, 0, 0, 0)))
                    for (ks, vs), (rk, rv) in zip(slabs, rows)]
                last = jax.lax.dynamic_index_in_dim(logits[0], length - 1,
                                                    axis=0, keepdims=False)
                tok0 = jnp.argmax(last, axis=-1).astype(jnp.int32)
                return tok0, new_slabs

            self._prefill_jit[bucket] = jax.jit(prefill_into_slot)
        return self._prefill_jit[bucket]

    def _decode(self):
        if self._decode_jit is None:
            def decode_step(params, toks, pos, slabs):
                # toks/pos [num_slots]: every slot decodes every iteration
                # (fixed width, ONE executable); inactive rows carry
                # (tok=0, pos=0) and scribble on their own free slot only.
                logits, slabs = self._decode_fn(params, toks, pos, slabs)
                return jnp.argmax(logits, axis=-1).astype(jnp.int32), slabs

            self._decode_jit = jax.jit(decode_step)
        return self._decode_jit

    # ---- lifecycle ----
    def start(self) -> "LLMEngine":
        """Run the scheduler on a background thread (production mode). Not
        needed under a SimClock — the harness calls pump() itself."""
        if isinstance(self.clock, SimClock):
            raise RuntimeError(
                "LLMEngine.start() with a SimClock would busy-spin: drive "
                "pump() from the simulation harness instead")
        with self._cond:
            if self._stopped:
                raise RuntimeError("engine already stopped")
            if self._thread is not None:
                return self
            self._thread = threading.Thread(
                target=self._scheduler_main, daemon=True,
                name="pdtpu-llm-scheduler")
            self._thread.start()
        return self

    def stop(self, drain: bool = True, timeout: Optional[float] = None):
        """Graceful drain: stop admissions (submit -> RejectedError), then
        finish EVERY admitted sequence — queued requests still get
        prefilled and decoded to completion — before stopping the
        scheduler. With drain=False, queued and decoding requests fail
        with RejectedError instead."""
        with self._cond:
            if self._stopped:
                return
            self._draining = True
            if not drain:
                while self._queue:
                    req = self._queue.popleft()
                    req.handle.future.set_exception(
                        RejectedError("engine shut down before prefill"))
                    self.metrics.on_reject("shutdown")
                for slot, req in list(self._active.items()):
                    req.handle.future.set_exception(
                        RejectedError("engine shut down mid-decode"))
                    self.metrics.on_reject("shutdown")
                    self.pool.free(slot)
                self._active.clear()
                self.metrics.set_queue_depth(0)
                self.metrics.set_slots(0, self.pool.num_slots)
            self._cond.notify_all()
            thread = self._thread
        if thread is not None:
            join_s = (timeout if timeout is not None
                      else self.config.drain_timeout_s)
            thread.join(join_s)
            if thread.is_alive():
                _log.warning(
                    "llm drain did not complete within %.1fs; failing "
                    "sequences still in flight", join_s)
        else:
            # threadless (sim) mode: run the scheduler inline to completion
            while self._queue or self._active:
                if self.pump() == 0 and not self._queue and not self._active:
                    break
        with self._cond:
            stranded = 0
            while self._queue:
                req = self._queue.popleft()
                req.handle.future.set_exception(RejectedError(
                    "engine drain timed out before prefill"))
                self.metrics.on_reject("drain_timeout")
                stranded += 1
            for slot, req in list(self._active.items()):
                req.handle.future.set_exception(RejectedError(
                    "engine drain timed out mid-decode"))
                self.metrics.on_reject("drain_timeout")
                self.pool.free(slot)
                stranded += 1
            self._active.clear()
            if stranded:
                self.metrics.set_queue_depth(0)
                self.metrics.set_slots(0, self.pool.num_slots)
            self._stopped = True
            self._cond.notify_all()

    @property
    def draining(self) -> bool:
        return self._draining

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop(drain=True)
        return False

    # ---- admission ----
    def submit(self, prompt, max_new_tokens: Optional[int] = None,
               eos_token_id: Optional[int] = None,
               deadline_ms: Optional[float] = None) -> GenerationHandle:
        """Admit one prompt (1-D int token ids). Raises RejectedError when
        the sequence can never fit a slot, the queue is full, or the engine
        is draining."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size < 1:
            raise ValueError("prompt must contain at least one token")
        mnt = (self.config.max_new_tokens if max_new_tokens is None
               else int(max_new_tokens))
        if mnt < 1:
            raise ValueError(f"max_new_tokens must be >= 1, got {mnt}")
        eos = (self.config.eos_token_id if eos_token_id is None
               else eos_token_id)
        if prompt.size + mnt > self.pool.capacity:
            self.metrics.on_reject("prompt_too_long")
            raise RejectedError(
                f"prompt ({prompt.size}) + max_new_tokens ({mnt}) exceeds "
                f"slot capacity ({self.pool.capacity} tokens)")
        if deadline_ms is None:
            deadline_ms = self.config.default_deadline_ms
        now = self.clock.now()
        deadline = None if deadline_ms is None else now + deadline_ms / 1e3
        with self._cond:
            if self._draining or self._stopped:
                self.metrics.on_reject("draining")
                raise RejectedError("engine is draining; request rejected")
            if len(self._queue) >= self.config.max_queue_depth:
                self.metrics.on_reject("queue_full")
                raise RejectedError(
                    f"queue at capacity ({self.config.max_queue_depth} "
                    "pending requests)")
            req = _GenRequest(prompt, mnt, eos, now, deadline)
            self._queue.append(req)
            self.metrics.on_submit(len(self._queue))
            self._cond.notify_all()
        return req.handle

    def generate(self, prompt, max_new_tokens: Optional[int] = None,
                 eos_token_id: Optional[int] = None,
                 deadline_ms: Optional[float] = None,
                 timeout: Optional[float] = None) -> np.ndarray:
        """Synchronous convenience: submit + wait for the full sequence."""
        return self.submit(prompt, max_new_tokens=max_new_tokens,
                           eos_token_id=eos_token_id,
                           deadline_ms=deadline_ms).result(timeout)

    # ---- scheduling ----
    def has_work(self) -> bool:
        with self._cond:
            return bool(self._queue or self._active)

    def next_event_time(self) -> Optional[float]:
        """Clock instant of the next scheduler action — `now` whenever any
        sequence is queued or decoding (decode/admission work is always
        immediately due), None when idle. The sim harness advances its
        clock here between scripted arrivals."""
        with self._cond:
            if self._queue or self._active:
                return self.clock.now()
            return None

    def pump(self) -> int:
        """One scheduler pass: drop expired queued requests, admit queued
        requests into free slots (one jitted prefill each), then run at
        most ONE fixed-width decode iteration and retire finished/evicted
        rows. Returns the number of decode iterations executed (0 or 1) —
        the quantity the continuous-batching tests count. This is THE
        scheduler: the background thread and the sim harness both call
        it."""
        now = self.clock.now()
        self._drop_expired_queued(now)
        self._admit()
        return self._decode_once()

    def _drop_expired_queued(self, now: float):
        with self._cond:
            if not self._queue:
                return
            alive = deque()
            expired = 0
            for r in self._queue:
                if r.deadline is not None and now >= r.deadline:
                    r.handle.future.set_exception(DeadlineExceededError(
                        f"deadline expired after "
                        f"{(now - r.arrival) * 1e3:.1f}ms in queue "
                        "(dropped before prefill)"))
                    expired += 1
                else:
                    alive.append(r)
            if expired:
                self._queue = alive
                self.metrics.on_expire(expired)
                self.metrics.set_queue_depth(len(alive))

    def _admit(self):
        """Prefill queued requests into free slots. Runs between decode
        iterations — each admission is one jitted prefill_into_slot call
        that also emits the request's first token (TTFT)."""
        while True:
            with self._cond:
                if not self._queue or self.pool.free_slots() == 0:
                    return
                req = self._queue.popleft()
                self.metrics.set_queue_depth(len(self._queue))
                slot = self.pool.allocate(
                    len(req.prompt) + req.max_new_tokens)
            length = len(req.prompt)
            bucket = self._bucket_of(length)
            padded = np.zeros((1, bucket), np.int32)
            padded[0, :length] = req.prompt
            fn = self._prefill_for_bucket(bucket)
            tok0, self.pool.slabs = fn(self.params, jnp.asarray(padded),
                                       jnp.int32(length), jnp.int32(slot),
                                       self.pool.slabs)
            now = self.clock.now()
            req.slot = slot
            req.handle.ttft_ms = (now - req.arrival) * 1e3
            self.metrics.on_prefill(req.handle.ttft_ms)
            self._emit(req, int(tok0))
            with self._cond:
                if self._finish_if_done(req, now):
                    continue
                self.pool.set_length(slot, length)
                self._active[slot] = req
                self.metrics.set_slots(self.pool.active_slots(),
                                       self.pool.num_slots)

    def _bucket_of(self, length: int) -> int:
        if not self.config.prompt_bucket_pow2:
            return length
        return max(self.config.min_prompt_bucket,
                   min(_next_pow2(length), self.pool.capacity))

    def _decode_once(self) -> int:
        with self._cond:
            if not self._active:
                return 0
            toks = np.zeros((self.pool.num_slots,), np.int32)
            pos = np.zeros((self.pool.num_slots,), np.int32)
            for slot, req in self._active.items():
                toks[slot] = req.last_tok
                pos[slot] = self.pool.lengths[slot]
        t0 = self.clock.now()
        nxt, self.pool.slabs = self._decode()(
            self.params, jnp.asarray(toks), jnp.asarray(pos),
            self.pool.slabs)
        nxt = np.asarray(nxt)
        now = self.clock.now()
        with self._cond:
            rows = len(self._active)
            self.decode_iterations += 1
            for slot, req in list(self._active.items()):
                # the decode wrote last_tok's KV at pos[slot]
                self.pool.set_length(slot, int(pos[slot]) + 1)
                self._emit(req, int(nxt[slot]))
                if self._finish_if_done(req, now):
                    del self._active[slot]
                elif req.deadline is not None and now >= req.deadline:
                    # mid-decode eviction: partial tokens stay readable on
                    # the handle; the future fails with the deadline error
                    req.handle.future.set_exception(DeadlineExceededError(
                        f"deadline expired after {len(req.emitted)} of "
                        f"{req.max_new_tokens} tokens (evicted mid-decode)"))
                    self.metrics.on_expire()
                    self.pool.free(slot)
                    del self._active[slot]
            self.metrics.set_slots(self.pool.active_slots(),
                                   self.pool.num_slots)
        self.metrics.on_decode_step(rows, (now - t0) * 1e3)
        return 1

    def _emit(self, req: _GenRequest, tok: int):
        req.emitted.append(tok)
        req.last_tok = tok
        req.handle._append(tok)

    def _finish_if_done(self, req: _GenRequest, now: float) -> bool:
        """Retire a request whose last emitted token ended it (EOS or
        max-tokens). Frees its slot when it held one."""
        done = (len(req.emitted) >= req.max_new_tokens
                or (req.eos_token_id is not None
                    and req.emitted[-1] == req.eos_token_id))
        if not done:
            return False
        req.handle.future.set_result(np.asarray(req.emitted, np.int32))
        self.metrics.on_complete((now - req.arrival) * 1e3)
        if req.slot is not None and self.pool.active[req.slot]:
            self.pool.free(req.slot)
        return True

    # ---- scheduler thread (production mode) ----
    def _scheduler_main(self):
        while True:
            with self._cond:
                while True:
                    if self._stopped:
                        return
                    if (self._draining and not self._queue
                            and not self._active):
                        return          # drained: stop() joins us
                    if self._queue or self._active:
                        break
                    self.clock.wait(self._cond, None)
            try:
                self.pump()
            except Exception:
                _log.exception("llm scheduler pump failed; continuing")
