"""Per-slot seeded sampling + grammar-constrained decoding (ISSUE 18).

The unified mixed prefill+decode step stays ONE fixed-width jitted
program; everything a request can ask for — temperature, top-k, top-p,
a reproducible seed, a JSON-schema grammar — rides through it as
batched per-slot ARRAYS, never as static knobs, so per-request params
cannot force a recompile (the generate() JitLRUCache churn story,
solved at the engine by construction).

Three pieces:

* `SamplingParams` — the request-level contract. A request samples iff
  `seed is not None`; greedy requests never consume RNG. The seeding
  contract is **per-request threefry lanes indexed by stream
  position**: token `i` of a request's emitted stream is drawn with
  `fold_in(fold_in(PRNGKey(0), seed), i)` — a pure function of
  `(seed, i)` that never sees the slot index, the batch composition,
  or wall clock. That single property is what makes sampled streams
  bit-identical across batch-mate churn, engine restart, AND router
  failover re-prefill (the survivor just resumes the lane at
  `i = tokens_already_emitted` via `sample_offset`).

* A JSON-schema -> token-level DFA compiler. The schema subset
  (objects with properties emitted in declared order, string enums,
  const, integer, boolean, arrays) compiles to a character NFA, is
  determinized, then LIFTED to token level against the request's
  `tokens` table (token id -> text): token `t` is legal in DFA state
  `q` iff running its text through the char DFA from `q` lands in a
  live state. EOS is legal exactly in accepting states (self-loop).
  Dead token-states — no legal token and no EOS — are pruned to a
  fixpoint so a constrained slot can never paint itself into a
  maskless corner mid-stream.

* `select_tokens` — the pure, jit-traceable selection applied to the
  step's [N, C, V] logits: grammar mask first (so top-k/top-p filter
  the LEGAL set, an empty intersection is impossible), then the
  vectorized `_select_token` per-row params path, with per-(row,
  column) fold_in keys. Greedy rows take the masked argmax — for
  unconstrained greedy rows the mask is pass-through and the result
  is bit-identical to the pre-sampling verify argmax.

Speculative decoding composes via *seeded-replay acceptance*: because
the target's draw at stream index `i` is coin-fixed by `(seed, i)`,
the verify pass simply computes the token the target WOULD sample at
every window position; the existing longest-matching-prefix acceptance
then yields output literally identical to plain sampled decode —
strictly stronger than distribution-level unbiasedness (it is the same
token stream), which is the rejection-sampling guarantee with the
residual-resampling machinery collapsed away by determinism. A draft
sharing the lane (same seed, same indices, its own logits) proposes
exactly the target's draws whenever the two models agree, so the
PR 17 speedup survives. Grammar-constrained slots do not speculate.
"""
from __future__ import annotations

import json
import threading
from dataclasses import dataclass
from typing import Dict, Mapping, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ...models.generation import _select_token

# char-DFA subset-construction blowup guard; schemas in the supported
# subset are tiny (tens of states) — hitting this means a pathological
# enum/nesting, better rejected at admission than OOMing the bank
_MAX_CHAR_STATES = 4096


# ---------------------------------------------------------------------------
# request-level params
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class SamplingParams:
    """Per-request sampling contract carried from /generate to the slot.

    `seed is None` -> greedy (the default; bit-identical to the
    pre-sampling engine). `grammar`, when set, is a dict
    `{"schema": <json-schema subset>, "tokens": {token_id: text}}`;
    constrained decoding works for greedy and sampled requests alike.
    """
    temperature: float = 1.0
    top_k: int = 0
    top_p: float = 1.0
    seed: Optional[int] = None
    grammar: Optional[dict] = None

    @property
    def do_sample(self) -> bool:
        return self.seed is not None

    @property
    def constrained(self) -> bool:
        return self.grammar is not None

    def validate(self):
        if not (float(self.temperature) > 0.0):
            raise ValueError(
                f"temperature must be > 0, got {self.temperature}")
        if int(self.top_k) < 0:
            raise ValueError(f"top_k must be >= 0, got {self.top_k}")
        if not (0.0 < float(self.top_p) <= 1.0):
            raise ValueError(
                f"top_p must be in (0, 1], got {self.top_p}")
        if self.seed is not None and not (
                0 <= int(self.seed) < 2 ** 31):
            raise ValueError(f"seed must be a non-negative int31, "
                             f"got {self.seed}")
        if self.grammar is not None:
            if (not isinstance(self.grammar, dict)
                    or "schema" not in self.grammar
                    or "tokens" not in self.grammar):
                raise ValueError(
                    "grammar must be {'schema': ..., 'tokens': "
                    "{token_id: text}}")
        return self

    def grammar_key(self) -> Optional[str]:
        """Canonical intern key for the compiled-DFA bank."""
        if self.grammar is None:
            return None
        return json.dumps(self.grammar, sort_keys=True)

    @classmethod
    def from_payload(cls, body: Mapping) -> Optional["SamplingParams"]:
        """Build from a /generate JSON payload; None when the request
        carries no sampling field at all (pure greedy fast path)."""
        fields = ("temperature", "top_k", "top_p", "seed", "grammar")
        if not any(f in body for f in fields):
            return None
        grammar = body.get("grammar")
        if grammar is not None and isinstance(grammar.get("tokens"), dict):
            # JSON object keys arrive as strings; token ids are ints
            grammar = dict(grammar)
            grammar["tokens"] = {int(k): str(v)
                                 for k, v in grammar["tokens"].items()}
        return cls(
            temperature=float(body.get("temperature", 1.0)),
            top_k=int(body.get("top_k", 0)),
            top_p=float(body.get("top_p", 1.0)),
            seed=(None if body.get("seed") is None
                  else int(body["seed"])),
            grammar=grammar,
        ).validate()


GREEDY = SamplingParams()


# ---------------------------------------------------------------------------
# JSON-schema subset -> char NFA -> char DFA
# ---------------------------------------------------------------------------

class _NFA:
    def __init__(self):
        self.n = 0
        self.eps: Dict[int, set] = {}
        self.edges: Dict[int, Dict[str, set]] = {}

    def state(self) -> int:
        s = self.n
        self.n += 1
        return s

    def add_eps(self, a, b):
        self.eps.setdefault(a, set()).add(b)

    def add_edge(self, a, ch, b):
        self.edges.setdefault(a, {}).setdefault(ch, set()).add(b)

    def literal(self, text: str):
        """Chain of states consuming `text`; returns (start, end)."""
        start = cur = self.state()
        for ch in text:
            nxt = self.state()
            self.add_edge(cur, ch, nxt)
            cur = nxt
        return start, cur


def _json_string_literal(value) -> str:
    return json.dumps(value, ensure_ascii=False)


def _frag(nfa: _NFA, schema: dict):
    """Compile one schema node to an NFA fragment (start, end)."""
    if not isinstance(schema, dict):
        raise ValueError(f"unsupported schema node: {schema!r}")
    if "const" in schema:
        return nfa.literal(_json_string_literal(schema["const"]))
    if "enum" in schema:
        start, end = nfa.state(), nfa.state()
        for v in schema["enum"]:
            s, e = nfa.literal(_json_string_literal(v))
            nfa.add_eps(start, s)
            nfa.add_eps(e, end)
        return start, end
    typ = schema.get("type")
    if typ == "string":
        raise ValueError(
            "free-form strings are not DFA-boundable; constrain with "
            "'enum' or 'const'")
    if typ == "boolean":
        return _frag(nfa, {"enum": [True, False]})
    if typ == "integer" or typ == "number":
        # -?(0|[1-9][0-9]*) — JSON-canonical integers; 'number' shares
        # the integer grammar (fractions are out of the subset)
        start, end = nfa.state(), nfa.state()
        body = nfa.state()
        nfa.add_eps(start, body)
        nfa.add_edge(start, "-", body)
        nfa.add_edge(body, "0", end)
        loop = nfa.state()
        for d in "123456789":
            nfa.add_edge(body, d, loop)
        for d in "0123456789":
            nfa.add_edge(loop, d, loop)
        nfa.add_eps(loop, end)
        return start, end
    if typ == "object":
        props = schema.get("properties", {})
        if not props:
            return nfa.literal("{}")
        start, cur = nfa.literal("{")
        first = True
        # properties are REQUIRED and emitted in declared order — the
        # canonical serialization a constrained emitter produces; free
        # ordering would square the DFA for no modeled benefit
        for name, sub in props.items():
            prefix = ("" if first else ",") + _json_string_literal(
                str(name)) + ":"
            first = False
            ps, pe = nfa.literal(prefix)
            nfa.add_eps(cur, ps)
            vs, ve = _frag(nfa, sub)
            nfa.add_eps(pe, vs)
            cur = ve
        cs, ce = nfa.literal("}")
        nfa.add_eps(cur, cs)
        return start, ce
    if typ == "array":
        items = schema.get("items")
        if items is None:
            raise ValueError("array schema requires 'items'")
        start, cur = nfa.literal("[")
        end = nfa.state()
        min_items = int(schema.get("minItems", 0))
        if min_items == 0:
            nfa.add_eps(cur, end)    # empty array
        s0, e0 = _frag(nfa, items)
        nfa.add_eps(cur, s0)
        sep_s, sep_e = nfa.literal(",")
        nfa.add_eps(e0, sep_s)
        s1, e1 = _frag(nfa, items)
        nfa.add_eps(sep_e, s1)
        nfa.add_eps(e1, sep_s)       # unbounded repetition
        nfa.add_eps(e0, end)
        nfa.add_eps(e1, end)
        cs, ce = nfa.literal("]")
        nfa.add_eps(end, cs)
        return start, ce
    raise ValueError(f"unsupported schema type: {typ!r}")


class _CharDFA:
    """Determinized char automaton: trans[(state, ch)] -> state,
    `accept` the set of accepting states, state 0 the start."""

    def __init__(self, trans, accept, n_states):
        self.trans = trans
        self.accept = accept
        self.n_states = n_states

    def run(self, state: int, text: str) -> int:
        """Advance `state` over `text`; -1 once any char is illegal."""
        for ch in text:
            state = self.trans.get((state, ch), -1)
            if state < 0:
                return -1
        return state


def _determinize(nfa: _NFA, start: int, end: int) -> _CharDFA:
    def closure(states):
        stack, seen = list(states), set(states)
        while stack:
            s = stack.pop()
            for t in nfa.eps.get(s, ()):
                if t not in seen:
                    seen.add(t)
                    stack.append(t)
        return frozenset(seen)

    s0 = closure({start})
    ids = {s0: 0}
    order = [s0]
    trans: Dict[tuple, int] = {}
    i = 0
    while i < len(order):
        cur = order[i]
        i += 1
        chars = set()
        for s in cur:
            chars.update(nfa.edges.get(s, {}))
        for ch in sorted(chars):
            nxt = set()
            for s in cur:
                nxt.update(nfa.edges.get(s, {}).get(ch, ()))
            nc = closure(nxt)
            if nc not in ids:
                if len(ids) >= _MAX_CHAR_STATES:
                    raise ValueError(
                        "grammar too large: char-DFA exceeds "
                        f"{_MAX_CHAR_STATES} states")
                ids[nc] = len(ids)
                order.append(nc)
            trans[(ids[cur], ch)] = ids[nc]
    accept = {ids[s] for s in order if end in s}
    return _CharDFA(trans, accept, len(ids))


# ---------------------------------------------------------------------------
# token lift
# ---------------------------------------------------------------------------

class TokenDFA:
    """Token-level DFA: `trans` [S, V] int32 (-1 = forbidden),
    `accept` [S] bool (EOS legal there, as a self-loop)."""

    __slots__ = ("trans", "accept", "n_states")

    def __init__(self, trans: np.ndarray, accept: np.ndarray):
        self.trans = trans
        self.accept = accept
        self.n_states = trans.shape[0]


def compile_grammar(grammar: dict, vocab_size: int,
                    eos_token_id: Optional[int]) -> TokenDFA:
    """Compile `{"schema":..., "tokens": {id: text}}` into a TokenDFA.

    Raises ValueError when the schema is outside the subset, the token
    table cannot realize it (start state dead after pruning), or EOS is
    required to terminate but the request has none."""
    schema = grammar["schema"]
    token_strs = grammar["tokens"]
    nfa = _NFA()
    start, end = _frag(nfa, schema)
    cdfa = _determinize(nfa, start, end)

    S = cdfa.n_states
    trans = np.full((S, vocab_size), -1, np.int32)
    for tid, text in token_strs.items():
        tid = int(tid)
        if not (0 <= tid < vocab_size):
            raise ValueError(f"grammar token id {tid} outside vocab "
                             f"[0, {vocab_size})")
        if not text:
            continue                  # empty-text tokens never legal
        for q in range(S):
            r = cdfa.run(q, text)
            if r >= 0:
                trans[q, tid] = r
    accept = np.zeros(S, bool)
    accept[list(cdfa.accept)] = True
    if eos_token_id is not None and 0 <= int(eos_token_id) < vocab_size:
        # EOS legal exactly at acceptance — emitting it finishes the
        # request, the self-loop keeps the mask well-formed afterwards
        trans[accept, int(eos_token_id)] = np.nonzero(accept)[0]
    elif not accept.any():
        raise ValueError("grammar has no accepting state")

    # prune dead states to a fixpoint: a state with NO legal token is a
    # trap (if it accepts without EOS the stream merely stops early at
    # max_new_tokens — still only valid prefixes emitted — but a
    # non-accepting trap would force an illegal token, so transitions
    # into it must die too)
    changed = True
    while changed:
        changed = False
        live = (trans >= 0).any(axis=1) | accept
        for q in range(S):
            row = trans[q]
            bad = (row >= 0) & ~live[np.clip(row, 0, S - 1)]
            if bad.any():
                row[bad] = -1
                changed = True
    if not ((trans[0] >= 0).any() or accept[0]):
        raise ValueError(
            "grammar unsatisfiable with the given token table")
    return TokenDFA(trans, accept)


# ---------------------------------------------------------------------------
# per-slot table + stacked grammar bank
# ---------------------------------------------------------------------------

class SlotSamplingTable:
    """Host-side per-slot sampling state, mirrored into the jitted step
    as batched arrays every dispatch.

    The grammar bank is a FIXED-shape [1 + max_grammars, max_states, V]
    int32 tensor (row 0 = pass-through: one state, every token legal,
    self-loop) so interning a new grammar never changes the step's
    traced shapes — the device copy is cached and invalidated only when
    a compile lands a new row."""

    def __init__(self, num_slots: int, vocab_size: int,
                 max_grammars: int = 8, max_dfa_states: int = 128):
        n = int(num_slots)
        self.vocab_size = int(vocab_size)
        self.max_grammars = int(max_grammars)
        self.max_dfa_states = int(max_dfa_states)
        self.temperature = np.ones(n, np.float32)
        self.top_k = np.zeros(n, np.int32)
        self.top_p = np.ones(n, np.float32)
        self.do_sample = np.zeros(n, bool)
        self.seed = np.zeros(n, np.int32)
        self.dfa_state = np.zeros(n, np.int32)
        self.grammar_id = np.zeros(n, np.int32)
        self.bank = np.full(
            (1 + self.max_grammars, self.max_dfa_states, self.vocab_size),
            -1, np.int32)
        self.bank[0, 0, :] = 0
        self._accept = [np.array([True])]   # per-gid accept vectors
        self._interned: Dict[str, int] = {}
        self._dev_bank = None
        self._dev_args = None   # cached device copies of the per-slot arrays
        self._lock = threading.Lock()

    # -- grammar interning --
    def lookup(self, key: str) -> Optional[int]:
        """gid of an already-interned grammar, else None (the caller
        compiles outside the lock and calls intern)."""
        with self._lock:
            return self._interned.get(key)

    def intern(self, key: str, dfa: TokenDFA) -> int:
        with self._lock:
            gid = self._interned.get(key)
            if gid is not None:
                return gid
            if len(self._interned) >= self.max_grammars:
                raise ValueError(
                    f"grammar bank full ({self.max_grammars}); raise "
                    "max_grammars or retire grammars")
            if dfa.n_states > self.max_dfa_states:
                raise ValueError(
                    f"grammar needs {dfa.n_states} DFA states > "
                    f"max_dfa_states={self.max_dfa_states}")
            gid = len(self._interned) + 1
            self.bank[gid, :dfa.n_states, :] = dfa.trans
            # park unused state rows on a harmless self-loop-free -1
            self._interned[key] = gid
            while len(self._accept) <= gid:
                self._accept.append(None)
            self._accept[gid] = dfa.accept
            self._dev_bank = None
            return gid

    @property
    def grammars_compiled(self) -> int:
        return len(self._interned)

    def accept_of(self, gid: int) -> np.ndarray:
        return self._accept[gid]

    def is_terminal(self, gid: int, state: int) -> bool:
        """True when a constrained slot's grammar is fully emitted and
        has NO legal continuation (an accepting trap with no EOS) —
        the engine finishes the request rather than let the mask go
        empty next step."""
        return gid > 0 and not (self.bank[gid, state] >= 0).any()

    def device_bank(self):
        with self._lock:
            if self._dev_bank is None:
                self._dev_bank = jnp.asarray(self.bank)
            return self._dev_bank

    def device_args(self):
        """Device copies of the 7 per-slot operand arrays, rebuilt only
        when a slot binds/clears or a DFA state commits — the per-step
        host cost of sampling is then just the [N] ctr upload."""
        if self._dev_args is None:
            self._dev_args = (
                jnp.asarray(self.temperature), jnp.asarray(self.top_k),
                jnp.asarray(self.top_p), jnp.asarray(self.do_sample),
                jnp.asarray(self.seed), jnp.asarray(self.dfa_state),
                jnp.asarray(self.grammar_id))
        return self._dev_args

    def set_dfa_state(self, slot: int, state: int):
        """Commit a constrained slot's advanced DFA state (the engine's
        post-step writeback). Mutating `dfa_state` directly would leave
        the device-args cache stale — always go through here."""
        self.dfa_state[slot] = int(state)
        self._dev_args = None

    # -- slot lifecycle --
    def bind(self, slot: int, params: SamplingParams, gid: int = 0,
             dfa_state: int = 0):
        p = params or GREEDY
        self.temperature[slot] = float(p.temperature)
        self.top_k[slot] = int(p.top_k)
        self.top_p[slot] = float(p.top_p)
        self.do_sample[slot] = bool(p.do_sample)
        self.seed[slot] = 0 if p.seed is None else int(p.seed)
        self.grammar_id[slot] = int(gid)
        self.dfa_state[slot] = int(dfa_state)
        self._dev_args = None

    def clear(self, slot: int):
        self.bind(slot, GREEDY)

    def mode_counts(self, active_slots) -> Dict[str, int]:
        """Per-mode occupancy over the given active slot ids."""
        out = {"greedy": 0, "sampled": 0, "constrained": 0}
        for s in active_slots:
            if self.grammar_id[s] > 0:
                out["constrained"] += 1
            elif self.do_sample[s]:
                out["sampled"] += 1
            else:
                out["greedy"] += 1
        return out


# ---------------------------------------------------------------------------
# in-step selection (pure; traced inside the engine's one jitted step)
# ---------------------------------------------------------------------------

_BASE_KEY = jax.random.PRNGKey(0)


def lane_key(seed, index):
    """The seeding contract, exposed for tests/oracles: the key that
    draws stream token `index` of a request seeded `seed`."""
    return jax.random.fold_in(jax.random.fold_in(_BASE_KEY, seed), index)


def select_tokens(logits, adv, temperature, top_k, top_p, do_sample,
                  seed, ctr, dfa_state, grammar_id, bank):
    """[N, C, V] logits -> ([N, C] tokens, [N] new DFA states).

    `ctr[n]` is the stream index of row n's COLUMN 0 (decode rows:
    sample_offset + emitted; prefill rows: sample_offset - (adv-1), so
    the emission column adv-1 lands exactly on sample_offset — earlier
    columns' draws are discarded with their logits). The grammar mask
    of the CURRENT state applies to every column: constrained rows
    never speculate, so their single emission column is the only one
    consumed; unconstrained rows ride the pass-through row of `bank`.
    """
    N, C, V = logits.shape
    allowed = bank[grammar_id, dfa_state] >= 0          # [N, V]
    masked = jnp.where(allowed[:, None, :],
                       logits.astype(jnp.float32), -1e30)

    cols = jnp.arange(C, dtype=jnp.int32)
    keys = jax.vmap(
        lambda s, c0: jax.vmap(lambda t: lane_key(s, c0 + t))(cols)
    )(seed, ctr)                                        # [N, C, 2]

    flat = masked.reshape(N * C, V)
    rep = lambda a: jnp.repeat(a, C)
    toks = _select_token(
        flat, rep(jnp.asarray(do_sample, bool)),
        rep(temperature), rep(top_k), keys.reshape(N * C, 2),
        rep(top_p)).reshape(N, C)

    emit_col = jnp.maximum(adv - 1, 0)
    tok_e = jnp.take_along_axis(toks, emit_col[:, None], axis=1)[:, 0]
    stepped = bank[grammar_id, dfa_state, tok_e]
    new_state = jnp.where((grammar_id > 0) & (adv > 0),
                          jnp.maximum(stepped, 0), dfa_state)
    return toks, new_state


def select_next(logits, temperature, top_k, top_p, do_sample, seed, ctr):
    """Width-1 selection for the draft propose scan: [N, V] logits ->
    [N] tokens drawn on the SAME lanes the target verify will use, so
    a draft that agrees with the target proposes exactly the target's
    coin-fixed draw (seeded-replay acceptance; module docstring)."""
    keys = jax.vmap(lane_key)(seed, ctr)
    return _select_token(logits, jnp.asarray(do_sample, bool),
                         temperature, top_k, keys, top_p)
