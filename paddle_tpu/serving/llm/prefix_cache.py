"""Radix prefix cache over shared KV blocks (ISSUE 8 tentpole).

A per-tenant radix/trie index over token prefixes, one level per full
`block_len`-token chunk, each node naming the global KV page that holds
that chunk's keys/values. On admission the engine looks the prompt up:
every matched full block is ATTACHED (the new slot's block table points
at the donor's physical pages, refcounted for the reader's lifetime) and
a matched *partial* block — a trie tail, or a full block truncated by
the always-prefill-one-token cap — is COPY-ON-WRITten into the slot's
own page so the divergent suffix can append in place. The engine then
chunk-prefills only the uncovered suffix: at a full hit TTFT collapses
to one chunk-wide step, and N requests sharing a prefix cost ~1
prefill's worth of prefill work in total.

Correctness lever: chunked prefill is bit-invariant to chunking (PR 7),
and a row's KV depends only on that row's own tokens/positions, so KV
attached from a donor row — or COW-copied out of one — is bitwise the KV
the request would have computed itself. Warm streams are therefore
bit-identical to cold-path greedy `generate()`.

Lifecycle and safety:

- Pages enter the cache only when their prefill COMPLETED (the blocks
  provably hold the full chunk's KV); insertion registers them with the
  pool (`register_cached`), pinning the owning row against reallocation.
- Readers take a refcount per attached page (`SlotPagedKVPool.refcount`)
  held until the reader's slot frees. Eviction refuses refcount>0 pages
  structurally — `release_cached` raises — so cache pressure can never
  reclaim a block out from under a live stream.
- Eviction is LRU over refcount-0 leaves and tails (a deterministic
  monotonic tick, no wall clock), driven by the pool's `on_pressure`
  hook from inside `allocate()`: evict just enough to unpin one row.
- Tenant namespacing is structural: each tenant gets its own root, so
  one tenant's prompts can never attach another tenant's KV.

The index is host-side pure-python bookkeeping — dict hops per block, no
device work — sized by cached blocks, not tokens.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from .kv_pool import SlotPagedKVPool


class _Node:
    """One radix node = one full cached block. `children` is keyed by the
    next block's token tuple; `page` is the global KV page holding THIS
    node's block (None only at roots). A node may also carry one cached
    partial-block `tail` — the sub-block remainder of some inserted
    prompt — usable by COW up to its longest common prefix with a new
    prompt's remainder."""

    __slots__ = ("children", "page", "tick",
                 "tail_tokens", "tail_page", "tail_tick")

    def __init__(self, page: Optional[int] = None):
        self.children: Dict[Tuple[int, ...], "_Node"] = {}
        self.page = page
        self.tick = 0
        self.tail_tokens: Optional[Tuple[int, ...]] = None
        self.tail_page: Optional[int] = None
        self.tail_tick = 0


class AttachPlan:
    """Result of a cache lookup, increfs already taken.

    `pages` back the prompt's leading full blocks (held until the
    reader's slot frees — `SlotPagedKVPool.free` drops them). `tail_page`
    holds `tail_len` further tokens to COW into the slot's own page; its
    refcount is transient — release via `PrefixCache.release_tail` right
    after the copy. `attach_len = len(pages) * block_len + tail_len` is
    the number of prompt tokens the engine may skip prefilling."""

    __slots__ = ("pages", "attach_len", "tail_page", "tail_len")

    def __init__(self, pages: List[int], attach_len: int,
                 tail_page: Optional[int], tail_len: int):
        self.pages = pages
        self.attach_len = attach_len
        self.tail_page = tail_page
        self.tail_len = tail_len


def _tenant_stats() -> dict:
    return {"hits": 0, "misses": 0, "hit_tokens": 0, "lookup_tokens": 0,
            "insertions": 0, "evictions": 0, "cached_blocks": 0}


class PrefixCache:
    """Per-tenant radix index over cached KV pages in a SlotPagedKVPool.

    Constructing the cache wires itself as the pool's `on_pressure` hook
    so allocation pressure transparently evicts cold entries.

    `name` labels which pool this cache fronts (ISSUE 17: the engine runs
    a "target" cache and, with a draft model attached, a parallel "draft"
    cache over the draft pool — both tries are keyed by the same prompt
    tokens and the same page-aligned block_len, so a prompt that warm-hits
    on the target side attaches the congruent draft pages too and the
    draft skips re-prefilling the shared prefix)."""

    def __init__(self, pool: SlotPagedKVPool, name: str = "target",
                 host_pool=None, clock=None):
        self.pool = pool
        self.name = name
        self.block_len = pool.block_len
        self._roots: Dict[str, _Node] = {}
        self._tick = 0
        self.stats = _tenant_stats()
        self.tenant_stats: Dict[str, dict] = {}
        # ISSUE 19 spill tier: when a HostKVPool is attached, pressure
        # eviction of a refcount-0 FULL block serializes its page to host
        # RAM (keyed by tenant + full token path) before releasing it, so
        # a later admission can re-onboard it instead of re-prefilling.
        # Tails (partial blocks) are dropped as before — see host_kv.py.
        self.host_pool = host_pool
        # optional clock (engine passes clock.now) so spill copy time is
        # attributable: the engine books the delta into the ledger's
        # `kv_spill` phase each pump
        self.clock = clock
        self.spill_seconds = 0.0
        self.spilled_pages = 0
        pool.on_pressure = self.evict_for_pressure

    def _ts(self, tenant: str) -> dict:
        return self.tenant_stats.setdefault(tenant, _tenant_stats())

    # ---- lookup ----
    def acquire(self, tenant: str, tokens, max_tokens: int) -> AttachPlan:
        """Match `tokens` against the tenant's trie and take refcounts on
        every matched page. `max_tokens` caps the covered length — the
        engine passes len(prompt)-1 so at least one prompt token is
        always prefilled (the step that produces the first output
        token's logits). A full matched block pushed over the cap
        becomes a partially-used COW tail, which is what makes an
        exact-duplicate prompt still cost only a one-token prefill."""
        self._tick += 1
        ts = self._ts(tenant)
        n = len(tokens)
        ts["lookup_tokens"] += n
        self.stats["lookup_tokens"] += n
        bl = self.block_len
        node = self._roots.get(tenant)
        chain: List[int] = []
        i = 0
        if node is not None:
            while i + bl <= n:
                child = node.children.get(
                    tuple(int(t) for t in tokens[i:i + bl]))
                if child is None:
                    break
                child.tick = self._tick
                chain.append(child.page)
                node = child
                i += bl
        n_full = min(len(chain), max(0, int(max_tokens)) // bl)
        pages = chain[:n_full]
        attach_len = n_full * bl
        tail_page: Optional[int] = None
        tail_len = 0
        if n_full < len(chain):
            # next matched block exists but the cap truncates it
            u = int(max_tokens) - attach_len
            if u > 0:
                tail_page = chain[n_full]
                tail_len = u
        elif node is not None and node.tail_tokens is not None:
            rem = [int(t) for t in tokens[attach_len:]]
            m = 0
            for a, b in zip(node.tail_tokens, rem):
                if a != b:
                    break
                m += 1
            u = min(m, int(max_tokens) - attach_len)
            if u > 0:
                tail_page = node.tail_page
                tail_len = u
                node.tail_tick = self._tick
        hit_tokens = attach_len + tail_len
        if hit_tokens > 0:
            ts["hits"] += 1
            self.stats["hits"] += 1
            ts["hit_tokens"] += hit_tokens
            self.stats["hit_tokens"] += hit_tokens
        else:
            ts["misses"] += 1
            self.stats["misses"] += 1
        for p in pages:
            self.pool.refcount[p] = self.pool.refcount.get(p, 0) + 1
        if tail_page is not None:
            self.pool.refcount[tail_page] = \
                self.pool.refcount.get(tail_page, 0) + 1
        return AttachPlan(pages, attach_len + tail_len, tail_page, tail_len)

    def probe(self, tenant: str, tokens) -> int:
        """Read-only lookup: the longest block-aligned cached prefix of
        `tokens` in the tenant's trie, in tokens. Unlike `acquire` it
        takes no refcounts and touches no ticks or stats — the router
        probes every candidate replica per admission, and a probe must
        never distort LRU order or hit-rate accounting, let alone pin
        pages on replicas that lose the election."""
        node = self._roots.get(tenant)
        if node is None:
            return 0
        bl = self.block_len
        n = len(tokens)
        i = 0
        while i + bl <= n:
            child = node.children.get(
                tuple(int(t) for t in tokens[i:i + bl]))
            if child is None:
                break
            node = child
            i += bl
        return i

    def release_tail(self, plan: AttachPlan):
        """Drop the transient tail refcount once its KV has been COW'd
        into the reader's own page."""
        if plan.tail_page is not None:
            self.pool.release_block(plan.tail_page)
            plan.tail_page = None

    def release(self, plan: AttachPlan):
        """Drop ALL of acquire()'s transient refcounts: call after the
        reader holds its own protection — attach_blocks() took per-slot
        refs on the full pages and the tail was COW'd into the slot's
        own page. Idempotent (the plan is cleared as it is released)."""
        for p in plan.pages:
            self.pool.release_block(p)
        plan.pages = []
        self.release_tail(plan)

    # ---- insertion ----
    def insert(self, tenant: str, tokens, slot: int,
               attached_pages: List[int]):
        """Index a completed prefill. Called by the engine the moment the
        final prefill chunk commits (slot still active, full prompt KV
        provably in place). Path nodes the prompt attached from already
        exist (their refcounts kept them alive); every NEW node claims
        the slot's own page for that block index and pins it via
        `register_cached`. The sub-block remainder becomes the terminal
        node's tail, replacing a shorter refcount-0 tail only."""
        self._tick += 1
        ts = self._ts(tenant)
        bl = self.block_len
        nb_pool = self.pool.n_blocks
        node = self._roots.setdefault(tenant, _Node())
        n_full = len(tokens) // bl
        for j in range(n_full):
            key = tuple(int(t) for t in tokens[j * bl:(j + 1) * bl])
            child = node.children.get(key)
            if child is None:
                page = (attached_pages[j] if j < len(attached_pages)
                        else slot * nb_pool + j)
                if page in self.pool.cached:
                    # defensive: never double-register (an attached page
                    # is only reachable through an existing node)
                    node = node.children.setdefault(key, _Node(page))
                    continue
                self.pool.register_cached(page)
                child = _Node(page)
                node.children[key] = child
                ts["insertions"] += 1
                self.stats["insertions"] += 1
                ts["cached_blocks"] += 1
                self.stats["cached_blocks"] += 1
            child.tick = self._tick
            node = child
        rem = tuple(int(t) for t in tokens[n_full * bl:])
        if rem:
            if node.tail_tokens is None or (
                    len(rem) > len(node.tail_tokens)
                    and self.pool.refcount.get(node.tail_page, 0) == 0):
                page = slot * nb_pool + n_full
                if page in self.pool.cached or page == node.tail_page:
                    return
                if node.tail_page is not None:
                    self.pool.release_cached(node.tail_page)
                    ts["cached_blocks"] -= 1
                    self.stats["cached_blocks"] -= 1
                self.pool.register_cached(page)
                node.tail_tokens = rem
                node.tail_page = page
                node.tail_tick = self._tick
                ts["insertions"] += 1
                self.stats["insertions"] += 1
                ts["cached_blocks"] += 1
                self.stats["cached_blocks"] += 1

    # ---- eviction ----
    def _lru_victim(self):
        """Least-recently-touched evictable entry across all tenants:
        refcount-0 tails, and refcount-0 leaf nodes (no children AND no
        tail — interior nodes and tailed nodes are structurally pinned
        until their descendants go first). Each candidate carries the
        victim block's FULL token path from the prefix start — the
        content address the host spill tier is keyed by (ISSUE 19)."""
        best = None   # (tick, kind, tenant, node_or_parent, key, path)
        for tenant, root in self._roots.items():
            stack: List[Tuple[_Node, Optional[_Node],
                              Optional[Tuple[int, ...]],
                              Tuple[int, ...]]] = \
                [(root, None, None, ())]
            while stack:
                node, parent, key, path = stack.pop()
                if (node.tail_page is not None
                        and self.pool.refcount.get(node.tail_page, 0) == 0):
                    cand = (node.tail_tick, "tail", tenant, node, None, path)
                    if best is None or cand[0] < best[0]:
                        best = cand
                if (parent is not None and not node.children
                        and node.tail_page is None
                        and self.pool.refcount.get(node.page, 0) == 0):
                    cand = (node.tick, "node", tenant, parent, key, path)
                    if best is None or cand[0] < best[0]:
                        best = cand
                for k, c in node.children.items():
                    stack.append((c, node, k, path + k))
        return best

    def evict_for_pressure(self) -> int:
        """Pool pressure hook: evict LRU refcount-0 entries until the
        pool has an allocatable row (or nothing evictable remains).
        Returns pages released. Pages with live readers never qualify,
        so eviction under slot pressure cannot reclaim a block a stream
        is still reading — the fault matrix proves this."""
        released = 0
        while not self.pool.has_allocatable_row():
            victim = self._lru_victim()
            if victim is None:
                break
            _, kind, tenant, holder, key, path = victim
            ts = self._ts(tenant)
            if kind == "tail":
                self.pool.release_cached(holder.tail_page)
                holder.tail_tokens = None
                holder.tail_page = None
                holder.tail_tick = 0
            else:
                child = holder.children.pop(key)
                if self.host_pool is not None:
                    # spill the full block to the host tier before the
                    # page is released (refcount is provably 0 here, so
                    # the device copy is quiescent — the export is the
                    # exact KV the trie indexed)
                    t0 = self.clock() if self.clock is not None else None
                    self.host_pool.put(
                        tenant, path, self.pool.export_page(child.page))
                    self.spilled_pages += 1
                    if t0 is not None:
                        self.spill_seconds += self.clock() - t0
                self.pool.release_cached(child.page)
            ts["evictions"] += 1
            self.stats["evictions"] += 1
            ts["cached_blocks"] -= 1
            self.stats["cached_blocks"] -= 1
            released += 1
        return released

    def clear(self, only=None) -> int:
        """Release cached pages and drop their trie(s), keeping the pool
        ledger balanced. Used on an in-place weight swap (ISSUE 16):
        cached KV was computed under the old weights, and attaching it to
        a new-version prompt would stitch two weight sets inside one
        attention window. Caller must hold the engine idle (acquire-plan
        refcounts all released); cached pins are dropped here.

        `only` (ISSUE 20) is an optional namespace predicate: an adapter
        hot-swap invalidates exactly that adapter's `(tenant, adapter)`
        namespaces, leaving base/other-adapter tries warm. None keeps
        the original flush-everything contract. Returns pages
        released."""
        released = 0
        victims = [t for t in self._roots
                   if only is None or only(t)]
        for tenant in victims:
            root = self._roots[tenant]
            ts = self._ts(tenant)
            stack: List[Tuple[_Node, bool]] = [(root, True)]
            while stack:
                node, is_root = stack.pop()
                if node.tail_page is not None:
                    self.pool.release_cached(node.tail_page)
                    node.tail_tokens = None
                    node.tail_page = None
                    released += 1
                    ts["evictions"] += 1
                    self.stats["evictions"] += 1
                if not is_root and node.page is not None:
                    self.pool.release_cached(node.page)
                    released += 1
                    ts["evictions"] += 1
                    self.stats["evictions"] += 1
                for c in node.children.values():
                    stack.append((c, False))
            self.stats["cached_blocks"] -= ts["cached_blocks"]
            ts["cached_blocks"] = 0
            del self._roots[tenant]
        if only is None:
            self._roots.clear()
            self.stats["cached_blocks"] = 0
        if self.host_pool is not None:
            # spilled KV is a function of the weights that computed it —
            # a weight swap poisons the host tier the same way it poisons
            # the device trie (adapter-scoped when `only` is)
            self.host_pool.clear(only=only)
        return released

    # ---- views ----
    def cached_blocks(self, tenant: Optional[str] = None) -> int:
        if tenant is None:
            return self.stats["cached_blocks"]
        return self._ts(tenant)["cached_blocks"]

    def hit_rate(self, tenant: Optional[str] = None) -> float:
        s = self.stats if tenant is None else self._ts(tenant)
        if s["lookup_tokens"] == 0:
            return 0.0
        return s["hit_tokens"] / s["lookup_tokens"]

    def snapshot(self) -> dict:
        return {
            "name": self.name,
            **self.stats,
            "hit_rate": self.hit_rate(),
            "tenants": {t: {**s, "hit_rate":
                            (s["hit_tokens"] / s["lookup_tokens"]
                             if s["lookup_tokens"] else 0.0)}
                        for t, s in self.tenant_stats.items()},
        }
