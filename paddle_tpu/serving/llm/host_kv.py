"""Host-RAM KV spill tier (ISSUE 19).

One chip's HBM caps the radix prefix cache's hit rate: under slot
pressure `PrefixCache.evict_for_pressure` releases exactly the pages the
next burst of traffic wants back, and every release used to turn a
would-be cache hit into a full re-prefill. `HostKVPool` is the tier
below the device pool: a bounded, byte-budgeted LRU of **owned host
numpy copies** of evicted full-block KV pages, namespaced per tenant.

Keying. A page's KV depends on every token before it, so a block is
addressed by its FULL token path from the prefix start:
``(tenant, (t0, t1, ..., t_{(j+1)*block_len - 1}))``. That makes the
host tier content-addressed the same way the device radix trie is —
two tenants with identical token streams never share an entry (same
isolation contract as the per-tenant radix roots), and a block is only
onboardable when *all* of its predecessors are also covered (the engine
walks block by block from the device-cached boundary).

Only FULL blocks spill. COW tails are partial blocks under a node that
may itself be evicted; re-onboarding a tail without its parent would
leave a hole, and a tail is at most ``block_len - 1`` tokens of
re-prefill — not worth the bookkeeping. Tails are simply dropped on
eviction, as before.

Values are plain per-layer ``(k, v)`` numpy pairs shaped
``[kv_heads, block_len, head_dim]`` — the exact payload
`SlotPagedKVPool.export_page` produces and the engine's onboard path
writes back with a `dynamic_update_slice`, so the round trip is bitwise
(pinned by tests/test_tiered.py).

Thread safety: one lock around the OrderedDict; callers (the engine
pump and `evict_for_pressure`, both under the engine lock today) stay
correct if that ever changes.
"""
from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

import numpy as np

Layers = List[Tuple[np.ndarray, np.ndarray]]
_Key = Tuple[str, Tuple[int, ...]]


class HostKVPool:
    """Bounded LRU of spilled KV pages, keyed ``(tenant, token_path)``."""

    def __init__(self, byte_budget: int, block_len: int):
        if byte_budget <= 0:
            raise ValueError(f"byte_budget must be > 0, got {byte_budget}")
        if block_len <= 0:
            raise ValueError(f"block_len must be > 0, got {block_len}")
        self.byte_budget = int(byte_budget)
        self.block_len = int(block_len)
        self._lock = threading.Lock()
        self._pages: "OrderedDict[_Key, Layers]" = OrderedDict()
        self._sizes: Dict[_Key, int] = {}
        self.bytes_used = 0
        self.stats: Dict[str, int] = {
            "spills": 0,        # pages accepted by put()
            "onboards": 0,      # pages served by get()
            "hits": 0,          # get() found the key
            "misses": 0,        # get() did not
            "evictions": 0,     # pages LRU-evicted to stay under budget
            "rejected": 0,      # pages refused (single page over budget)
        }

    @staticmethod
    def _key(tenant: str, path) -> _Key:
        return (str(tenant), tuple(int(t) for t in path))

    # ---- spill side ----
    def put(self, tenant: str, path, layers: Layers) -> bool:
        """Admit one evicted full-block page. `path` is the block's full
        token path (length must be a block_len multiple). Returns False
        when the page alone exceeds the byte budget (refused, counted)."""
        key = self._key(tenant, path)
        if len(key[1]) == 0 or len(key[1]) % self.block_len != 0:
            raise ValueError(
                f"path length {len(key[1])} is not a positive multiple of "
                f"block_len={self.block_len}")
        # np.array(copy=True): ascontiguousarray would alias an already-
        # contiguous input, and an aliased page silently mutates when the
        # caller reuses its buffer — the host tier must own its bytes
        owned = [(np.array(k, copy=True, order="C"),
                  np.array(v, copy=True, order="C"))
                 for k, v in layers]
        size = sum(k.nbytes + v.nbytes for k, v in owned)
        with self._lock:
            if size > self.byte_budget:
                self.stats["rejected"] += 1
                return False
            if key in self._pages:        # refresh in place
                self.bytes_used -= self._sizes[key]
                self._pages.pop(key)
            while self._pages and self.bytes_used + size > self.byte_budget:
                old_key, _ = self._pages.popitem(last=False)
                self.bytes_used -= self._sizes.pop(old_key)
                self.stats["evictions"] += 1
            self._pages[key] = owned
            self._sizes[key] = size
            self.bytes_used += size
            self.stats["spills"] += 1
            return True

    # ---- onboard side ----
    def get(self, tenant: str, path) -> Optional[Layers]:
        """Fetch one page for re-onboarding; bumps LRU recency. Returns
        None on miss. The stored arrays are returned directly (read-only
        by convention — the onboard path only uploads them)."""
        key = self._key(tenant, path)
        with self._lock:
            layers = self._pages.get(key)
            if layers is None:
                self.stats["misses"] += 1
                return None
            self._pages.move_to_end(key)
            self.stats["hits"] += 1
            self.stats["onboards"] += 1
            return layers

    def probe(self, tenant: str, tokens) -> int:
        """Read-only: longest prefix of `tokens` (in whole blocks, in
        tokens) fully covered by spilled pages. No LRU bump, no stats —
        safe for router placement scoring (mirrors PrefixCache.probe)."""
        toks = [int(t) for t in tokens]
        bl = self.block_len
        covered = 0
        with self._lock:
            j = 0
            while (j + 1) * bl <= len(toks):
                key = (str(tenant), tuple(toks[:(j + 1) * bl]))
                if key not in self._pages:
                    break
                covered = (j + 1) * bl
                j += 1
        return covered

    # ---- maintenance / views ----
    def clear(self, only=None):
        """Drop spilled pages — called on weight swap: spilled KV is a
        pure function of (weights, tokens), so stale-version pages are
        poison. `only` (ISSUE 20) is an optional predicate on the
        namespace key: an adapter hot-swap drops exactly that adapter's
        pages; None drops everything."""
        with self._lock:
            if only is None:
                self._pages.clear()
                self._sizes.clear()
                self.bytes_used = 0
                return
            for key in [k for k in self._pages if only(k[0])]:
                self._pages.pop(key)
                self.bytes_used -= self._sizes.pop(key)

    @property
    def pages(self) -> int:
        with self._lock:
            return len(self._pages)

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "pages": len(self._pages),
                "bytes": self.bytes_used,
                "byte_budget": self.byte_budget,
                **dict(self.stats),
            }
