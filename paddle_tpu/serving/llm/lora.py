"""Multi-adapter LoRA bank for the unified prefill+decode step.

Holds K stacked adapter trees on device — per decoder layer, per target site,
``A [K, r, in]`` / ``B [K, out, r]`` — plus a per-slot ``adapter_idx`` lane.
The ONE jitted unified step gathers each row's bank entry inside the dispatch
(ops/lora.py), so adapters load/swap/unload without a single recompile: the
operand shapes never change, only the values.  Row 0 is all-zeros and is what
``adapter=None`` slots ride — exact-zero delta, bit-identical to base.

Mutations go through host numpy staging + a cached device mirror (the
``SlotSamplingTable`` idiom): a row load rebuilds only the touched layer/site
arrays; binding a slot invalidates only the tiny [N] idx upload.
"""
from __future__ import annotations

import threading
from typing import Dict, List, Optional

import jax.numpy as jnp
import numpy as np

from ...tuning.lora import adapter_signature, target_sites


class AdapterError(ValueError):
    """Typed refusal: an adapter tree does not fit this bank/base model.

    reason in {"bank_full", "unknown_adapter", "adapter_mismatch",
    "rank_mismatch", "targets_mismatch", "layers_mismatch"}.
    """

    def __init__(self, msg, reason="adapter_mismatch"):
        super().__init__(msg)
        self.reason = reason


class AdapterBank:
    def __init__(self, model, max_adapters: int, rank: int,
                 num_slots: int, default_alpha: Optional[float] = None):
        if max_adapters < 1:
            raise ValueError(f"max_adapters must be >= 1, got {max_adapters}")
        if rank < 1:
            raise ValueError(f"rank must be >= 1, got {rank}")
        sites, arch = target_sites(model)
        self.arch = arch
        self.rank = int(rank)
        self.max_adapters = int(max_adapters)
        self.num_rows = self.max_adapters + 1  # row 0 = base pass-through
        self.default_alpha = (2.0 * rank if default_alpha is None
                              else float(default_alpha))
        self.signature = adapter_signature(model, rank)
        self._site_dims = sites[0]            # {site: (in, out)} — homogeneous
        self._num_layers = len(sites)
        K = self.num_rows
        self._A: List[Dict[str, jnp.ndarray]] = [
            {name: jnp.zeros((K, self.rank, i), jnp.float32)
             for name, (i, o) in dims.items()} for dims in sites]
        self._B: List[Dict[str, jnp.ndarray]] = [
            {name: jnp.zeros((K, o, self.rank), jnp.float32)
             for name, (i, o) in dims.items()} for dims in sites]
        self._scale = np.zeros(K, np.float32)
        self._slot_rows = np.zeros(int(num_slots), np.int32)
        self._rows: Dict[str, int] = {}
        self._free = list(range(1, K))
        self._dev_layers = None
        self._dev_slot = None
        self.version = 0          # bumped on every row mutation
        self._lock = threading.Lock()

    # -- registry --
    @property
    def adapter_ids(self):
        with self._lock:
            return sorted(self._rows)

    def row_of(self, adapter_id: str) -> Optional[int]:
        with self._lock:
            return self._rows.get(adapter_id)

    def validate_tree(self, tree, rank: Optional[int] = None):
        """Typed refusal when a tree's rank/target-module signature
        mismatches the base model this bank was built for."""
        r = self.rank if rank is None else int(rank)
        if r != self.rank:
            raise AdapterError(
                f"adapter rank {r} != bank rank {self.rank}",
                reason="rank_mismatch")
        if not isinstance(tree, dict):
            raise AdapterError(
                f"adapter tree must be a dict, got {type(tree).__name__}")
        want_layers = {str(i) for i in range(self._num_layers)}
        if set(tree) != want_layers:
            raise AdapterError(
                f"adapter covers layers {sorted(tree)} but base model has "
                f"layers {sorted(want_layers)}", reason="layers_mismatch")
        want_sites = set(self._site_dims)
        for li, layer_tree in tree.items():
            if set(layer_tree) != want_sites:
                raise AdapterError(
                    f"layer {li} adapts {sorted(layer_tree)} but the bank "
                    f"targets {sorted(want_sites)}",
                    reason="targets_mismatch")
            for name, entry in layer_tree.items():
                in_f, out_f = self._site_dims[name]
                A, B = np.asarray(entry["A"]), np.asarray(entry["B"])
                if A.shape != (self.rank, in_f) or \
                        B.shape != (out_f, self.rank):
                    raise AdapterError(
                        f"layer {li} site {name!r}: got A{A.shape}/"
                        f"B{B.shape}, base model wants "
                        f"A{(self.rank, in_f)}/B{(out_f, self.rank)}",
                        reason="adapter_mismatch")

    def load(self, adapter_id: str, tree, alpha: Optional[float] = None) -> int:
        """Upsert an adapter into a bank row (hot swap when it exists).

        Validates against the base-model signature first (typed refusal),
        then rewrites the row's slices functionally — the step's operand
        shapes are untouched, so no recompile.  Returns the row index.
        """
        adapter_id = str(adapter_id)
        if not adapter_id:
            raise AdapterError("adapter_id must be non-empty",
                               reason="unknown_adapter")
        self.validate_tree(tree)
        with self._lock:
            row = self._rows.get(adapter_id)
            if row is None:
                if not self._free:
                    raise AdapterError(
                        f"adapter bank full ({self.max_adapters} rows); "
                        "unload an adapter first", reason="bank_full")
                row = self._free.pop(0)
                self._rows[adapter_id] = row
            self._write_row_locked(row, tree,
                                   self.default_alpha if alpha is None
                                   else float(alpha))
            return row

    def _write_row_locked(self, row, tree, alpha):
        for i in range(self._num_layers):
            layer_tree = tree[str(i)]
            for name in self._site_dims:
                A = jnp.asarray(np.asarray(layer_tree[name]["A"],
                                           np.float32))
                B = jnp.asarray(np.asarray(layer_tree[name]["B"],
                                           np.float32))
                self._A[i][name] = self._A[i][name].at[row].set(A)
                self._B[i][name] = self._B[i][name].at[row].set(B)
        self._scale[row] = float(alpha) / self.rank
        self._dev_layers = None
        self._dev_slot = None
        self.version += 1

    def _zero_row_locked(self, row):
        for i in range(self._num_layers):
            for name in self._site_dims:
                self._A[i][name] = self._A[i][name].at[row].set(0.0)
                self._B[i][name] = self._B[i][name].at[row].set(0.0)
        self._scale[row] = 0.0
        self._dev_layers = None
        self._dev_slot = None
        self.version += 1

    def unload(self, adapter_id: str):
        with self._lock:
            row = self._rows.pop(adapter_id, None)
            if row is None:
                raise AdapterError(f"unknown adapter {adapter_id!r}",
                                   reason="unknown_adapter")
            self._zero_row_locked(row)
            self._free.insert(0, row)

    def snapshot_row(self, adapter_id: str):
        """Host copy of an adapter's current row (None when absent) — the
        rollback token a hot swap stashes before overwriting."""
        with self._lock:
            row = self._rows.get(adapter_id)
            if row is None:
                return None
            tree = {}
            for i in range(self._num_layers):
                tree[str(i)] = {
                    name: {"A": np.asarray(self._A[i][name][row]),
                           "B": np.asarray(self._B[i][name][row])}
                    for name in self._site_dims}
            return {"tree": tree,
                    "alpha": float(self._scale[row]) * self.rank}

    def restore(self, adapter_id: str, snap):
        """Roll a row back to a snapshot_row() token; None = unload."""
        if snap is None:
            self.unload(adapter_id)
            return
        self.load(adapter_id, snap["tree"], alpha=snap["alpha"])

    # -- per-slot lane --
    def bind_slot(self, slot: int, adapter_id: Optional[str]) -> int:
        if adapter_id is None or adapter_id == "":
            row = 0
        else:
            with self._lock:
                row = self._rows.get(adapter_id)
            if row is None:
                raise AdapterError(f"unknown adapter {adapter_id!r}",
                                   reason="unknown_adapter")
        self._slot_rows[slot] = row
        self._dev_slot = None
        return row

    def clear_slot(self, slot: int):
        self._slot_rows[slot] = 0
        self._dev_slot = None

    def slot_row(self, slot: int) -> int:
        return int(self._slot_rows[slot])

    def adapter_of_row(self, row: int) -> Optional[str]:
        if row == 0:
            return None
        with self._lock:
            for aid, r in self._rows.items():
                if r == row:
                    return aid
        return None

    # -- jit operands --
    def device_args(self):
        """(per_layer_banks, adapter_idx [N], scale [K]) — the adapters
        operand of make_decoder_fns.  Pytree structure is fixed for the
        bank's lifetime; only leaf values change as adapters churn."""
        if self._dev_layers is None:
            self._dev_layers = tuple(
                {name: (self._A[i][name], self._B[i][name])
                 for name in self._site_dims}
                for i in range(self._num_layers))
        if self._dev_slot is None:
            self._dev_slot = (jnp.asarray(self._slot_rows),
                              jnp.asarray(self._scale))
        idx, scale = self._dev_slot
        return self._dev_layers, idx, scale

    def args_for_rows(self, rows):
        """Adapters operand for an ad-hoc batch (canary probes, blame
        probes): same banks, explicit row per batch row."""
        if self._dev_layers is None:
            self.device_args()
        return (self._dev_layers,
                jnp.asarray(np.asarray(rows, np.int32)),
                jnp.asarray(self._scale))

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "rank": self.rank,
                "max_adapters": self.max_adapters,
                "loaded": sorted(self._rows),
                "free_rows": len(self._free),
                "version": self.version,
            }
