"""Deterministic simulation harness for the batching engine.

Scripted arrival traces replayed against a `SimClock`-driven, threadless
engine: the harness advances the clock to each scheduler-relevant instant
(arrival, max_wait flush, deadline expiry) and calls `engine.pump()` there.
No real sleeps, no scheduler thread, no wall-clock flake — the exact
production scheduler (`BatchingEngine.pump`) runs at exact instants, which
is what makes assertions like "64 arrivals at max_batch=8 → ≤ 9 dispatches"
provable in a unit test.

    clock = SimClock()
    engine = BatchingEngine(fn, EngineConfig(max_batch_size=8), clock=clock)
    report = replay(engine, poisson_trace(64, rate_hz=2000, make_inputs=mk))
    assert report.dispatches <= 9

`bench.py --serve` replays the same kind of trace against a real clock for
measured latency/throughput rows.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, List, Optional, Sequence

import numpy as np

from .clock import SimClock
from .engine import BatchingEngine, RejectedError


@dataclass
class Arrival:
    t: float                      # seconds on the engine clock
    inputs: list                  # per-request input arrays (leading dim)
    deadline_ms: Optional[float] = None


def poisson_trace(n: int, rate_hz: float, make_inputs: Callable[[int], list],
                  seed: int = 0, deadline_ms: Optional[float] = None
                  ) -> List[Arrival]:
    """Seeded exponential inter-arrivals — deterministic 'open-loop' load."""
    rng = np.random.RandomState(seed)
    t = 0.0
    out = []
    for i in range(n):
        t += float(rng.exponential(1.0 / rate_hz))
        out.append(Arrival(t=t, inputs=make_inputs(i),
                           deadline_ms=deadline_ms))
    return out


def uniform_trace(n: int, interval_s: float,
                  make_inputs: Callable[[int], list],
                  deadline_ms: Optional[float] = None) -> List[Arrival]:
    return [Arrival(t=i * interval_s, inputs=make_inputs(i),
                    deadline_ms=deadline_ms) for i in range(n)]


@dataclass
class ReplayReport:
    outcomes: List[str] = field(default_factory=list)  # per arrival, in order
    results: List[Optional[list]] = field(default_factory=list)
    errors: List[Optional[BaseException]] = field(default_factory=list)
    dispatches: int = 0
    metrics: dict = field(default_factory=dict)

    @property
    def completed(self) -> int:
        return self.outcomes.count("completed")

    @property
    def rejected(self) -> int:
        return self.outcomes.count("rejected")

    @property
    def expired(self) -> int:
        return self.outcomes.count("expired")


def replay(engine: BatchingEngine, arrivals: Sequence[Arrival],
           settle_s: float = 1.0) -> ReplayReport:
    """Drive `engine` (threadless, sharing a SimClock) through the trace.

    Between consecutive arrivals the clock stops at every due flush/deadline
    instant and pumps there — exactly what the scheduler thread's condition
    timeout does in production. After the last arrival the engine is drained
    (`stop(drain=True)`) and the report collects every future's outcome.
    """
    clock = engine.clock
    if not isinstance(clock, SimClock):
        raise TypeError("replay() needs the engine on a SimClock; got "
                        f"{type(clock).__name__}")
    report = ReplayReport()
    futures = []
    for a in sorted(arrivals, key=lambda x: x.t):
        # fire time-driven scheduler actions due strictly before this arrival
        while True:
            nxt = engine.next_event_time()
            if nxt is None or nxt > a.t:
                break
            clock.advance_to(nxt)
            report.dispatches += engine.pump()
        clock.advance_to(a.t)
        try:
            futures.append(engine.submit(a.inputs,
                                         deadline_ms=a.deadline_ms))
        except RejectedError as e:
            futures.append(e)
        report.dispatches += engine.pump()  # size-triggered flush, same t
    # drain the tail: run out the remaining flush/deadline instants, then
    # a final settle window so nothing is left pending
    while True:
        nxt = engine.next_event_time()
        if nxt is None:
            break
        clock.advance_to(nxt)
        report.dispatches += engine.pump()
    clock.advance(settle_s)
    engine.stop(drain=True)

    for fut in futures:
        if isinstance(fut, RejectedError):
            report.outcomes.append("rejected")
            report.results.append(None)
            report.errors.append(fut)
            continue
        exc = fut.exception(timeout=0)
        if exc is None:
            report.outcomes.append("completed")
            report.results.append(fut.result(timeout=0))
            report.errors.append(None)
        else:
            from .engine import DeadlineExceededError
            report.outcomes.append(
                "expired" if isinstance(exc, DeadlineExceededError)
                else "failed")
            report.results.append(None)
            report.errors.append(exc)
    report.metrics = engine.metrics.snapshot()
    return report
