"""Sharded + async checkpointing and epoch-range auto-resume.

Reference: paddle.save/load pickle state (python/paddle/framework/io.py:550,766),
fleet-aware save (fleet_base.py:654-732), and the auto-checkpoint epoch-range
protocol (fluid/incubate/checkpoint/auto_checkpoint.py — snapshots keyed by job
id enabling elastic resume).

TPU-native: sharded jax arrays are written via orbax (each host writes its own
shards; restore re-shards to the current mesh), with an async option so the
train loop overlaps the write. The epoch-range protocol is kept verbatim:
`for epoch in train_epoch_range(n, ckpt_dir): ...` resumes mid-run after
preemption/elastic restart.
"""
from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, Optional

import jax
import numpy as np

from .core.tensor import Tensor

try:
    import orbax.checkpoint as ocp
    _HAS_ORBAX = True
except Exception:  # pragma: no cover
    _HAS_ORBAX = False


def _to_arrays(tree):
    return jax.tree_util.tree_map(
        lambda x: x.data if isinstance(x, Tensor) else x, tree,
        is_leaf=lambda x: isinstance(x, Tensor))


def _is_sharded(tree) -> bool:
    leaves = jax.tree_util.tree_leaves(tree)
    for leaf in leaves:
        if hasattr(leaf, "sharding") and not getattr(
                leaf.sharding, "is_fully_replicated", True):
            return True
    return False


class CheckpointManager:
    """Step-indexed checkpoint directory with retention + async save.

    usage:
        mgr = CheckpointManager(dir, max_to_keep=3, async_save=True)
        mgr.save(step, {"params": ..., "opt": ..., "meta": {...}})
        state = mgr.restore(step=None)   # latest
    """

    def __init__(self, directory: str, max_to_keep: int = 3,
                 async_save: bool = False):
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self._max_to_keep = max_to_keep
        self._async = async_save and _HAS_ORBAX
        if _HAS_ORBAX:
            opts = ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, enable_async_checkpointing=self._async)
            self._mgr = ocp.CheckpointManager(self.directory, options=opts)
        else:
            self._mgr = None

    def save(self, step: int, state: Dict[str, Any], force: bool = False):
        state = _to_arrays(state)
        if self._mgr is not None:
            self._mgr.save(step, args=ocp.args.StandardSave(state),
                           force=force)
        else:  # fallback: pickle per step (replicated arrays only)
            from .framework_io import save as _save
            _save(state, os.path.join(self.directory, f"step_{step}.pdckpt"))
            self._gc()

    def restore(self, step: Optional[int] = None,
                template: Optional[Dict[str, Any]] = None):
        if self._mgr is not None:
            step = self.latest_step() if step is None else step
            if step is None:
                return None
            if template is not None:
                return self._mgr.restore(
                    step, args=ocp.args.StandardRestore(_to_arrays(template)))
            return self._mgr.restore(step)
        from .framework_io import load as _load
        step = self.latest_step() if step is None else step
        if step is None:
            return None
        return _load(os.path.join(self.directory, f"step_{step}.pdckpt"))

    def latest_step(self) -> Optional[int]:
        if self._mgr is not None:
            return self._mgr.latest_step()
        steps = [int(f[len("step_"):-len(".pdckpt")])
                 for f in os.listdir(self.directory)
                 if f.startswith("step_") and f.endswith(".pdckpt")]
        return max(steps) if steps else None

    def wait_until_finished(self):
        if self._mgr is not None:
            self._mgr.wait_until_finished()

    def _gc(self):
        steps = sorted(s for s in [self.latest_step()] if s is not None)
        files = sorted(
            (f for f in os.listdir(self.directory) if f.startswith("step_")),
            key=lambda f: int(f[len("step_"):-len(".pdckpt")]))
        while len(files) > self._max_to_keep:
            os.remove(os.path.join(self.directory, files.pop(0)))

    def close(self):
        if self._mgr is not None:
            self._mgr.close()


def save_sharded(state: Dict[str, Any], path: str):
    """One-shot sharded save (orbax StandardSave)."""
    if not _HAS_ORBAX:
        from .framework_io import save as _save
        _save(_to_arrays(state), path)
        return
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(os.path.abspath(path), _to_arrays(state), force=True)
    ckptr.wait_until_finished()


def load_sharded(path: str, template: Optional[Dict[str, Any]] = None):
    if not _HAS_ORBAX:
        from .framework_io import load as _load
        return _load(path)
    ckptr = ocp.StandardCheckpointer()
    if template is not None:
        return ckptr.restore(os.path.abspath(path), _to_arrays(template))
    return ckptr.restore(os.path.abspath(path))


# ---- auto-checkpoint epoch-range protocol ----

class _EpochRange:
    def __init__(self, max_epoch: int, ckpt_dir: str, save_fn=None,
                 restore_fn=None):
        self.max_epoch = max_epoch
        self.dir = os.path.abspath(ckpt_dir)
        os.makedirs(self.dir, exist_ok=True)
        self._meta = os.path.join(self.dir, "epoch_meta.json")
        self.save_fn = save_fn
        self.restore_fn = restore_fn

    def _load_meta(self):
        if os.path.exists(self._meta):
            with open(self._meta) as f:
                return json.load(f)
        return {"next_epoch": 0}

    def __iter__(self):
        meta = self._load_meta()
        start = meta["next_epoch"]
        if start > 0 and self.restore_fn is not None:
            self.restore_fn(self.dir, start - 1)
        for epoch in range(start, self.max_epoch):
            yield epoch
            if self.save_fn is not None:
                self.save_fn(self.dir, epoch)
            with open(self._meta, "w") as f:
                json.dump({"next_epoch": epoch + 1,
                           "time": time.time()}, f)


def train_epoch_range(max_epoch: int, checkpoint_dir: str = "./auto_ckpt",
                      save_fn=None, restore_fn=None):
    """auto_checkpoint._get_train_epoch_range analog: iterate epochs, persist
    progress, resume where the last run stopped."""
    return _EpochRange(max_epoch, checkpoint_dir, save_fn, restore_fn)
