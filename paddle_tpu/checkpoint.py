"""Sharded + async checkpointing and epoch-range auto-resume.

Reference: paddle.save/load pickle state (python/paddle/framework/io.py:550,766),
fleet-aware save (fleet_base.py:654-732), and the auto-checkpoint epoch-range
protocol (fluid/incubate/checkpoint/auto_checkpoint.py — snapshots keyed by job
id enabling elastic resume).

TPU-native: sharded jax arrays are written via orbax (each host writes its own
shards; restore re-shards to the current mesh), with an async option so the
train loop overlaps the write. The epoch-range protocol is kept verbatim:
`for epoch in train_epoch_range(n, ckpt_dir): ...` resumes mid-run after
preemption/elastic restart.
"""
from __future__ import annotations

import json
import os
import time
import zlib
from typing import Any, Dict, Optional

import jax
import numpy as np

from .core.tensor import Tensor
from .utils import fault_injection

try:
    import orbax.checkpoint as ocp
    _HAS_ORBAX = True
except Exception:  # pragma: no cover
    _HAS_ORBAX = False


def _to_arrays(tree):
    return jax.tree_util.tree_map(
        lambda x: x.data if isinstance(x, Tensor) else x, tree,
        is_leaf=lambda x: isinstance(x, Tensor))


def _is_sharded(tree) -> bool:
    leaves = jax.tree_util.tree_leaves(tree)
    for leaf in leaves:
        if hasattr(leaf, "sharding") and not getattr(
                leaf.sharding, "is_fully_replicated", True):
            return True
    return False


def _leaf_specs(state) -> Dict[str, Dict[str, Any]]:
    """Per-leaf {path: {shape, dtype}} for the integrity manifest."""
    leaves = jax.tree_util.tree_leaves_with_path(state)
    out = {}
    for path, leaf in leaves:
        key = jax.tree_util.keystr(path)
        if hasattr(leaf, "shape") and hasattr(leaf, "dtype"):
            out[key] = {"shape": list(leaf.shape), "dtype": str(leaf.dtype)}
        else:
            out[key] = {"shape": [], "dtype": type(leaf).__name__}
    return out


def _file_crc(path: str) -> int:
    crc = 0
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            crc = zlib.crc32(chunk, crc)
    return crc & 0xFFFFFFFF


class CheckpointManager:
    """Step-indexed checkpoint directory with retention + async save.

    usage:
        mgr = CheckpointManager(dir, max_to_keep=3, async_save=True)
        mgr.save(step, {"params": ..., "opt": ..., "meta": {...}})
        state = mgr.restore(step=None)   # latest

    The non-orbax fallback path is torn-write safe: the pickle is written to
    a temp name, a JSON manifest (per-leaf shapes/dtypes + CRC32 of the data
    file) is written alongside, and both land via atomic os.replace — data
    first, manifest last, so a manifest's existence certifies a complete
    data file. restore()/latest_step() only consider steps whose manifest
    exists and whose checksum matches, so a process killed mid-save (or a
    corrupted file) falls back to the latest *valid* step.
    """

    def __init__(self, directory: str, max_to_keep: int = 3,
                 async_save: bool = False, use_orbax: bool = True):
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self._max_to_keep = max_to_keep
        use_orbax = use_orbax and _HAS_ORBAX
        self._async = async_save and use_orbax
        if use_orbax:
            opts = ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, enable_async_checkpointing=self._async)
            self._mgr = ocp.CheckpointManager(self.directory, options=opts)
        else:
            self._mgr = None

    # ---- fallback-path file layout ----
    def _data_path(self, step: int) -> str:
        return os.path.join(self.directory, f"step_{step}.pdckpt")

    def _manifest_path(self, step: int) -> str:
        return os.path.join(self.directory, f"step_{step}.manifest.json")

    def save(self, step: int, state: Dict[str, Any], force: bool = False):
        state = _to_arrays(state)
        if self._mgr is not None:
            self._mgr.save(step, args=ocp.args.StandardSave(state),
                           force=force)
            return
        # fallback: pickle per step (replicated arrays only), atomic +
        # manifest-certified so torn writes are detectable on restore
        from .framework_io import save as _save
        plan = fault_injection.global_plan()
        data, manifest = self._data_path(step), self._manifest_path(step)
        tmp_data, tmp_manifest = data + ".tmp", manifest + ".tmp"
        _save(state, tmp_data)
        plan.maybe_kill(step, fault_injection.KILL_POINT_MID_SAVE)
        spec = {"step": step, "format": "pdckpt.v1",
                "crc32": _file_crc(tmp_data), "time": time.time(),
                "leaves": _leaf_specs(state)}
        with open(tmp_manifest, "w") as f:
            json.dump(spec, f)
        os.replace(tmp_data, data)
        plan.maybe_kill(step, fault_injection.KILL_POINT_AFTER_DATA)
        os.replace(tmp_manifest, manifest)
        self._gc()

    def verify(self, step: int) -> bool:
        """True iff the fallback files for `step` are complete and the data
        file matches its manifest checksum. FLAGS_ckpt_integrity_check=False
        skips the CRC pass (huge checkpoints) but still requires the
        manifest, whose presence certifies the save sequence finished."""
        data, manifest = self._data_path(step), self._manifest_path(step)
        if not (os.path.exists(data) and os.path.exists(manifest)):
            return False
        from .flags import get_flags
        if not get_flags("FLAGS_ckpt_integrity_check")[
                "FLAGS_ckpt_integrity_check"]:
            return True
        try:
            with open(manifest) as f:
                spec = json.load(f)
            return _file_crc(data) == spec["crc32"]
        except (OSError, ValueError, KeyError):
            return False

    def restore(self, step: Optional[int] = None,
                template: Optional[Dict[str, Any]] = None):
        if self._mgr is not None:
            step = self.latest_step() if step is None else step
            if step is None:
                return None
            if template is not None:
                return self._mgr.restore(
                    step, args=ocp.args.StandardRestore(_to_arrays(template)))
            return self._mgr.restore(step)
        from .framework_io import load as _load
        if step is not None:
            if not self.verify(step):
                raise ValueError(
                    f"checkpoint step {step} in {self.directory} is missing "
                    "or fails integrity verification (torn write?)")
            return _load(self._data_path(step))
        step = self.latest_step()
        if step is None:
            return None
        return _load(self._data_path(step))

    def all_steps(self) -> list:
        """Steps present on disk (fallback: valid, manifest-certified only)."""
        if self._mgr is not None:
            return sorted(self._mgr.all_steps())
        steps = [int(f[len("step_"):-len(".pdckpt")])
                 for f in os.listdir(self.directory)
                 if f.startswith("step_") and f.endswith(".pdckpt")]
        return sorted(s for s in steps if self.verify(s))

    def latest_step(self) -> Optional[int]:
        """Latest *valid* step: fallback checkpoints that are torn or fail
        their checksum are skipped, not returned."""
        if self._mgr is not None:
            return self._mgr.latest_step()
        steps = self.all_steps()
        return steps[-1] if steps else None

    def wait_until_finished(self):
        if self._mgr is not None:
            self._mgr.wait_until_finished()

    def _gc(self):
        valid = self.all_steps()
        while len(valid) > self._max_to_keep:
            s = valid.pop(0)
            for p in (self._data_path(s), self._manifest_path(s)):
                if os.path.exists(p):
                    os.remove(p)

    def close(self):
        if self._mgr is not None:
            self._mgr.close()


def save_sharded(state: Dict[str, Any], path: str):
    """One-shot sharded save (orbax StandardSave)."""
    if not _HAS_ORBAX:
        from .framework_io import save as _save
        _save(_to_arrays(state), path)
        return
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(os.path.abspath(path), _to_arrays(state), force=True)
    ckptr.wait_until_finished()


def load_sharded(path: str, template: Optional[Dict[str, Any]] = None):
    if not _HAS_ORBAX:
        from .framework_io import load as _load
        return _load(path)
    ckptr = ocp.StandardCheckpointer()
    if template is not None:
        return ckptr.restore(os.path.abspath(path), _to_arrays(template))
    return ckptr.restore(os.path.abspath(path))


# ---- auto-checkpoint epoch-range protocol ----

class _EpochRange:
    def __init__(self, max_epoch: int, ckpt_dir: str, save_fn=None,
                 restore_fn=None):
        self.max_epoch = max_epoch
        self.dir = os.path.abspath(ckpt_dir)
        os.makedirs(self.dir, exist_ok=True)
        self._meta = os.path.join(self.dir, "epoch_meta.json")
        self.save_fn = save_fn
        self.restore_fn = restore_fn

    def _load_meta(self):
        if os.path.exists(self._meta):
            with open(self._meta) as f:
                return json.load(f)
        return {"next_epoch": 0}

    def __iter__(self):
        meta = self._load_meta()
        start = meta["next_epoch"]
        if start > 0 and self.restore_fn is not None:
            self.restore_fn(self.dir, start - 1)
        for epoch in range(start, self.max_epoch):
            yield epoch
            if self.save_fn is not None:
                self.save_fn(self.dir, epoch)
            with open(self._meta, "w") as f:
                json.dump({"next_epoch": epoch + 1,
                           "time": time.time()}, f)


def train_epoch_range(max_epoch: int, checkpoint_dir: str = "./auto_ckpt",
                      save_fn=None, restore_fn=None):
    """auto_checkpoint._get_train_epoch_range analog: iterate epochs, persist
    progress, resume where the last run stopped."""
    return _EpochRange(max_epoch, checkpoint_dir, save_fn, restore_fn)
