"""Sharded + async checkpointing and epoch-range auto-resume.

Reference: paddle.save/load pickle state (python/paddle/framework/io.py:550,766),
fleet-aware save (fleet_base.py:654-732), and the auto-checkpoint epoch-range
protocol (fluid/incubate/checkpoint/auto_checkpoint.py — snapshots keyed by job
id enabling elastic resume).

TPU-native: sharded jax arrays are written via orbax (each host writes its own
shards; restore re-shards to the current mesh), with an async option so the
train loop overlaps the write. The epoch-range protocol is kept verbatim:
`for epoch in train_epoch_range(n, ckpt_dir): ...` resumes mid-run after
preemption/elastic restart.

Continuous checkpointing tier (ISSUE 15): `AsyncCheckpointManager` snapshots
train state off-device into a small in-memory ring (the step thread blocks
only for the device→host fetch) and persists on a bounded background writer
thread with the same tmp→fsync→rename manifest/CRC protocol as the sync
fallback path — plus `scrub_checkpoints`, the restore-time scrubber that
quarantines manifest-certified-but-corrupt steps instead of restoring them.
"""
from __future__ import annotations

import copy
import json
import os
import threading
import time
import zlib
from collections import deque
from typing import Any, Dict, List, Optional

import jax
import numpy as np

from .core.tensor import Tensor
from .utils import fault_injection

try:
    import orbax.checkpoint as ocp
    _HAS_ORBAX = True
except Exception:  # pragma: no cover
    _HAS_ORBAX = False


def _to_arrays(tree):
    return jax.tree_util.tree_map(
        lambda x: x.data if isinstance(x, Tensor) else x, tree,
        is_leaf=lambda x: isinstance(x, Tensor))


def _is_sharded(tree) -> bool:
    leaves = jax.tree_util.tree_leaves(tree)
    for leaf in leaves:
        if hasattr(leaf, "sharding") and not getattr(
                leaf.sharding, "is_fully_replicated", True):
            return True
    return False


def _leaf_specs(state) -> Dict[str, Dict[str, Any]]:
    """Per-leaf {path: {shape, dtype}} for the integrity manifest."""
    leaves = jax.tree_util.tree_leaves_with_path(state)
    out = {}
    for path, leaf in leaves:
        key = jax.tree_util.keystr(path)
        if hasattr(leaf, "shape") and hasattr(leaf, "dtype"):
            out[key] = {"shape": list(leaf.shape), "dtype": str(leaf.dtype)}
        else:
            out[key] = {"shape": [], "dtype": type(leaf).__name__}
    return out


def _file_crc(path: str) -> int:
    crc = 0
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            crc = zlib.crc32(chunk, crc)
    return crc & 0xFFFFFFFF


def _fsync_file(path: str):
    with open(path, "rb") as f:
        os.fsync(f.fileno())


def _host_copy(tree):
    """Device→host copy of a state tree: every array leaf becomes an OWNED
    host numpy array (np.array always copies, so a later in-place update or
    donated-buffer reuse can never reach the snapshot); non-array leaves are
    deep-copied. This is the only blocking work `snapshot()` does."""
    def fetch(x):
        if hasattr(x, "shape") and hasattr(x, "dtype") \
                and hasattr(x, "__array__"):
            return np.array(x)  # blocks: this IS the device→host fetch
        return copy.deepcopy(x)
    return jax.tree_util.tree_map(fetch, tree)


def rng_cursor(rs) -> Dict[str, Any]:
    """JSON-safe capture of a np.random.RandomState — the usual data-stream
    half of an exact-resume cursor. Pair with `restore_rng`; store the dict
    via `CheckpointManager.save(..., cursor=...)` / the trainer's
    `get_cursor` hook so a restored run replays the identical batches."""
    name, keys, pos, has_gauss, cached = rs.get_state()
    return {"rng": name, "keys": [int(k) for k in keys], "pos": int(pos),
            "has_gauss": int(has_gauss), "cached": float(cached)}


def restore_rng(rs, cursor: Dict[str, Any]) -> None:
    """Inverse of `rng_cursor`: rewind a RandomState to the captured point."""
    rs.set_state((cursor["rng"],
                  np.asarray(cursor["keys"], dtype=np.uint32),
                  int(cursor["pos"]), int(cursor["has_gauss"]),
                  float(cursor["cached"])))


class CheckpointManager:
    """Step-indexed checkpoint directory with retention + async save.

    usage:
        mgr = CheckpointManager(dir, max_to_keep=3, async_save=True)
        mgr.save(step, {"params": ..., "opt": ..., "meta": {...}})
        state = mgr.restore(step=None)   # latest

    The non-orbax fallback path is torn-write safe: the pickle is written to
    a temp name, a JSON manifest (per-leaf shapes/dtypes + CRC32 of the data
    file) is written alongside, and both land via atomic os.replace — data
    first, manifest last, so a manifest's existence certifies a complete
    data file. restore()/latest_step() only consider steps whose manifest
    exists and whose checksum matches, so a process killed mid-save (or a
    corrupted file) falls back to the latest *valid* step.
    """

    def __init__(self, directory: str, max_to_keep: int = 3,
                 async_save: bool = False, use_orbax: bool = True):
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self._max_to_keep = max_to_keep
        use_orbax = use_orbax and _HAS_ORBAX
        self._async = async_save and use_orbax
        if use_orbax:
            opts = ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, enable_async_checkpointing=self._async)
            self._mgr = ocp.CheckpointManager(self.directory, options=opts)
        else:
            self._mgr = None

    # ---- fallback-path file layout ----
    def _data_path(self, step: int) -> str:
        return os.path.join(self.directory, f"step_{step}.pdckpt")

    def _manifest_path(self, step: int) -> str:
        return os.path.join(self.directory, f"step_{step}.manifest.json")

    def save(self, step: int, state: Dict[str, Any], force: bool = False,
             cursor: Optional[Dict[str, Any]] = None):
        """Persist `state` under `step`. `cursor` is an optional JSON-safe
        data-stream position (iterator index, RNG state — see rng_cursor)
        stored with the checkpoint so a restored run can replay the exact
        batch sequence; the fallback path keeps it in the manifest, the
        orbax path in a `step_<s>.cursor.json` sidecar."""
        state = _to_arrays(state)
        if self._mgr is not None:
            self._mgr.save(step, args=ocp.args.StandardSave(state),
                           force=force)
            if cursor is not None:
                side = os.path.join(self.directory,
                                    f"step_{step}.cursor.json")
                with open(side + ".tmp", "w") as f:
                    json.dump(cursor, f)
                os.replace(side + ".tmp", side)
            return
        # fallback: pickle per step (replicated arrays only), atomic +
        # manifest-certified so torn writes are detectable on restore
        from .framework_io import save as _save
        plan = fault_injection.global_plan()
        data, manifest = self._data_path(step), self._manifest_path(step)
        tmp_data, tmp_manifest = data + ".tmp", manifest + ".tmp"
        _save(state, tmp_data)
        _fsync_file(tmp_data)
        plan.maybe_kill(step, fault_injection.KILL_POINT_MID_SAVE)
        spec = {"step": step, "format": "pdckpt.v1",
                "crc32": _file_crc(tmp_data), "time": time.time(),
                "leaves": _leaf_specs(state)}
        if cursor is not None:
            spec["cursor"] = cursor
        with open(tmp_manifest, "w") as f:
            json.dump(spec, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp_data, data)
        plan.maybe_kill(step, fault_injection.KILL_POINT_AFTER_DATA)
        os.replace(tmp_manifest, manifest)
        # torn-write fault (ckpt_torn_write@step): corrupt the data file
        # AFTER its manifest landed — certified-but-corrupt, the case only
        # the restore scrubber can catch
        plan.maybe_torn_write(step, data)
        self._gc()

    def read_cursor(self, step: int) -> Optional[Dict[str, Any]]:
        """The cursor stored with `step`, or None. Fallback path: the
        manifest's "cursor" field; orbax path: the sidecar file."""
        manifest = self._manifest_path(step)
        if os.path.exists(manifest):
            try:
                with open(manifest) as f:
                    return json.load(f).get("cursor")
            except (OSError, ValueError):
                return None
        side = os.path.join(self.directory, f"step_{step}.cursor.json")
        try:
            with open(side) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def verify(self, step: int) -> bool:
        """True iff the fallback files for `step` are complete and the data
        file matches its manifest checksum. FLAGS_ckpt_integrity_check=False
        skips the CRC pass (huge checkpoints) but still requires the
        manifest, whose presence certifies the save sequence finished."""
        data, manifest = self._data_path(step), self._manifest_path(step)
        if not (os.path.exists(data) and os.path.exists(manifest)):
            return False
        from .flags import get_flags
        if not get_flags("FLAGS_ckpt_integrity_check")[
                "FLAGS_ckpt_integrity_check"]:
            return True
        try:
            with open(manifest) as f:
                spec = json.load(f)
            return _file_crc(data) == spec["crc32"]
        except (OSError, ValueError, KeyError):
            return False

    def restore(self, step: Optional[int] = None,
                template: Optional[Dict[str, Any]] = None):
        if self._mgr is not None:
            step = self.latest_step() if step is None else step
            if step is None:
                return None
            if template is not None:
                return self._mgr.restore(
                    step, args=ocp.args.StandardRestore(_to_arrays(template)))
            return self._mgr.restore(step)
        from .framework_io import load as _load
        if step is not None:
            if not self.verify(step):
                raise ValueError(
                    f"checkpoint step {step} in {self.directory} is missing "
                    "or fails integrity verification (torn write?)")
            return _load(self._data_path(step))
        step = self.latest_step()
        if step is None:
            return None
        return _load(self._data_path(step))

    def all_steps(self) -> list:
        """Steps present on disk (fallback: valid, manifest-certified only)."""
        if self._mgr is not None:
            return sorted(self._mgr.all_steps())
        steps = []
        for f in os.listdir(self.directory):
            if not (f.startswith("step_") and f.endswith(".pdckpt")):
                continue
            try:
                steps.append(int(f[len("step_"):-len(".pdckpt")]))
            except ValueError:
                continue  # stray file in our namespace: skip, don't crash
        return sorted(s for s in steps if self.verify(s))

    def latest_step(self) -> Optional[int]:
        """Latest *valid* step: fallback checkpoints that are torn or fail
        their checksum are skipped, not returned."""
        if self._mgr is not None:
            return self._mgr.latest_step()
        steps = self.all_steps()
        return steps[-1] if steps else None

    def wait_until_finished(self):
        if self._mgr is not None:
            self._mgr.wait_until_finished()

    def _gc(self):
        valid = self.all_steps()
        # retention floor: the newest manifest-certified step is never
        # collected, whatever max_to_keep says — deleting the only
        # restorable state to satisfy a quota is always the wrong trade
        keep = max(self._max_to_keep, 1)
        while len(valid) > keep:
            s = valid.pop(0)
            for p in (self._data_path(s), self._manifest_path(s)):
                try:
                    os.remove(p)
                except FileNotFoundError:
                    pass  # a concurrent emergency save may have GC'd it

    def close(self):
        if self._mgr is not None:
            self._mgr.close()


# ---- restore-time scrubber ----

def _parse_step_file(fname: str):
    """(step, suffix) for step_<n>.pdckpt / step_<n>.manifest.json, else
    None — strays that don't parse are never treated as candidates."""
    if not fname.startswith("step_"):
        return None
    for suffix in (".pdckpt", ".manifest.json"):
        if fname.endswith(suffix):
            try:
                return int(fname[len("step_"):-len(suffix)]), suffix
            except ValueError:
                return None
    return None


def scrub_checkpoints(directory: str) -> Dict[str, List]:
    """Walk a fallback-layout checkpoint directory, CRC-verify every step
    candidate, and QUARANTINE whatever fails: the step's files (data,
    manifest, stale tmps) move into `step_<n>.corrupt/` so latest_step()
    can never land on them and a human can triage the bytes later
    (docs/fault_tolerance.md § Scrubber runbook). Each quarantine drops a
    `ckpt_corrupt` flight event naming the step and the failing file.
    The CRC pass always runs here (unlike verify(), which honors
    FLAGS_ckpt_integrity_check): this is the once-per-restore moment
    where a certified-but-corrupt step would otherwise become live state.
    Returns {"clean": [steps...], "quarantined": [{step, file, reason}]}.
    Run it BEFORE any writer targets the directory — it treats data
    files without a manifest (in-flight saves included) as torn."""
    from .obs.flight_recorder import flight_recorder
    directory = os.path.abspath(directory)
    try:
        names = os.listdir(directory)
    except OSError:
        return {"clean": [], "quarantined": []}
    steps = set()
    for f in names:
        parsed = _parse_step_file(f)
        if parsed is not None:
            steps.add(parsed[0])
    clean: List[int] = []
    quarantined: List[Dict[str, Any]] = []
    for s in sorted(steps):
        data = os.path.join(directory, f"step_{s}.pdckpt")
        manifest = os.path.join(directory, f"step_{s}.manifest.json")
        bad = None  # (failing file, reason)
        if not os.path.exists(manifest):
            bad = (data, "uncertified: no manifest (torn save)")
        elif not os.path.exists(data):
            bad = (data, "manifest without data file")
        else:
            try:
                with open(manifest) as f:
                    expect = json.load(f)["crc32"]
            except (OSError, ValueError, KeyError) as e:
                bad = (manifest, f"manifest unreadable: {type(e).__name__}")
            else:
                if _file_crc(data) != expect:
                    bad = (data, "crc32 mismatch (torn write / bit rot)")
        if bad is None:
            clean.append(s)
            continue
        qdir = os.path.join(directory, f"step_{s}.corrupt")
        os.makedirs(qdir, exist_ok=True)
        for p in (data, manifest, data + ".tmp", manifest + ".tmp"):
            if os.path.exists(p):
                os.replace(p, os.path.join(qdir, os.path.basename(p)))
        rec = {"step": s, "file": os.path.basename(bad[0]),
               "reason": bad[1]}
        quarantined.append(rec)
        flight_recorder().record("ckpt_corrupt", **rec)
    return {"clean": clean, "quarantined": quarantined}


# ---- certified serving weight sets (ISSUE 16) ----

class UncertifiedWeightsError(ValueError):
    """A serving `WeightSet` failed certification: missing/unreadable
    manifest, missing data file, wrong format, or CRC mismatch. Deploys
    refuse uncertified weights outright — a torn or bit-rotted weight
    file must never reach a live fleet. `reason` is machine-readable
    and mirrors the scrubber's quarantine vocabulary."""

    def __init__(self, msg: str, reason: str = "uncertified"):
        super().__init__(msg)
        self.reason = reason


class WeightSet:
    """A versioned, manifest/CRC-certified serving parameter set.

    The deployable unit of ISSUE 16's rolling deploys: a params tree
    published as `weights_<version>.pdckpt` + `weights_<version>
    .manifest.json` under the same tmp→fsync→rename, data-first/
    manifest-last protocol as `CheckpointManager.save`, so the manifest's
    presence certifies the write sequence finished and its crc32 pins
    the bytes. `certify()` ALWAYS runs the CRC pass (like
    `scrub_checkpoints`, unlike `verify()`): a deploy is the
    once-per-rollout moment where corrupt weights would otherwise reach
    every replica in the fleet. The manifest may carry a `golden` block
    (canary prompts + expected greedy tokens) published alongside the
    weights by whoever trained them."""

    FORMAT = "pdtpu.weights.v1"

    def __init__(self, directory: str, version: str):
        if not version or not all(
                c.isalnum() or c in "._-" for c in str(version)):
            raise ValueError(
                f"weight version {version!r} must be non-empty and "
                "filesystem-safe ([A-Za-z0-9._-])")
        self.directory = os.path.abspath(directory)
        self.version = str(version)

    @property
    def data_path(self) -> str:
        return os.path.join(self.directory,
                            f"weights_{self.version}.pdckpt")

    @property
    def manifest_path(self) -> str:
        return os.path.join(self.directory,
                            f"weights_{self.version}.manifest.json")

    @classmethod
    def publish(cls, directory: str, version: str, params,
                golden: Optional[Dict[str, Any]] = None,
                extra: Optional[Dict[str, Any]] = None) -> "WeightSet":
        """Write `params` as a certified weight set. Data lands first
        (tmp → fsync → rename), the manifest last — a crash at any point
        leaves either no manifest (uncertified, refused by deploys) or a
        fully certified pair. `extra` merges additional manifest keys
        (subclass metadata — e.g. the adapter signature) and may not
        shadow the protocol keys."""
        from .framework_io import save as _save
        ws = cls(directory, version)
        os.makedirs(ws.directory, exist_ok=True)
        params = _to_arrays(params)
        tmp_data = ws.data_path + ".tmp"
        tmp_manifest = ws.manifest_path + ".tmp"
        _save(params, tmp_data)
        _fsync_file(tmp_data)
        spec = {"version": ws.version, "format": cls.FORMAT,
                "crc32": _file_crc(tmp_data), "time": time.time(),
                "leaves": _leaf_specs(params)}
        if extra:
            clash = set(extra) & set(spec) | ({"golden"} & set(extra))
            if clash:
                raise ValueError(
                    f"extra manifest keys {sorted(clash)} shadow the "
                    "weight-set protocol")
            spec.update(extra)
        if golden is not None:
            spec["golden"] = golden
        with open(tmp_manifest, "w") as f:
            json.dump(spec, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp_data, ws.data_path)
        os.replace(tmp_manifest, ws.manifest_path)
        return ws

    def certify(self) -> Dict[str, Any]:
        """Full certification pass: manifest present + readable, format
        recognised, version matches, data present, crc32 matches the
        bytes on disk. Returns the manifest dict; raises
        `UncertifiedWeightsError` (typed, with a scrubber-vocabulary
        `reason`) on any failure."""
        if not os.path.exists(self.manifest_path):
            raise UncertifiedWeightsError(
                f"weight set {self.version!r} in {self.directory} has no "
                "manifest (torn or unfinished publish)",
                reason="no_manifest")
        try:
            with open(self.manifest_path) as f:
                spec = json.load(f)
        except (OSError, ValueError) as e:
            raise UncertifiedWeightsError(
                f"weight set {self.version!r} manifest unreadable: "
                f"{type(e).__name__}", reason="manifest_unreadable")
        if spec.get("format") != self.FORMAT:
            raise UncertifiedWeightsError(
                f"weight set {self.version!r} has unknown format "
                f"{spec.get('format')!r} (expected {self.FORMAT!r})",
                reason="bad_format")
        if spec.get("version") != self.version:
            raise UncertifiedWeightsError(
                f"manifest names version {spec.get('version')!r} but the "
                f"deploy asked for {self.version!r}",
                reason="version_mismatch")
        if not os.path.exists(self.data_path):
            raise UncertifiedWeightsError(
                f"weight set {self.version!r} manifest without data file",
                reason="no_data")
        try:
            expect = int(spec["crc32"])
        except (KeyError, TypeError, ValueError):
            raise UncertifiedWeightsError(
                f"weight set {self.version!r} manifest carries no usable "
                "crc32", reason="manifest_unreadable")
        if _file_crc(self.data_path) != expect:
            raise UncertifiedWeightsError(
                f"weight set {self.version!r} crc32 mismatch "
                "(torn write / bit rot)", reason="crc_mismatch")
        return spec

    def load(self):
        """Certify, then load the params tree. The only sanctioned way
        weights reach a serving engine."""
        from .framework_io import load as _load
        self.certify()
        return _load(self.data_path)

    @property
    def golden(self) -> Optional[Dict[str, Any]]:
        """The manifest's golden canary block, if published (certifies as
        a side effect — golden data from an uncertified set is useless)."""
        return self.certify().get("golden")


class AdapterWeightSet(WeightSet):
    """A certified **adapter-only** weight set (ISSUE 20).

    Same protocol as `WeightSet` (tmp→fsync→rename, manifest-last,
    CRC-certified, optional golden block) with its own format string so
    a base-weight deploy can never accidentally consume an adapter tree
    and vice versa, plus a mandatory `adapter` manifest block carrying
    `tuning.lora.adapter_signature` of the base model the adapter was
    trained against. `certify_for(signature)` is the deploy-side gate:
    full CRC certification AND a field-by-field signature comparison,
    with a typed `UncertifiedWeightsError(reason="adapter_mismatch")`
    refusal when the serving fleet's base model disagrees on rank,
    target modules, layer count or projection dims — a rank-16 adapter
    must never be gathered into a rank-8 bank."""

    FORMAT = "pdtpu.adapter.v1"

    @classmethod
    def publish(cls, directory: str, version: str, params,
                signature: Dict[str, Any],
                golden: Optional[Dict[str, Any]] = None,
                ) -> "AdapterWeightSet":
        if not isinstance(signature, dict) or "rank" not in signature:
            raise ValueError(
                "AdapterWeightSet.publish requires the adapter_signature "
                "dict of the base model (got "
                f"{type(signature).__name__})")
        return super().publish(directory, version, params, golden=golden,
                               extra={"adapter": signature})

    def certify_for(self, signature: Dict[str, Any]) -> Dict[str, Any]:
        """Certify bytes AND bind to a base model: raises a typed
        refusal unless the manifest's adapter signature matches
        `signature` exactly. Returns the manifest dict."""
        spec = self.certify()
        published = spec.get("adapter")
        if not isinstance(published, dict):
            raise UncertifiedWeightsError(
                f"adapter set {self.version!r} manifest carries no "
                "adapter signature", reason="adapter_mismatch")
        diff = sorted(
            k for k in set(published) | set(signature)
            if published.get(k) != signature.get(k))
        if diff:
            pub = {k: published.get(k) for k in diff}
            want = {k: signature.get(k) for k in diff}
            raise UncertifiedWeightsError(
                f"adapter set {self.version!r} was trained against a "
                f"different base model: mismatched field(s) {diff} "
                f"(published {pub!r}, serving {want!r})",
                reason="adapter_mismatch")
        return spec


# ---- continuous checkpointing tier ----

class Snapshot:
    """One off-device train-state snapshot: the host-copied state tree,
    the data-stream cursor, and the monotonic instant it was taken
    (persist lag is measured against it)."""
    __slots__ = ("step", "state", "cursor", "taken_at")

    def __init__(self, step: int, state, cursor=None,
                 taken_at: Optional[float] = None):
        self.step = int(step)
        self.state = state
        self.cursor = cursor
        self.taken_at = time.monotonic() if taken_at is None else taken_at


class AsyncCheckpointManager:
    """Continuous checkpointing: snapshot-to-ring on the step thread,
    persist on a bounded background writer (ISSUE 15 tentpole).

    `snapshot(step, state, cursor)` blocks only for the device→host fetch
    (one owned copy per leaf), appends the copy to a small in-memory ring,
    and enqueues it for the writer thread, which persists with the SAME
    tmp→fsync→rename manifest/CRC protocol as `CheckpointManager` — the
    on-disk layout and restore path are identical to the sync tier, so
    `restore()`/`latest_step()`/`verify()` simply delegate. Backpressure
    is typed and explicit: past `max_pending` queued snapshots the OLDEST
    pending one is dropped — never the latest, which is exactly the state
    an emergency save or ring rollback needs — and a `ckpt_lag` flight
    event records the drop. Every snapshot/persist drops `ckpt_snapshot`
    / `ckpt_persist` events, so a flight dump reads as the full pipeline
    timeline.

    The ring additionally serves:
    - `emergency_save()` — persist the newest snapshot synchronously
      (SIGTERM / watchdog escalation: NO device round-trip; never raises);
    - `newest_snapshot()` + `ring_state()` — NaN-rollback state without
      touching disk.

    `scrub()` runs the restore-time scrubber (`scrub_checkpoints`) over
    the directory. This tier is fallback-protocol only (use_orbax=False
    underneath): the manifest machinery is what makes torn background
    persists detectable.
    """

    def __init__(self, directory: str, max_to_keep: int = 3,
                 ring_size: int = 2, max_pending: int = 2, ledger=None):
        self._sync = CheckpointManager(directory, max_to_keep=max_to_keep,
                                       use_orbax=False)
        self.directory = self._sync.directory
        # obs.goodput.GoodputLedger (or None): background persist seconds
        # are booked via book_async_checkpoint — a non-phase counter, so
        # the writer thread never breaks the phases-tile-wall invariant
        self.ledger = ledger
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._disk_lock = threading.Lock()  # serializes writer vs emergency
        self._ring: deque = deque(maxlen=max(1, int(ring_size)))
        self._pending: deque = deque()
        self._max_pending = max(1, int(max_pending))
        self._in_flight: Optional[Snapshot] = None
        self._stop = False
        self._stats: Dict[str, Any] = {
            "snapshots": 0, "persisted": 0, "dropped": 0,
            "persist_errors": 0, "emergency_saves": 0,
            "corrupt_quarantined": 0,
            "lag_seconds_total": 0.0, "last_lag_seconds": 0.0,
            "blocking_seconds_total": 0.0, "async_seconds_total": 0.0,
        }
        self._thread = threading.Thread(
            target=self._writer_loop, name="pdtpu-ckpt-writer", daemon=True)
        self._thread.start()

    # ---- snapshot pipeline ----
    def snapshot(self, step: int, state: Dict[str, Any],
                 cursor: Optional[Dict[str, Any]] = None) -> Snapshot:
        """Host-copy `state` (the only blocking work), ring it, enqueue it
        for the background writer. Call at a step boundary."""
        from .obs.flight_recorder import flight_recorder
        t0 = time.perf_counter()
        host = _host_copy(_to_arrays(state))
        blocking = time.perf_counter() - t0
        snap = Snapshot(step, host, cursor)
        dropped = None
        with self._cv:
            self._stats["snapshots"] += 1
            self._stats["blocking_seconds_total"] += blocking
            self._ring.append(snap)
            self._pending.append(snap)
            # typed backpressure: the writer fell behind, so shed the
            # OLDEST pending snapshot — never the one just taken
            while len(self._pending) > self._max_pending:
                dropped = self._pending.popleft()
                self._stats["dropped"] += 1
            depth = len(self._pending)
            self._cv.notify()
        flight_recorder().record(
            "ckpt_snapshot", step=snap.step,
            blocking_ms=round(blocking * 1e3, 3), queue_depth=depth)
        if dropped is not None:
            flight_recorder().record(
                "ckpt_lag", dropped_step=dropped.step, newest_step=snap.step,
                queue_depth=depth, policy="drop_oldest_pending")
        return snap

    def _writer_loop(self):
        from .obs.flight_recorder import flight_recorder
        while True:
            with self._cv:
                while not self._pending and not self._stop:
                    self._cv.wait(timeout=0.2)
                if not self._pending and self._stop:
                    return
                snap = self._pending.popleft()
                self._in_flight = snap
            try:
                self._persist(snap)
            except Exception as e:  # the writer must outlive bad disks
                with self._cv:
                    self._stats["persist_errors"] += 1
                flight_recorder().record(
                    "ckpt_persist_error", step=snap.step,
                    error=f"{type(e).__name__}: {e}"[:200])
            finally:
                with self._cv:
                    self._in_flight = None
                    self._cv.notify_all()

    def _persist(self, snap: Snapshot, emergency: bool = False):
        from .obs.flight_recorder import flight_recorder
        plan = fault_injection.global_plan()
        if not emergency:
            # fault hooks live on the BACKGROUND path only: the emergency
            # path must stay unconditionally fast and unkillable-by-plan
            plan.maybe_kill(snap.step, fault_injection.KILL_POINT_PERSIST)
            plan.maybe_ckpt_stall(snap.step)
        t0 = time.perf_counter()
        with self._disk_lock:
            self._sync.save(snap.step, snap.state, cursor=snap.cursor)
        dt = time.perf_counter() - t0
        lag = time.monotonic() - snap.taken_at
        with self._cv:
            self._stats["persisted"] += 1
            key = ("blocking_seconds_total" if emergency
                   else "async_seconds_total")
            self._stats[key] += dt
            self._stats["lag_seconds_total"] += lag
            self._stats["last_lag_seconds"] = lag
        if self.ledger is not None and not emergency:
            self.ledger.book_async_checkpoint(dt)
        flight_recorder().record(
            "ckpt_persist", step=snap.step, ms=round(dt * 1e3, 3),
            lag_ms=round(lag * 1e3, 3), emergency=emergency)

    # ---- ring services ----
    def newest_snapshot(self) -> Optional[Snapshot]:
        with self._cv:
            return self._ring[-1] if self._ring else None

    def ring_state(self, snap: Snapshot):
        """A restore-shaped view of a ring snapshot: the same tree a disk
        restore of that snapshot would produce, without touching disk."""
        from .framework_io import _unpack
        return _unpack(snap.state)

    def emergency_save(self) -> Optional[int]:
        """Persist the newest ring snapshot synchronously — the signal
        path: no device round-trip, no queue wait, never raises. Returns
        the persisted step, or None (empty ring / disk failure)."""
        from .obs.flight_recorder import flight_recorder
        with self._cv:
            snap = self._ring[-1] if self._ring else None
            if snap is not None and snap in self._pending:
                self._pending.remove(snap)  # don't persist it twice
        if snap is None:
            return None
        try:
            self._persist(snap, emergency=True)
        except Exception as e:
            with self._cv:
                self._stats["persist_errors"] += 1
            flight_recorder().record(
                "ckpt_persist_error", step=snap.step, emergency=True,
                error=f"{type(e).__name__}: {e}"[:200])
            return None
        with self._cv:
            self._stats["emergency_saves"] += 1
        flight_recorder().record("ckpt_emergency", step=snap.step)
        return snap.step

    # ---- scrub + delegation to the sync tier ----
    def scrub(self) -> Dict[str, List]:
        report = scrub_checkpoints(self.directory)
        if report["quarantined"]:
            with self._cv:
                self._stats["corrupt_quarantined"] += len(
                    report["quarantined"])
        return report

    def save(self, step: int, state: Dict[str, Any], force: bool = False,
             cursor: Optional[Dict[str, Any]] = None):
        """Synchronous escape hatch (same protocol as the writer uses)."""
        with self._disk_lock:
            self._sync.save(step, state, force=force, cursor=cursor)

    def restore(self, step: Optional[int] = None,
                template: Optional[Dict[str, Any]] = None):
        return self._sync.restore(step, template)

    def read_cursor(self, step: int) -> Optional[Dict[str, Any]]:
        return self._sync.read_cursor(step)

    def verify(self, step: int) -> bool:
        return self._sync.verify(step)

    def all_steps(self) -> list:
        return self._sync.all_steps()

    def latest_step(self) -> Optional[int]:
        return self._sync.latest_step()

    def wait_until_finished(self):
        """Block until every queued snapshot has been persisted."""
        with self._cv:
            while self._pending or self._in_flight is not None:
                self._cv.wait(timeout=0.1)

    def stats(self) -> Dict[str, Any]:
        """Counter/gauge snapshot for the pdtpu_train_ckpt_* families."""
        with self._cv:
            s = dict(self._stats)
            s["queue_depth"] = len(self._pending) + (
                1 if self._in_flight is not None else 0)
        return s

    def close(self):
        self.wait_until_finished()
        with self._cv:
            self._stop = True
            self._cv.notify_all()
        self._thread.join(timeout=5.0)
        self._sync.close()


def save_sharded(state: Dict[str, Any], path: str, shard_id: int = 0,
                 num_shards: int = 1, use_orbax: bool = True):
    """One-shot sharded save.

    orbax path: StandardSave (orbax's own atomic commit; each host writes
    its arrays' shards natively, so shard_id/num_shards are ignored).

    Fallback path: `path` is a DIRECTORY of manifest-certified shards
    under the same torn-write protocol as CheckpointManager — each rank
    writes `shard_<i>.pdckpt` + `shard_<i>.manifest.json` (per-shard
    CRC32 plus its (shard_id, num_shards) coordinates) via
    tmp→fsync→rename, data first, manifest last. A complete manifest SET
    certifies a complete shard set: load_sharded refuses anything less,
    because a shard may be the only copy of its slice of optimizer state
    (the ROADMAP's ZeRO-style sharded update)."""
    if _HAS_ORBAX and use_orbax:
        ckptr = ocp.StandardCheckpointer()
        ckptr.save(os.path.abspath(path), _to_arrays(state), force=True)
        ckptr.wait_until_finished()
        return
    shard_id, num_shards = int(shard_id), int(num_shards)
    if not (0 <= shard_id < num_shards):
        raise ValueError(
            f"shard_id {shard_id} out of range for num_shards {num_shards}")
    from .framework_io import save as _save
    path = os.path.abspath(path)
    os.makedirs(path, exist_ok=True)
    state = _to_arrays(state)
    data = os.path.join(path, f"shard_{shard_id}.pdckpt")
    manifest = os.path.join(path, f"shard_{shard_id}.manifest.json")
    tmp_data, tmp_manifest = data + ".tmp", manifest + ".tmp"
    _save(state, tmp_data)
    _fsync_file(tmp_data)
    spec = {"shard": shard_id, "num_shards": num_shards,
            "format": "pdckpt.shard.v1", "crc32": _file_crc(tmp_data),
            "time": time.time(), "leaves": _leaf_specs(state)}
    with open(tmp_manifest, "w") as f:
        json.dump(spec, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp_data, data)
    os.replace(tmp_manifest, manifest)


def load_sharded(path: str, template: Optional[Dict[str, Any]] = None,
                 shard_id: Optional[int] = None, use_orbax: bool = True):
    """Restore a sharded save. The fallback path REFUSES (ValueError) any
    shard set that is not fully certified: missing/unreadable manifests,
    mismatched num_shards across manifests, missing shards, or a data
    file failing its manifest CRC — partial restores of partitioned
    optimizer state are silent corruption, not resilience. `shard_id`
    picks the shard to load (required when num_shards > 1); `template`
    applies to the orbax path only."""
    if _HAS_ORBAX and use_orbax:
        ckptr = ocp.StandardCheckpointer()
        if template is not None:
            return ckptr.restore(os.path.abspath(path), _to_arrays(template))
        return ckptr.restore(os.path.abspath(path))
    from .framework_io import load as _load
    path = os.path.abspath(path)
    if os.path.isfile(path):  # pre-certification single-file layout
        return _load(path)
    if not os.path.isdir(path):
        raise ValueError(f"no sharded checkpoint at {path}")
    specs: Dict[int, Dict[str, Any]] = {}
    for fname in sorted(os.listdir(path)):
        if not (fname.startswith("shard_")
                and fname.endswith(".manifest.json")):
            continue
        try:
            idx = int(fname[len("shard_"):-len(".manifest.json")])
        except ValueError:
            continue
        try:
            with open(os.path.join(path, fname)) as f:
                specs[idx] = json.load(f)
        except (OSError, ValueError) as e:
            raise ValueError(
                f"refusing sharded restore from {path}: manifest {fname} "
                f"unreadable ({type(e).__name__})")
    if not specs:
        raise ValueError(
            f"refusing sharded restore from {path}: no shard manifests "
            "(uncertified or torn save)")
    counts = {int(s.get("num_shards", -1)) for s in specs.values()}
    if len(counts) != 1:
        raise ValueError(
            f"refusing sharded restore from {path}: mismatched num_shards "
            f"across shard manifests ({sorted(counts)})")
    n = counts.pop()
    missing = [i for i in range(n) if i not in specs]
    if missing:
        raise ValueError(
            f"refusing sharded restore from {path}: missing manifests for "
            f"shards {missing} of {n}")
    for i in range(n):
        data = os.path.join(path, f"shard_{i}.pdckpt")
        if not os.path.exists(data):
            raise ValueError(
                f"refusing sharded restore from {path}: shard {i} has a "
                "manifest but no data file")
        if _file_crc(data) != specs[i]["crc32"]:
            raise ValueError(
                f"refusing sharded restore from {path}: shard {i} fails "
                "its manifest CRC (torn write / bit rot)")
    if shard_id is None:
        if n != 1:
            raise ValueError(
                f"{path} holds {n} shards; pass shard_id to pick one")
        shard_id = 0
    if not (0 <= int(shard_id) < n):
        raise ValueError(f"shard_id {shard_id} out of range for {n} shards")
    return _load(os.path.join(path, f"shard_{int(shard_id)}.pdckpt"))


# ---- auto-checkpoint epoch-range protocol ----

class _EpochRange:
    def __init__(self, max_epoch: int, ckpt_dir: str, save_fn=None,
                 restore_fn=None):
        self.max_epoch = max_epoch
        self.dir = os.path.abspath(ckpt_dir)
        os.makedirs(self.dir, exist_ok=True)
        self._meta = os.path.join(self.dir, "epoch_meta.json")
        self.save_fn = save_fn
        self.restore_fn = restore_fn

    def _load_meta(self):
        if os.path.exists(self._meta):
            with open(self._meta) as f:
                return json.load(f)
        return {"next_epoch": 0}

    def __iter__(self):
        meta = self._load_meta()
        start = meta["next_epoch"]
        if start > 0 and self.restore_fn is not None:
            self.restore_fn(self.dir, start - 1)
        for epoch in range(start, self.max_epoch):
            yield epoch
            if self.save_fn is not None:
                self.save_fn(self.dir, epoch)
            with open(self._meta, "w") as f:
                json.dump({"next_epoch": epoch + 1,
                           "time": time.time()}, f)


def train_epoch_range(max_epoch: int, checkpoint_dir: str = "./auto_ckpt",
                      save_fn=None, restore_fn=None):
    """auto_checkpoint._get_train_epoch_range analog: iterate epochs, persist
    progress, resume where the last run stopped."""
    return _EpochRange(max_epoch, checkpoint_dir, save_fn, restore_fn)
