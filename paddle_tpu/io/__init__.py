"""paddle.io analog: Dataset / Sampler / DataLoader.

Reference: python/paddle/fluid/dataloader/ (dataloader_iter.py:97,248 single/multi
process iterators, worker.py, batch_sampler.py, collate.py) and reader.py:146.

TPU-native notes: the loader collates to numpy on host; device transfer happens when
tensors hit an op (or explicitly via feed helpers), letting jax overlap H2D with
compute. Multi-process loading uses a multiprocessing.Pool of index-workers feeding
an ordered prefetch queue — same worker model as the reference, minus shared-memory
LoD plumbing which XLA doesn't need.
"""
from __future__ import annotations

import bisect
import itertools
import multiprocessing as mp
import queue as _queue
import threading
from typing import Iterable, List, Optional

import numpy as np

import jax as _jax

from ..core.random import next_key as _next_key

from ..core.tensor import Tensor


class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class IterableDataset(Dataset):
    def __iter__(self):
        raise NotImplementedError

    # TypeError (not RuntimeError) so list()/length_hint degrade gracefully
    def __getitem__(self, idx):
        raise TypeError("IterableDataset is not indexable")

    def __len__(self):
        raise TypeError("IterableDataset has no len()")


class TensorDataset(Dataset):
    def __init__(self, tensors):
        self.tensors = tensors

    def __getitem__(self, idx):
        return tuple(t[idx] for t in self.tensors)

    def __len__(self):
        return self.tensors[0].shape[0]


class Subset(Dataset):
    def __init__(self, dataset, indices):
        self.dataset = dataset
        self.indices = indices

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


class ConcatDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)
        self.cumulative_sizes = list(
            itertools.accumulate(len(d) for d in self.datasets))

    def __len__(self):
        return self.cumulative_sizes[-1]

    def __getitem__(self, idx):
        ds_idx = bisect.bisect_right(self.cumulative_sizes, idx)
        prev = self.cumulative_sizes[ds_idx - 1] if ds_idx > 0 else 0
        return self.datasets[ds_idx][idx - prev]


class ChainDataset(IterableDataset):
    def __init__(self, datasets):
        self.datasets = datasets

    def __iter__(self):
        for d in self.datasets:
            yield from d


class ComposeDataset(Dataset):
    """Compose the FIELDS of same-length map-style datasets into one sample
    tuple (reference: fluid/dataloader/dataset.py:286)."""

    def __init__(self, datasets):
        self.datasets = list(datasets)
        assert self.datasets, "datasets should not be empty"
        n = len(self.datasets[0])
        for d in self.datasets[1:]:
            assert len(d) == n, "composed datasets must share one length"

    def __len__(self):
        return len(self.datasets[0])

    def __getitem__(self, idx):
        sample = []
        for d in self.datasets:
            item = d[idx]
            sample.extend(item if isinstance(item, (tuple, list))
                          else [item])
        return tuple(sample)


def _framework_permutation(n):
    """Permutation driven by the FRAMEWORK PRNG (paddle.seed), not numpy's
    module-global state: shuffle order is reproducible under paddle.seed
    and immune to unrelated np.random consumers (cross-test/global-state
    coupling made fit() accuracy order-dependent before this)."""
    return np.asarray(_jax.random.permutation(_next_key(), n))


def random_split(dataset, lengths, generator=None):
    total = sum(lengths)
    assert total == len(dataset)
    indices = _framework_permutation(total).tolist()
    out, offset = [], 0
    for ln in lengths:
        out.append(Subset(dataset, indices[offset:offset + ln]))
        offset += ln
    return out


class Sampler:
    def __init__(self, data_source=None):
        self.data_source = data_source

    def __iter__(self):
        raise NotImplementedError

    def __len__(self):
        return len(self.data_source)


class SequenceSampler(Sampler):
    def __iter__(self):
        return iter(range(len(self.data_source)))


class RandomSampler(Sampler):
    def __init__(self, data_source, replacement=False, num_samples=None,
                 generator=None):
        super().__init__(data_source)
        self.replacement = replacement
        self._num_samples = num_samples

    @property
    def num_samples(self):
        return self._num_samples or len(self.data_source)

    def __iter__(self):
        n = len(self.data_source)
        if self.replacement:
            idx = _jax.random.randint(_next_key(), (self.num_samples,), 0, n)
            return iter(np.asarray(idx).tolist())
        return iter(
            _framework_permutation(n)[:self.num_samples].tolist())

    def __len__(self):
        return self.num_samples


class WeightedRandomSampler(Sampler):
    def __init__(self, weights, num_samples, replacement=True):
        self.weights = np.asarray(weights, np.float64)
        self.num_samples = num_samples
        self.replacement = replacement

    def __iter__(self):
        p = self.weights / self.weights.sum()
        # framework PRNG like its siblings — weighted order reproduces
        # under paddle.seed and ignores numpy's global state
        idx = _jax.random.choice(_next_key(), len(self.weights),
                                 (self.num_samples,),
                                 replace=self.replacement,
                                 p=_jax.numpy.asarray(p))
        return iter(np.asarray(idx).tolist())

    def __len__(self):
        return self.num_samples


class BatchSampler(Sampler):
    def __init__(self, dataset=None, sampler=None, shuffle=False, batch_size=1,
                 drop_last=False):
        self.batch_size = batch_size
        self.drop_last = drop_last
        if sampler is not None:
            self.sampler = sampler
        elif shuffle:
            self.sampler = RandomSampler(dataset)
        else:
            self.sampler = SequenceSampler(dataset)

    def __iter__(self):
        batch = []
        for idx in self.sampler:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        n = len(self.sampler)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size


class DistributedBatchSampler(BatchSampler):
    """Rank-sharded batch sampler (reference:
    python/paddle/fluid/dataloader/batch_sampler.py DistributedBatchSampler)."""

    def __init__(self, dataset, batch_size, num_replicas=None, rank=None,
                 shuffle=False, drop_last=False):
        from ..distributed import get_rank, get_world_size
        self.dataset = dataset
        self.batch_size = batch_size
        self.nranks = num_replicas if num_replicas is not None else \
            get_world_size()
        self.local_rank = rank if rank is not None else get_rank()
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.epoch = 0
        self.num_samples = int(np.ceil(len(dataset) / self.nranks))
        self.total_size = self.num_samples * self.nranks

    def __iter__(self):
        n = len(self.dataset)
        if self.shuffle:
            rng = np.random.RandomState(self.epoch)
            indices = rng.permutation(n).tolist()
            self.epoch += 1
        else:
            indices = list(range(n))
        indices += indices[:(self.total_size - n)]
        indices = indices[self.local_rank:self.total_size:self.nranks]
        batch = []
        for idx in indices:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def set_epoch(self, epoch):
        self.epoch = epoch

    def __len__(self):
        if self.drop_last:
            return self.num_samples // self.batch_size
        return (self.num_samples + self.batch_size - 1) // self.batch_size


def default_collate_fn(batch):
    sample = batch[0]
    if isinstance(sample, (Tensor,)):
        return Tensor(np.stack([np.asarray(s.numpy()) for s in batch]))
    if isinstance(sample, np.ndarray):
        return Tensor(np.stack(batch))
    if isinstance(sample, (int, float)):
        return Tensor(np.asarray(batch))
    if isinstance(sample, (list, tuple)):
        transposed = list(zip(*batch))
        return type(sample)(default_collate_fn(list(s)) for s in transposed)
    if isinstance(sample, dict):
        return {k: default_collate_fn([d[k] for d in batch]) for k in sample}
    return batch


def _fetch(dataset, indices, collate_fn):
    return collate_fn([dataset[i] for i in indices])


class _MultiWorkerIter:
    def __init__(self, loader, batches):
        self._loader = loader
        self._batches = batches
        self._pool = mp.Pool(loader.num_workers)
        self._results = _queue.Queue()
        self._stop = False
        self._thread = threading.Thread(target=self._submit, daemon=True)
        self._thread.start()

    def _submit(self):
        pending = []
        for b in self._batches:
            if self._stop:
                break
            pending.append(self._pool.apply_async(
                _fetch, (self._loader.dataset, b, self._loader.collate_fn)))
            while len(pending) > 2 * self._loader.num_workers:
                self._results.put(pending.pop(0).get())
        for r in pending:
            self._results.put(r.get())
        self._results.put(None)

    def __iter__(self):
        return self

    def __next__(self):
        item = self._results.get()
        if item is None:
            self._pool.close()
            raise StopIteration
        return item

    def __del__(self):
        self._stop = True
        try:
            self._pool.terminate()
        except Exception:
            pass


class DataLoader:
    def __init__(self, dataset, feed_list=None, places=None,
                 return_list=True, batch_sampler=None, batch_size=1,
                 shuffle=False, drop_last=False, collate_fn=None,
                 num_workers=0, use_buffer_reader=True, use_shared_memory=True,
                 timeout=0, worker_init_fn=None,
                 prefetch_factor=2, persistent_workers=False):
        self.dataset = dataset
        self.num_workers = num_workers
        self.collate_fn = collate_fn or default_collate_fn
        self._iterable_mode = isinstance(dataset, IterableDataset)
        if self._iterable_mode:
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
        else:
            self.batch_sampler = BatchSampler(dataset, shuffle=shuffle,
                                              batch_size=batch_size,
                                              drop_last=drop_last)

    def __len__(self):
        if self._iterable_mode:
            raise TypeError("IterableDataset DataLoader has no len()")
        return len(self.batch_sampler)

    def __iter__(self):
        if self._iterable_mode:
            return self._iter_iterable()
        if self.num_workers > 0:
            return _MultiWorkerIter(self, list(self.batch_sampler))
        return self._iter_single()

    def _iter_single(self):
        for indices in self.batch_sampler:
            yield self.collate_fn([self.dataset[i] for i in indices])

    def _iter_iterable(self):
        batch = []
        for sample in self.dataset:
            batch.append(sample)
            if len(batch) == self.batch_size:
                yield self.collate_fn(batch)
                batch = []
        if batch and not self.drop_last:
            yield self.collate_fn(batch)


def get_worker_info():
    return None


from .prefetch import ChunkPrefetcher  # noqa: E402,F401
