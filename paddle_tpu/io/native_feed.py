"""ctypes binding for the native C++ data feed (csrc/datafeed).

Reference analog: framework/data_feed.cc driving trainer threads; here the
native reader keeps a prefetch ring of length-prefixed records ahead of the
host loop (which is ahead of jax dispatch). Builds the .so on first use via the
Makefile (g++ is part of the baked toolchain)."""
from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Iterator, List, Optional, Sequence

import numpy as np

from . import IterableDataset

_SRC_DIR = os.path.join(os.path.dirname(__file__), "..", "..", "csrc",
                        "datafeed")
_LIB_PATH = os.path.join(_SRC_DIR, "libdatafeed.so")
_LIB = None


def _load_lib():
    global _LIB
    if _LIB is not None:
        return _LIB
    if not os.path.exists(_LIB_PATH):
        subprocess.run(["make", "-C", _SRC_DIR], check=True,
                       capture_output=True)
    lib = ctypes.CDLL(_LIB_PATH)
    lib.datafeed_create.restype = ctypes.c_void_p
    lib.datafeed_create.argtypes = [
        ctypes.POINTER(ctypes.c_char_p), ctypes.c_int64, ctypes.c_int,
        ctypes.c_int64, ctypes.c_int]
    lib.datafeed_next.restype = ctypes.c_int64
    lib.datafeed_next.argtypes = [ctypes.c_void_p,
                                  ctypes.POINTER(ctypes.c_uint8),
                                  ctypes.c_int64]
    lib.datafeed_queue_size.restype = ctypes.c_int64
    lib.datafeed_queue_size.argtypes = [ctypes.c_void_p]
    lib.datafeed_destroy.argtypes = [ctypes.c_void_p]
    lib.datafeed_write_records.restype = ctypes.c_int64
    lib.datafeed_write_records.argtypes = [
        ctypes.c_char_p, ctypes.POINTER(ctypes.c_uint8),
        ctypes.POINTER(ctypes.c_int64), ctypes.c_int64]
    _LIB = lib
    return lib


def write_record_file(path: str, records: Sequence[bytes]) -> int:
    """Write length-prefixed records via the native writer."""
    lib = _load_lib()
    blob = b"".join(records)
    lengths = np.asarray([len(r) for r in records], np.int64)
    buf = (ctypes.c_uint8 * len(blob)).from_buffer_copy(blob) if blob else \
        (ctypes.c_uint8 * 1)()
    n = lib.datafeed_write_records(
        path.encode(), buf,
        lengths.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        len(records))
    if n < 0:
        raise IOError(f"failed writing records to {path}")
    return int(n)


class NativeRecordReader:
    """Iterate raw record bytes from the native prefetching reader."""

    def __init__(self, files: List[str], num_threads: int = 2,
                 capacity: int = 1024, repeat: int = 1,
                 max_record_bytes: int = 1 << 20):
        self._lib = _load_lib()
        arr = (ctypes.c_char_p * len(files))(
            *[f.encode() for f in files])
        self._handle = self._lib.datafeed_create(
            arr, len(files), num_threads, capacity, repeat)
        if not self._handle:
            raise RuntimeError("datafeed_create failed")
        self._buf = (ctypes.c_uint8 * max_record_bytes)()
        self._buf_len = max_record_bytes
        self._closed = False

    _END_OF_DATA = -3
    _BUFFER_TOO_SMALL = -1

    def __iter__(self) -> Iterator[bytes]:
        while True:
            n = self._lib.datafeed_next(self._handle, self._buf,
                                        self._buf_len)
            if n == self._END_OF_DATA:
                return
            if n == self._BUFFER_TOO_SMALL:  # grow buffer and retry
                self._buf_len *= 2
                self._buf = (ctypes.c_uint8 * self._buf_len)()
                continue
            if n < 0:
                raise IOError("native datafeed read error")
            yield bytes(bytearray(self._buf[:n]))

    def queue_size(self) -> int:
        return self._lib.datafeed_queue_size(self._handle)

    def close(self):
        if not self._closed and self._handle:
            self._lib.datafeed_destroy(self._handle)
            self._closed = True

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class RecordFileDataset(IterableDataset):
    """IterableDataset over native record files with an optional decoder
    (e.g. np.frombuffer) — plugs straight into DataLoader."""

    def __init__(self, files: List[str], decoder=None, num_threads: int = 2,
                 capacity: int = 1024, repeat: int = 1):
        self.files = files
        self.decoder = decoder
        self.num_threads = num_threads
        self.capacity = capacity
        self.repeat = repeat

    def __iter__(self):
        reader = NativeRecordReader(self.files, self.num_threads,
                                    self.capacity, self.repeat)
        try:
            for rec in reader:
                yield self.decoder(rec) if self.decoder else rec
        finally:
            reader.close()
