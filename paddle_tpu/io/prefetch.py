"""Async double-buffered host→device chunk prefetcher.

The scan-fused runner (parallel.ScanTrainStep) consumes [K, ...] stacked
chunks in ONE dispatch; feeding it synchronously would serialize K batch
decodes + one sharded device_put with the chunk's compute. This prefetcher
moves that work onto a background thread: while chunk N computes on device,
the thread stacks the next K host batches and *starts* their sharded
device_put, so the H2D transfer overlaps compute instead of extending the
step. jax transfers are async — device_put returns immediately and the
arrays materialize on the device's transfer stream; by the time the runner
dequeues the chunk the bytes are (usually) already resident.

depth=2 is classic double buffering: one chunk in flight on device, one
staged. Deeper queues only help when decode jitter exceeds a whole chunk's
compute; each extra slot pins another chunk of host+device memory (see
docs/performance.md for the tradeoff).

usage:
    pf = ChunkPrefetcher(batch_iter, scan_steps=8,
                         put_fn=step.device_put_chunk)
    for chunk in pf:              # tuple of device-resident [K, ...] arrays
        losses = step(*chunk)
"""
from __future__ import annotations

import queue as _queue
import threading
import time
import warnings
from typing import Callable, Iterable, Optional

import numpy as np


class _Done:
    pass


class _Err:
    def __init__(self, exc):
        self.exc = exc


def _stack(batches):
    """K per-step batches (tuples/lists of arrays, or bare arrays) →
    tuple of [K, ...] numpy arrays."""
    from ..core.tensor import Tensor

    def as_np(x):
        return np.asarray(x.data if isinstance(x, Tensor) else x)

    first = batches[0]
    if isinstance(first, (tuple, list)):
        return tuple(np.stack([as_np(b[j]) for b in batches])
                     for j in range(len(first)))
    return (np.stack([as_np(b) for b in batches]),)


class ChunkPrefetcher:
    """Background-thread chunk stacker + async H2D stager.

    source: iterable of per-step batches (what a DataLoader yields).
    scan_steps: K — batches per fused chunk.
    put_fn: tuple-of-stacked-np-arrays -> device arrays. Pass the runner's
        `device_put_chunk` so chunks land pre-sharded; default jax.device_put
        (committed to the default device layout).
    depth: max staged chunks (2 = double buffering).

    A trailing partial chunk (< K batches) is DROPPED — a lax.scan chunk has
    a static trip count; `dropped_steps` records how many batches fell off
    so callers can account for them (no silent truncation).

    stall_timeout_s: if the consumer takes nothing for this long while the
    queue is full (iteration abandoned without close() and no context
    manager), the producer gives up and exits instead of busy-polling
    forever with staged device buffers pinned. Raise it when a single
    chunk's device compute can legitimately exceed the default.
    """

    def __init__(self, source: Iterable, scan_steps: int,
                 put_fn: Optional[Callable] = None, depth: int = 2,
                 stall_timeout_s: float = 60.0):
        if scan_steps < 1:
            raise ValueError(f"scan_steps must be >= 1, got {scan_steps}")
        if depth < 1:
            raise ValueError(f"depth must be >= 1, got {depth}")
        if stall_timeout_s <= 0:
            raise ValueError(
                f"stall_timeout_s must be > 0, got {stall_timeout_s}")
        self.source = source
        self.scan_steps = int(scan_steps)
        self.depth = int(depth)
        self.stall_timeout_s = float(stall_timeout_s)
        if put_fn is None:
            import jax
            put_fn = lambda stacked: tuple(jax.device_put(a)  # noqa: E731
                                           for a in stacked)
        self.put_fn = put_fn
        self.dropped_steps = 0
        self.chunks_produced = 0
        # goodput ledger (obs.goodput) — consumer-side blocking waits book
        # to "data_wait" (prefetcher starvation). Producer-thread work is
        # deliberately NOT booked: overlapping it with device compute is
        # the prefetcher's whole point. None = one predicate per __next__.
        self.ledger = None
        self._q: _queue.Queue = _queue.Queue(maxsize=self.depth)
        self._stop = threading.Event()
        self._closed = False
        self._thread: Optional[threading.Thread] = None

    # ---- producer ----
    def _produce(self):
        try:
            it = iter(self.source)
            pending = []
            for batch in it:
                if self._stop.is_set():
                    return
                pending.append(batch)
                if len(pending) < self.scan_steps:
                    continue
                dev = self.put_fn(_stack(pending))  # starts the async H2D
                pending = []
                if not self._bounded_put(dev):
                    return
                self.chunks_produced += 1
            self.dropped_steps = len(pending)
            if pending:
                warnings.warn(
                    f"ChunkPrefetcher dropped a trailing partial chunk of "
                    f"{len(pending)} step(s) (< scan_steps="
                    f"{self.scan_steps})", stacklevel=2)
        except BaseException as e:  # propagate into the consumer
            self._bounded_put(_Err(e))
            return
        self._bounded_put(_Done())

    def _bounded_put(self, item) -> bool:
        """Queue put that can never wedge the producer. Wakes every 100ms so
        close() can join promptly, and — for a consumer that abandoned
        iteration without close() (no context manager) — gives up after
        `stall_timeout_s` of continuous queue-full, dropping the item and
        stopping production so staged device buffers aren't pinned for the
        process lifetime. Returns True iff the item was enqueued."""
        deadline = time.monotonic() + self.stall_timeout_s
        while not self._stop.is_set():
            try:
                self._q.put(item, timeout=0.1)
                return True
            except _queue.Full:
                if time.monotonic() >= deadline:
                    self._stop.set()  # before warn(): filters may raise
                    warnings.warn(
                        f"ChunkPrefetcher consumer took nothing for "
                        f"{self.stall_timeout_s:.0f}s with a full queue; "
                        "assuming iteration was abandoned without close() — "
                        "stopping the producer and dropping staged chunks",
                        stacklevel=2)
                    return False
        return False

    # ---- consumer ----
    def __iter__(self):
        if self._closed:
            return self       # closed: iteration terminates, never restarts
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._produce, daemon=True,
                name="pdtpu-chunk-prefetch")
            self._thread.start()
        return self

    def _take(self):
        """Blocking dequeue of the next staged item (the consumer-side
        starvation wait the goodput ledger books as data_wait)."""
        while True:
            try:
                return self._q.get(timeout=0.1)
            except _queue.Empty:
                if self._closed:  # closed under us mid-wait
                    raise StopIteration

    def __next__(self):
        if self._closed:
            raise StopIteration
        if self._thread is None:
            iter(self)
        if self.ledger is not None:
            with self.ledger.measure("data_wait"):
                item = self._take()
        else:
            item = self._take()
        if isinstance(item, _Done):
            raise StopIteration
        if isinstance(item, _Err):
            raise item.exc
        return item

    def __enter__(self):
        """Context-manager use guarantees the drain discipline: a consumer
        that raises mid-epoch still joins the producer thread and releases
        every staged (in-flight device_put) chunk on the way out — the same
        drain-on-error contract the serving engine holds itself to."""
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    def close(self):
        """Stop the producer thread, join it, and drain staged chunks so
        their device buffers are released. Idempotent; a closed prefetcher
        iterates as exhausted instead of blocking."""
        self._closed = True
        self._stop.set()
        self._drain()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            # the producer may have slipped one last control message in
            # between the drain and its exit — release that too
            self._drain()
            self._thread = None

    def _drain(self):
        try:
            while True:
                self._q.get_nowait()
        except _queue.Empty:
            pass

    def __del__(self):
        try:
            self._stop.set()
        except Exception:
            pass
