"""Tensor creation ops (reference: python/paddle/tensor/creation.py)."""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..core import dtypes
from ..core.tensor import Tensor, apply, to_array


def _d(dtype):
    return dtypes.convert_dtype(dtype) if dtype is not None else dtypes.get_default_dtype()


def to_tensor(data, dtype=None, place=None, stop_gradient=True) -> Tensor:
    if isinstance(data, Tensor):
        t = Tensor(data.data, dtype=dtype, stop_gradient=stop_gradient)
        return t
    arr = to_array(data)
    if dtype is not None:
        arr = arr.astype(dtypes.convert_dtype(dtype))
    elif arr.dtype == jnp.float64:
        arr = arr.astype(dtypes.get_default_dtype())
    return Tensor(arr, stop_gradient=stop_gradient)


def zeros(shape, dtype=None) -> Tensor:
    return Tensor(jnp.zeros(_shape(shape), _d(dtype)))


def ones(shape, dtype=None) -> Tensor:
    return Tensor(jnp.ones(_shape(shape), _d(dtype)))


def full(shape, fill_value, dtype=None) -> Tensor:
    if isinstance(fill_value, Tensor):
        fill_value = fill_value.item()
    return Tensor(jnp.full(_shape(shape), fill_value, _d(dtype)))


def empty(shape, dtype=None) -> Tensor:
    return zeros(shape, dtype)


def zeros_like(x, dtype=None) -> Tensor:
    return apply(lambda a: jnp.zeros_like(a, dtype=dtypes.convert_dtype(dtype)), _t(x))


def ones_like(x, dtype=None) -> Tensor:
    return apply(lambda a: jnp.ones_like(a, dtype=dtypes.convert_dtype(dtype)), _t(x))


def full_like(x, fill_value, dtype=None) -> Tensor:
    return apply(lambda a: jnp.full_like(a, fill_value,
                                         dtype=dtypes.convert_dtype(dtype)), _t(x))


def empty_like(x, dtype=None) -> Tensor:
    return zeros_like(x, dtype)


def arange(start=0, end=None, step=1, dtype=None) -> Tensor:
    if end is None:
        start, end = 0, start
    for v in (start, end, step):
        if isinstance(v, Tensor):
            raise TypeError("arange bounds must be python numbers")
    if dtype is None:
        dtype = (dtypes.int64 if all(
            float(v) == int(v) for v in (start, end, step)) else
            dtypes.get_default_dtype())
    return Tensor(jnp.arange(start, end, step, dtype=dtypes.convert_dtype(dtype)))


def linspace(start, stop, num, dtype=None) -> Tensor:
    return Tensor(jnp.linspace(start, stop, int(num), dtype=_d(dtype)))


def logspace(start, stop, num, base=10.0, dtype=None) -> Tensor:
    return Tensor(jnp.logspace(start, stop, int(num), base=base, dtype=_d(dtype)))


def eye(num_rows, num_columns=None, dtype=None) -> Tensor:
    return Tensor(jnp.eye(num_rows, num_columns, dtype=_d(dtype)))


def diag(x, offset=0, padding_value=0) -> Tensor:
    x = _t(x)

    def f(a):
        if a.ndim == 1:
            out = jnp.diag(a, k=offset)
            if padding_value != 0:
                mask = jnp.eye(*out.shape, k=offset, dtype=bool)
                out = jnp.where(mask, out, padding_value)
            return out
        return jnp.diagonal(a, offset=offset)

    return apply(f, x)


def diagflat(x, offset=0) -> Tensor:
    return apply(lambda a: jnp.diagflat(a, k=offset), _t(x))


def tril(x, diagonal=0) -> Tensor:
    return apply(lambda a: jnp.tril(a, k=diagonal), _t(x))


def triu(x, diagonal=0) -> Tensor:
    return apply(lambda a: jnp.triu(a, k=diagonal), _t(x))


def meshgrid(*args):
    args = [_t(a) for a in args]
    outs = apply(lambda *xs: tuple(jnp.meshgrid(*xs, indexing="ij")), *args)
    return list(outs) if isinstance(outs, tuple) else [outs]


def assign(x, output=None) -> Tensor:
    src = _t(x)
    if output is None:
        return apply(lambda a: a + 0 if jnp.issubdtype(a.dtype, jnp.inexact) else a,
                     src)
    output.set_value(src.data)
    return output


def clone(x) -> Tensor:
    return _t(x).clone()


def _t(x) -> Tensor:
    return x if isinstance(x, Tensor) else to_tensor(x)


def _shape(shape):
    if isinstance(shape, Tensor):
        return tuple(int(s) for s in shape.numpy())
    if isinstance(shape, (int, np.integer)):
        return (int(shape),)
    return tuple(int(s.item() if isinstance(s, Tensor) else s) for s in shape)
