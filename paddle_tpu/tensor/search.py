"""Search/sort ops (reference: python/paddle/tensor/search.py)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core import dtypes
from ..core.tensor import Tensor, apply
from .creation import _t


def argmax(x, axis=None, keepdim=False, dtype="int64", name=None):
    d = dtypes.convert_dtype(dtype)
    return apply(lambda a: jnp.argmax(a, axis=axis, keepdims=keepdim and
                                      axis is not None).astype(d), _t(x))


def argmin(x, axis=None, keepdim=False, dtype="int64", name=None):
    d = dtypes.convert_dtype(dtype)
    return apply(lambda a: jnp.argmin(a, axis=axis, keepdims=keepdim and
                                      axis is not None).astype(d), _t(x))


def argsort(x, axis=-1, descending=False, name=None):
    def f(a):
        idx = jnp.argsort(a, axis=axis)
        if descending:
            idx = jnp.flip(idx, axis=axis)
        return idx.astype(jnp.int64)

    return apply(f, _t(x))


def sort(x, axis=-1, descending=False, name=None):
    def f(a):
        out = jnp.sort(a, axis=axis)
        if descending:
            out = jnp.flip(out, axis=axis)
        return out

    return apply(f, _t(x))


def topk(x, k, axis=None, largest=True, sorted=True, name=None):
    x = _t(x)
    if isinstance(k, Tensor):
        k = int(k.item())

    def f(a):
        ax = -1 if axis is None else axis
        moved = jnp.moveaxis(a, ax, -1)
        src = moved if largest else -moved
        vals, idx = jax.lax.top_k(src, k)
        if not largest:
            vals = -vals
        return (jnp.moveaxis(vals, -1, ax),
                jnp.moveaxis(idx, -1, ax).astype(jnp.int64))

    return apply(f, x)


def kthvalue(x, k, axis=-1, keepdim=False, name=None):
    def f(a):
        srt = jnp.sort(a, axis=axis)
        idx = jnp.argsort(a, axis=axis)
        vals = jnp.take(srt, k - 1, axis=axis)
        inds = jnp.take(idx, k - 1, axis=axis).astype(jnp.int64)
        if keepdim:
            vals = jnp.expand_dims(vals, axis)
            inds = jnp.expand_dims(inds, axis)
        return vals, inds

    return apply(f, _t(x))


def mode(x, axis=-1, keepdim=False, name=None):
    def f(a):
        # O(n^2) pairwise count along the axis — fine for the small n this op
        # sees; keeps everything static-shaped for XLA.
        moved = jnp.moveaxis(a, axis, -1)
        eq = moved[..., :, None] == moved[..., None, :]
        counts = eq.sum(-1)
        best = jnp.argmax(counts, axis=-1)
        vals = jnp.take_along_axis(moved, best[..., None], axis=-1)[..., 0]
        hit = moved == vals[..., None]
        idx = jnp.max(jnp.where(hit, jnp.arange(moved.shape[-1]), -1), axis=-1)
        if keepdim:
            return (jnp.expand_dims(vals, axis),
                    jnp.expand_dims(idx, axis).astype(jnp.int64))
        return vals, idx.astype(jnp.int64)

    return apply(f, _t(x))


def searchsorted(sorted_sequence, values, out_int32=False, right=False, name=None):
    side = "right" if right else "left"
    d = jnp.int32 if out_int32 else jnp.int64
    return apply(lambda s, v: jnp.searchsorted(s, v, side=side).astype(d),
                 _t(sorted_sequence), _t(values))


def masked_select(x, mask, name=None):
    import numpy as np
    arr = np.asarray(_t(x).numpy())
    m = np.asarray(_t(mask).numpy()).astype(bool)
    return Tensor(arr[m])


def index_put(x, indices, value, accumulate=False, name=None):
    x = _t(x)
    idx = tuple(i.data if isinstance(i, Tensor) else i for i in indices)
    v = _t(value)

    def f(a, vv):
        if accumulate:
            return a.at[idx].add(vv)
        return a.at[idx].set(vv)

    return apply(f, x, v)


def bucketize(x, sorted_sequence, out_int32=False, right=False, name=None):
    return searchsorted(sorted_sequence, x, out_int32, right)
