"""Comparison & logical ops (reference: python/paddle/tensor/logic.py)."""
from __future__ import annotations

import jax.numpy as jnp

from ..core.tensor import Tensor, apply
from .creation import _t


def _cmp(fn):
    def op(x, y, name=None):
        return apply(fn, _t(x), _t(y))
    return op


equal = _cmp(jnp.equal)
not_equal = _cmp(jnp.not_equal)
greater_than = _cmp(jnp.greater)
greater_equal = _cmp(jnp.greater_equal)
less_than = _cmp(jnp.less)
less_equal = _cmp(jnp.less_equal)
logical_and = _cmp(jnp.logical_and)
logical_or = _cmp(jnp.logical_or)
logical_xor = _cmp(jnp.logical_xor)
_bitwise_and_impl = _cmp(jnp.bitwise_and)
_bitwise_or_impl = _cmp(jnp.bitwise_or)
_bitwise_xor_impl = _cmp(jnp.bitwise_xor)


def _with_out(result, out):
    """Reference bitwise ops take out=None: honored as an in-place
    overwrite of `out` (the logical_*/bitwise_* op contract)."""
    if out is None:
        return result
    from .manipulation import _inplace_via_tape
    return _inplace_via_tape(out, result, "bitwise_out")


def bitwise_and(x, y, out=None, name=None):
    return _with_out(_bitwise_and_impl(x, y), out)


def bitwise_or(x, y, out=None, name=None):
    return _with_out(_bitwise_or_impl(x, y), out)


def bitwise_xor(x, y, out=None, name=None):
    return _with_out(_bitwise_xor_impl(x, y), out)


def logical_not(x, name=None):
    return apply(jnp.logical_not, _t(x))


def bitwise_not(x, out=None, name=None):
    return _with_out(apply(jnp.bitwise_not, _t(x)), out)


def equal_all(x, y, name=None):
    return apply(lambda a, b: jnp.array_equal(a, b), _t(x), _t(y))


def allclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return apply(lambda a, b: jnp.allclose(a, b, rtol=rtol, atol=atol,
                                           equal_nan=equal_nan), _t(x), _t(y))


def isclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return apply(lambda a, b: jnp.isclose(a, b, rtol=rtol, atol=atol,
                                          equal_nan=equal_nan), _t(x), _t(y))


def is_empty(x, name=None):
    return Tensor(jnp.array(_t(x).size == 0))


def is_tensor(x):
    return isinstance(x, Tensor)


def where(condition, x=None, y=None, name=None):
    if x is None and y is None:
        return nonzero(condition, as_tuple=True)
    return apply(lambda c, a, b: jnp.where(c, a, b), _t(condition), _t(x), _t(y))


def nonzero(x, as_tuple=False):
    # Data-dependent shape → host round-trip (mirrors reference CPU behavior).
    import numpy as np
    arr = np.asarray(_t(x).numpy())
    idx = np.nonzero(arr)
    if as_tuple:
        return tuple(Tensor(i.astype(np.int64)) for i in idx)
    return Tensor(np.stack(idx, axis=1).astype(np.int64))
