"""Comparison & logical ops (reference: python/paddle/tensor/logic.py)."""
from __future__ import annotations

import jax.numpy as jnp

from ..core.tensor import Tensor, apply
from .creation import _t


def _cmp(fn):
    def op(x, y, name=None):
        return apply(fn, _t(x), _t(y))
    return op


equal = _cmp(jnp.equal)
not_equal = _cmp(jnp.not_equal)
greater_than = _cmp(jnp.greater)
greater_equal = _cmp(jnp.greater_equal)
less_than = _cmp(jnp.less)
less_equal = _cmp(jnp.less_equal)
logical_and = _cmp(jnp.logical_and)
logical_or = _cmp(jnp.logical_or)
logical_xor = _cmp(jnp.logical_xor)
bitwise_and = _cmp(jnp.bitwise_and)
bitwise_or = _cmp(jnp.bitwise_or)
bitwise_xor = _cmp(jnp.bitwise_xor)


def logical_not(x, name=None):
    return apply(jnp.logical_not, _t(x))


def bitwise_not(x, name=None):
    return apply(jnp.bitwise_not, _t(x))


def equal_all(x, y, name=None):
    return apply(lambda a, b: jnp.array_equal(a, b), _t(x), _t(y))


def allclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return apply(lambda a, b: jnp.allclose(a, b, rtol=rtol, atol=atol,
                                           equal_nan=equal_nan), _t(x), _t(y))


def isclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return apply(lambda a, b: jnp.isclose(a, b, rtol=rtol, atol=atol,
                                          equal_nan=equal_nan), _t(x), _t(y))


def is_empty(x, name=None):
    return Tensor(jnp.array(_t(x).size == 0))


def is_tensor(x):
    return isinstance(x, Tensor)


def where(condition, x=None, y=None, name=None):
    if x is None and y is None:
        return nonzero(condition, as_tuple=True)
    return apply(lambda c, a, b: jnp.where(c, a, b), _t(condition), _t(x), _t(y))


def nonzero(x, as_tuple=False):
    # Data-dependent shape → host round-trip (mirrors reference CPU behavior).
    import numpy as np
    arr = np.asarray(_t(x).numpy())
    idx = np.nonzero(arr)
    if as_tuple:
        return tuple(Tensor(i.astype(np.int64)) for i in idx)
    return Tensor(np.stack(idx, axis=1).astype(np.int64))
