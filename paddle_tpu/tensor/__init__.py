"""paddle.tensor analog: functional tensor surface + Tensor method patching.

The reference patches ~300 methods onto its VarBase via
python/paddle/fluid/dygraph/varbase_patch_methods.py and generated core.ops functions;
here the same functions are plain jax-backed callables attached to Tensor once.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..core.tensor import Tensor, apply
from . import creation, linalg, logic, manipulation, math, random, search, stat
from .creation import *  # noqa: F401,F403
from .linalg import *  # noqa: F401,F403
from .logic import *  # noqa: F401,F403
from .lod import (LoDTensor, SelectedRows, sequence_expand,  # noqa: F401
                  sequence_mask, sequence_pad, sequence_unpad)
from .manipulation import *  # noqa: F401,F403
from .math import *  # noqa: F401,F403
from .random import *  # noqa: F401,F403
from .search import *  # noqa: F401,F403
from .stat import (median, nanmedian, nanquantile, quantile, std,  # noqa: F401
                   var)
from .creation import _t


def einsum(equation, *operands):
    tensors = [_t(o) for o in operands]
    return apply(lambda *xs: jnp.einsum(equation, *xs), *tensors)


_BINARY_OPS = {
    "__add__": math.add, "__radd__": lambda x, y: math.add(y, x),
    "__sub__": math.subtract, "__rsub__": lambda x, y: math.subtract(y, x),
    "__mul__": math.multiply, "__rmul__": lambda x, y: math.multiply(y, x),
    "__truediv__": math.divide, "__rtruediv__": lambda x, y: math.divide(y, x),
    "__floordiv__": math.floor_divide,
    "__mod__": math.mod,
    "__pow__": math.pow, "__rpow__": lambda x, y: math.pow(y, x),
    "__matmul__": linalg.matmul,
    "__rmatmul__": lambda x, y: linalg.matmul(y, x),
    "__eq__": logic.equal, "__ne__": logic.not_equal,
    "__lt__": logic.less_than, "__le__": logic.less_equal,
    "__gt__": logic.greater_than, "__ge__": logic.greater_equal,
    "__and__": logic.bitwise_and, "__or__": logic.bitwise_or,
    "__xor__": logic.bitwise_xor,
}

_METHODS = dict(
    # math
    add=math.add, subtract=math.subtract, multiply=math.multiply,
    divide=math.divide, pow=math.pow, mod=math.mod, floor_divide=math.floor_divide,
    maximum=math.maximum, minimum=math.minimum, remainder=math.remainder,
    exp=math.exp, log=math.log, log2=math.log2, log10=math.log10,
    log1p=math.log1p, sqrt=math.sqrt, rsqrt=math.rsqrt, square=math.square,
    abs=math.abs, sign=math.sign, floor=math.floor, ceil=math.ceil,
    round=math.round, trunc=math.trunc, sin=math.sin, cos=math.cos,
    tan=math.tan, tanh=math.tanh, sigmoid=math.sigmoid, erf=math.erf,
    reciprocal=math.reciprocal, neg=math.neg, clip=math.clip, scale=math.scale,
    isnan=math.isnan, isinf=math.isinf, isfinite=math.isfinite,
    sum=math.sum, mean=math.mean, max=math.max, min=math.min, prod=math.prod,
    logsumexp=math.logsumexp, all=math.all, any=math.any,
    cumsum=math.cumsum, cumprod=math.cumprod, trace=math.trace,
    kron=math.kron, inner=math.inner, outer=math.outer, lerp=math.lerp,
    erfinv=math.erfinv, frac=math.frac, digamma=math.digamma,
    lgamma=math.lgamma, multiplex=math.multiplex, rad2deg=math.rad2deg,
    deg2rad=math.deg2rad, heaviside=math.heaviside, add_=math.add_,
    subtract_=math.subtract_, clip_=math.clip_, fill_=math.fill_,
    zero_=math.zero_, exp_=math.exp_, sqrt_=math.sqrt_, rsqrt_=math.rsqrt_,
    ceil_=math.ceil_, floor_=math.floor_, round_=math.round_,
    reciprocal_=math.reciprocal_, scale_=math.scale_,
    flatten_=manipulation.flatten_,
    # stat
    var=stat.var, std=stat.std, median=stat.median, quantile=stat.quantile,
    # linalg
    matmul=linalg.matmul, mm=linalg.mm, bmm=linalg.bmm, dot=linalg.dot,
    norm=linalg.norm, dist=linalg.dist, cholesky=linalg.cholesky,
    inverse=linalg.inv, cross=linalg.cross, t=linalg.t,
    matrix_power=linalg.matrix_power, bincount=linalg.bincount,
    histogram=linalg.histogram, tensordot=linalg.tensordot,
    # manipulation
    reshape=manipulation.reshape, reshape_=manipulation.reshape_,
    flatten=manipulation.flatten, transpose=manipulation.transpose,
    squeeze=manipulation.squeeze, unsqueeze=manipulation.unsqueeze,
    expand=manipulation.expand, expand_as=manipulation.expand_as,
    broadcast_to=manipulation.broadcast_to, tile=manipulation.tile,
    roll=manipulation.roll, flip=manipulation.flip, gather=manipulation.gather,
    gather_nd=manipulation.gather_nd, scatter=manipulation.scatter,
    split=manipulation.split, chunk=manipulation.chunk, unbind=manipulation.unbind,
    index_select=manipulation.index_select, slice=manipulation.slice,
    take_along_axis=manipulation.take_along_axis, pad=manipulation.pad,
    put_along_axis=manipulation.put_along_axis,
    rot90=manipulation.rot90, nonzero=logic.nonzero,
    diag=creation.diag,
    repeat_interleave=manipulation.repeat_interleave, unique=manipulation.unique,
    # logic
    equal=logic.equal, not_equal=logic.not_equal,
    greater_than=logic.greater_than, greater_equal=logic.greater_equal,
    less_than=logic.less_than, less_equal=logic.less_equal,
    logical_and=logic.logical_and, logical_or=logic.logical_or,
    logical_not=logic.logical_not, logical_xor=logic.logical_xor,
    equal_all=logic.equal_all, allclose=logic.allclose, isclose=logic.isclose,
    where=lambda x, cond, y: logic.where(cond, x, y),
    masked_select=search.masked_select,
    # search
    argmax=search.argmax, argmin=search.argmin, argsort=search.argsort,
    sort=search.sort, topk=search.topk, kthvalue=search.kthvalue,
    mode=search.mode,
    # random (in-place)
    uniform_=random.uniform_, normal_=random.normal_,
    exponential_=random.exponential_,
    # method-surface tail (reference tensor/__init__.py attaches every
    # name in its tensor list as a Tensor method; x.concat(y) binds self
    # as the list head the way the reference's monkey-patch does)
    acos=math.acos, asin=math.asin, atan=math.atan, sinh=math.sinh,
    cosh=math.cosh, stanh=math.stanh, conj=math.conj, real=math.real,
    imag=math.imag, floor_mod=math.floor_mod, add_n=math.add_n,
    addmm=math.addmm, increment=math.increment,
    rank=manipulation.rank,
    is_empty=logic.is_empty, is_tensor=logic.is_tensor,
    bitwise_and=logic.bitwise_and, bitwise_or=logic.bitwise_or,
    bitwise_xor=logic.bitwise_xor, bitwise_not=logic.bitwise_not,
    broadcast_shape=math.broadcast_shape,
    mv=linalg.mv, index_sample=manipulation.index_sample,
    scatter_=manipulation.scatter_, scatter_nd=manipulation.scatter_nd,
    scatter_nd_add=manipulation.scatter_nd_add,
    shard_index=manipulation.shard_index, reverse=manipulation.reverse,
    strided_slice=manipulation.strided_slice,
    squeeze_=manipulation.squeeze_, unsqueeze_=manipulation.unsqueeze_,
    tanh_=math.tanh_, unstack=manipulation.unstack,
    concat=lambda x, others, axis=0: manipulation.concat(
        [x] + (list(others) if isinstance(others, (list, tuple))
               else [others]), axis),
    stack=lambda x, others, axis=0: manipulation.stack(
        [x] + (list(others) if isinstance(others, (list, tuple))
               else [others]), axis),
    broadcast_tensors=lambda x, others: manipulation.broadcast_tensors(
        [x] + (list(others) if isinstance(others, (list, tuple))
               else [others])),
)


def monkey_patch_tensor():
    for name, fn in _BINARY_OPS.items():
        setattr(Tensor, name, (lambda f: lambda self, other: f(self, other))(fn))
    Tensor.__neg__ = lambda self: math.neg(self)
    Tensor.__abs__ = lambda self: math.abs(self)
    Tensor.__invert__ = lambda self: logic.logical_not(self)
    for name, fn in _METHODS.items():
        setattr(Tensor, name, (lambda f: lambda self, *a, **k: f(self, *a, **k))(fn))

    @property
    def T(self):
        return apply(lambda a: jnp.transpose(a), self)

    Tensor.T = T


monkey_patch_tensor()
