"""Ragged sequences: LoDTensor metadata + the sequence ops models actually
use.

Reference: paddle/fluid/framework/lod_tensor.h (level-of-detail offsets over
a packed dense tensor) and operators/sequence_ops/ (~20 ragged ops:
sequence_pad, sequence_unpad, sequence_expand, sequence_mask, ...).

TPU-native stance: XLA wants STATIC shapes, so ragged data lives as
(packed values, offsets) on the host side and converts to padded dense +
length mask at the device boundary — exactly what sequence_pad does. The
ops here are the conversion layer; padded compute + masks is the idiomatic
TPU representation (same call the reference's own NLP models make before
dense compute).
"""
from __future__ import annotations

from typing import List, Sequence

import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor, apply
from .creation import _t


class LoDTensor:
    """A packed dense tensor + level-of-detail offsets (lod_tensor.h analog).

    lod is a list of levels; each level is a monotonically increasing offset
    vector [0, ...]; level[-1] partitions the rows of `data`.
    """

    def __init__(self, data, lod: Sequence[Sequence[int]]):
        from ..core.errors import InvalidArgumentError, enforce
        self.tensor = data if isinstance(data, Tensor) else Tensor(data)
        self.lod = [list(level) for level in lod]
        enforce(self.lod and all(self.lod),
                "lod must contain at least one non-empty offset level",
                InvalidArgumentError)
        for level in self.lod:
            enforce(level[0] == 0 and all(
                a <= b for a, b in zip(level, level[1:])),
                "lod levels must be ascending offsets starting at 0",
                InvalidArgumentError)
        enforce(self.lod[-1][-1] == self.tensor.shape[0],
                f"last lod level must cover all {self.tensor.shape[0]} "
                f"packed rows (got offsets ending at {self.lod[-1][-1]})",
                InvalidArgumentError)

    @property
    def data(self):
        return self.tensor.data

    def sequence_lengths(self) -> List[int]:
        last = self.lod[-1]
        return [b - a for a, b in zip(last, last[1:])]

    def num_sequences(self) -> int:
        return len(self.lod[-1]) - 1

    @classmethod
    def from_sequences(cls, seqs: Sequence[np.ndarray]) -> "LoDTensor":
        lens = [0]
        for s in seqs:
            lens.append(lens[-1] + len(s))
        return cls(np.concatenate([np.asarray(s) for s in seqs], axis=0),
                   [lens])

    def to_padded(self, pad_value=0.0, maxlen=None):
        """sequence_pad_op analog: -> (padded [N, maxlen, ...], lengths)."""
        return sequence_pad(self, pad_value, maxlen)

    def __repr__(self):
        return f"LoDTensor(shape={self.tensor.shape}, lod={self.lod})"


def sequence_pad(x: LoDTensor, pad_value=0.0, maxlen=None):
    """Pack -> padded dense + lengths (sequence_pad_op.cc: padded_length
    must cover the longest sequence)."""
    from ..core.errors import InvalidArgumentError, enforce
    lens = x.sequence_lengths()
    n = len(lens)
    longest = max(lens) if lens else 0
    if maxlen is not None:
        enforce(maxlen >= longest,
                f"sequence_pad maxlen={maxlen} is shorter than the longest "
                f"sequence ({longest})", InvalidArgumentError)
    m = maxlen or longest
    trailing = x.tensor.shape[1:]
    arr = np.asarray(x.tensor.data)
    out = np.full([n, m] + trailing, pad_value, dtype=arr.dtype)
    last = x.lod[-1]
    for i, (a, b) in enumerate(zip(last, last[1:])):
        out[i, :b - a] = arr[a:b]
    return Tensor(out), Tensor(np.asarray(lens, np.int64))


def sequence_unpad(x, length):
    """Padded dense + lengths -> LoDTensor (sequence_unpad_op.cc)."""
    arr = np.asarray(_t(x).data)
    lens = [int(v) for v in np.asarray(_t(length).data)]
    packed = np.concatenate([arr[i, :l] for i, l in enumerate(lens)], axis=0)
    offsets = [0]
    for l in lens:
        offsets.append(offsets[-1] + l)
    return LoDTensor(packed, [offsets])


def sequence_mask(lengths, maxlen=None, dtype="int64"):
    """[N] lengths -> [N, maxlen] 0/1 mask (sequence_mask_op.cc); the device
    op every padded-compute consumer actually needs."""
    from ..core import dtypes
    d = dtypes.convert_dtype(dtype)
    lt = _t(lengths)
    m = maxlen if maxlen is not None else int(jnp.max(lt.data))

    def f(ln):
        ar = jnp.arange(m)[None, :]
        return (ar < ln[:, None]).astype(d)

    return apply(f, lt)


def sequence_expand(x: LoDTensor, y: LoDTensor, ref_level=-1) -> LoDTensor:
    """Repeat each sequence of x to match y's ref_level lod
    (sequence_expand_op.cc: x and the ref level must have equally many
    sequences)."""
    from ..core.errors import InvalidArgumentError, enforce
    arr = np.asarray(x.tensor.data)
    x_off = x.lod[-1]
    y_off = y.lod[ref_level]
    enforce(len(x_off) == len(y_off),
            f"sequence_expand: x has {len(x_off) - 1} sequences but y's "
            f"ref level has {len(y_off) - 1}", InvalidArgumentError)
    pieces = []
    offsets = [0]
    for i, (a, b) in enumerate(zip(x_off, x_off[1:])):
        repeat = y_off[i + 1] - y_off[i]
        for _ in range(max(repeat, 0)):
            pieces.append(arr[a:b])
            offsets.append(offsets[-1] + (b - a))
    packed = (np.concatenate(pieces, axis=0) if pieces
              else arr[:0])
    return LoDTensor(packed, [offsets])


# canonical implementation lives in core.selected_rows (it is also what the
# sparse-embedding tape and the optimizers' row-wise rules produce/consume)
from ..core.selected_rows import SelectedRows  # noqa: E402,F401
