"""Ragged sequences: LoDTensor metadata + the sequence ops models actually
use.

Reference: paddle/fluid/framework/lod_tensor.h (level-of-detail offsets over
a packed dense tensor) and operators/sequence_ops/ (~20 ragged ops:
sequence_pad, sequence_unpad, sequence_expand, sequence_mask, ...).

TPU-native stance: XLA wants STATIC shapes, so ragged data lives as
(packed values, offsets) on the host side and converts to padded dense +
length mask at the device boundary — exactly what sequence_pad does. The
ops here are the conversion layer; padded compute + masks is the idiomatic
TPU representation (same call the reference's own NLP models make before
dense compute).
"""
from __future__ import annotations

from typing import List, Sequence

import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor, apply
from .creation import _t


class LoDTensor:
    """A packed dense tensor + level-of-detail offsets (lod_tensor.h analog).

    lod is a list of levels; each level is a monotonically increasing offset
    vector [0, ...]; level[-1] partitions the rows of `data`.
    """

    def __init__(self, data, lod: Sequence[Sequence[int]]):
        from ..core.errors import InvalidArgumentError, enforce
        self.tensor = data if isinstance(data, Tensor) else Tensor(data)
        self.lod = [list(level) for level in lod]
        enforce(self.lod and all(self.lod),
                "lod must contain at least one non-empty offset level",
                InvalidArgumentError)
        for level in self.lod:
            enforce(level[0] == 0 and all(
                a <= b for a, b in zip(level, level[1:])),
                "lod levels must be ascending offsets starting at 0",
                InvalidArgumentError)
        enforce(self.lod[-1][-1] == self.tensor.shape[0],
                f"last lod level must cover all {self.tensor.shape[0]} "
                f"packed rows (got offsets ending at {self.lod[-1][-1]})",
                InvalidArgumentError)

    @property
    def data(self):
        return self.tensor.data

    def sequence_lengths(self) -> List[int]:
        last = self.lod[-1]
        return [b - a for a, b in zip(last, last[1:])]

    def num_sequences(self) -> int:
        return len(self.lod[-1]) - 1

    @classmethod
    def from_sequences(cls, seqs: Sequence[np.ndarray]) -> "LoDTensor":
        lens = [0]
        for s in seqs:
            lens.append(lens[-1] + len(s))
        return cls(np.concatenate([np.asarray(s) for s in seqs], axis=0),
                   [lens])

    def to_padded(self, pad_value=0.0, maxlen=None):
        """sequence_pad_op analog: -> (padded [N, maxlen, ...], lengths)."""
        return sequence_pad(self, pad_value, maxlen)

    def __repr__(self):
        return f"LoDTensor(shape={self.tensor.shape}, lod={self.lod})"


def sequence_pad(x: LoDTensor, pad_value=0.0, maxlen=None):
    """Pack -> padded dense + lengths (sequence_pad_op.cc: padded_length
    must cover the longest sequence)."""
    from ..core.errors import InvalidArgumentError, enforce
    lens = x.sequence_lengths()
    n = len(lens)
    longest = max(lens) if lens else 0
    if maxlen is not None:
        enforce(maxlen >= longest,
                f"sequence_pad maxlen={maxlen} is shorter than the longest "
                f"sequence ({longest})", InvalidArgumentError)
    m = maxlen or longest
    trailing = x.tensor.shape[1:]
    arr = np.asarray(x.tensor.data)
    out = np.full([n, m] + trailing, pad_value, dtype=arr.dtype)
    last = x.lod[-1]
    for i, (a, b) in enumerate(zip(last, last[1:])):
        out[i, :b - a] = arr[a:b]
    return Tensor(out), Tensor(np.asarray(lens, np.int64))


def sequence_unpad(x, length):
    """Padded dense + lengths -> LoDTensor (sequence_unpad_op.cc)."""
    arr = np.asarray(_t(x).data)
    lens = [int(v) for v in np.asarray(_t(length).data)]
    packed = np.concatenate([arr[i, :l] for i, l in enumerate(lens)], axis=0)
    offsets = [0]
    for l in lens:
        offsets.append(offsets[-1] + l)
    return LoDTensor(packed, [offsets])


def sequence_mask(lengths, maxlen=None, dtype="int64"):
    """[N] lengths -> [N, maxlen] 0/1 mask (sequence_mask_op.cc); the device
    op every padded-compute consumer actually needs."""
    from ..core import dtypes
    d = dtypes.convert_dtype(dtype)
    lt = _t(lengths)
    m = maxlen if maxlen is not None else int(jnp.max(lt.data))

    def f(ln):
        ar = jnp.arange(m)[None, :]
        return (ar < ln[:, None]).astype(d)

    return apply(f, lt)


def sequence_expand(x: LoDTensor, y: LoDTensor, ref_level=-1) -> LoDTensor:
    """Repeat each sequence of x to match y's ref_level lod
    (sequence_expand_op.cc: x and the ref level must have equally many
    sequences)."""
    from ..core.errors import InvalidArgumentError, enforce
    arr = np.asarray(x.tensor.data)
    x_off = x.lod[-1]
    y_off = y.lod[ref_level]
    enforce(len(x_off) == len(y_off),
            f"sequence_expand: x has {len(x_off) - 1} sequences but y's "
            f"ref level has {len(y_off) - 1}", InvalidArgumentError)
    pieces = []
    offsets = [0]
    for i, (a, b) in enumerate(zip(x_off, x_off[1:])):
        repeat = y_off[i + 1] - y_off[i]
        for _ in range(max(repeat, 0)):
            pieces.append(arr[a:b])
            offsets.append(offsets[-1] + (b - a))
    packed = (np.concatenate(pieces, axis=0) if pieces
              else arr[:0])
    return LoDTensor(packed, [offsets])


# canonical implementation lives in core.selected_rows (it is also what the
# sparse-embedding tape and the optimizers' row-wise rules produce/consume)
from ..core.selected_rows import SelectedRows  # noqa: E402,F401


def sequence_concat(xs: Sequence[LoDTensor]) -> LoDTensor:
    """sequence_concat_op: concatenate the i-th sequences of each input
    (NOT a plain row concat — per-sequence interleaving)."""
    n = xs[0].num_sequences()
    from ..core.errors import InvalidArgumentError, enforce
    for x in xs:
        enforce(x.num_sequences() == n,
                "sequence_concat inputs must hold the same sequence count",
                InvalidArgumentError)
    seqs = []
    for i in range(n):
        parts = []
        for x in xs:
            lo, hi = x.lod[-1][i], x.lod[-1][i + 1]
            parts.append(np.asarray(x.data)[lo:hi])
        seqs.append(np.concatenate(parts, axis=0))
    return LoDTensor.from_sequences(seqs)


def sequence_reverse(x: LoDTensor) -> LoDTensor:
    """sequence_reverse_op: reverse rows WITHIN each sequence."""
    d = np.asarray(x.data)
    out = d.copy()
    last = x.lod[-1]
    for a, b in zip(last, last[1:]):
        out[a:b] = d[a:b][::-1]
    return LoDTensor(out, [list(x.lod[-1])])


def sequence_pool(x: LoDTensor, pool_type: str = "sum"):
    """sequence_pool_op: per-sequence reduction over the packed rows.
    pool_type: sum | average | max | min | sqrt | last | first.
    Returns a dense Tensor [num_seqs, ...]."""
    d = np.asarray(x.data)
    # mean-family reductions compute in fp32; max/min/first/last keep the
    # input dtype (pooled int ids must stay exact ints)
    if pool_type in ("sum", "average", "sqrt") and not np.issubdtype(
            d.dtype, np.floating):
        d = d.astype(np.float32)
    last = x.lod[-1]
    outs = []
    for a, b in zip(last, last[1:]):
        seg = d[a:b]
        if b == a:  # empty sequence pools to 0 (op semantics)
            outs.append(np.zeros(d.shape[1:], d.dtype))
            continue
        if pool_type == "sum":
            outs.append(seg.sum(0))
        elif pool_type == "average":
            outs.append(seg.mean(0))
        elif pool_type == "sqrt":
            outs.append(seg.sum(0) / np.sqrt(len(seg)))
        elif pool_type == "max":
            outs.append(seg.max(0))
        elif pool_type == "min":
            outs.append(seg.min(0))
        elif pool_type == "last":
            outs.append(seg[-1])
        elif pool_type == "first":
            outs.append(seg[0])
        else:
            raise ValueError(f"unknown pool_type {pool_type!r}")
    from .creation import to_tensor
    return to_tensor(np.stack(outs))


def sequence_softmax(x: LoDTensor) -> LoDTensor:
    """sequence_softmax_op: softmax over each sequence's rows (x is [N] or
    [N, 1] packed scores)."""
    d = np.asarray(x.data, np.float32)
    flat = d.reshape(len(d))
    out = np.empty_like(flat)
    last = x.lod[-1]
    for a, b in zip(last, last[1:]):
        seg = flat[a:b]
        e = np.exp(seg - seg.max()) if b > a else seg
        out[a:b] = e / e.sum() if b > a else seg
    return LoDTensor(out.reshape(d.shape), [list(last)])


def sequence_enumerate(x: LoDTensor, win_size: int, pad_value: int = 0):
    """sequence_enumerate_op: sliding windows of ids per sequence,
    padded with pad_value past the end. [N] int -> [N, win_size]."""
    d = np.asarray(x.data).reshape(-1)
    out = np.full((len(d), win_size), pad_value, d.dtype)
    last = x.lod[-1]
    for a, b in zip(last, last[1:]):
        for i in range(a, b):
            take = min(win_size, b - i)
            out[i, :take] = d[i:i + take]
    return LoDTensor(out, [list(last)])


def sequence_erase(x: LoDTensor, tokens: Sequence[int]) -> LoDTensor:
    """sequence_erase_op: drop the listed token ids from each sequence."""
    d = np.asarray(x.data).reshape(-1)
    last = x.lod[-1]
    seqs = []
    for a, b in zip(last, last[1:]):
        seg = d[a:b]
        seqs.append(seg[~np.isin(seg, list(tokens))])
    return LoDTensor.from_sequences(seqs)


def sequence_expand_as(x: LoDTensor, y: LoDTensor) -> LoDTensor:
    """sequence_expand_as_op: repeat x's i-th ROW len(y_i) times."""
    d = np.asarray(x.data)
    lens = y.sequence_lengths()
    from ..core.errors import InvalidArgumentError, enforce
    enforce(len(lens) == d.shape[0],
            "sequence_expand_as: x rows must match y's sequence count",
            InvalidArgumentError)
    seqs = [np.repeat(d[i:i + 1], lens[i], axis=0) for i in range(len(lens))]
    return LoDTensor.from_sequences(seqs)


def sequence_slice(x: LoDTensor, offset: Sequence[int],
                   length: Sequence[int]) -> LoDTensor:
    """sequence_slice_op: per-sequence [offset, offset+length) row slice.
    Bounds are enforced like the reference (offset+length within the
    sequence) — a silent out-of-range slice would read the NEXT sequence."""
    from ..core.errors import InvalidArgumentError, enforce
    d = np.asarray(x.data)
    last = x.lod[-1]
    seqs = []
    for i, (a, b) in enumerate(zip(last, last[1:])):
        o, L = int(offset[i]), int(length[i])
        enforce(0 <= o and L >= 0 and o + L <= b - a,
                f"sequence_slice out of range for sequence {i}: offset {o} "
                f"+ length {L} > sequence length {b - a}",
                InvalidArgumentError)
        seqs.append(d[a + o:a + o + L])
    return LoDTensor.from_sequences(seqs)


def sequence_reshape(x: LoDTensor, new_dim: int) -> LoDTensor:
    """sequence_reshape_op: re-chunk each sequence's flattened payload into
    rows of new_dim."""
    from ..core.errors import InvalidArgumentError, enforce
    d = np.asarray(x.data)
    last = x.lod[-1]
    seqs = []
    for a, b in zip(last, last[1:]):
        seg = d[a:b].reshape(-1)
        enforce(seg.size % new_dim == 0,
                "sequence payload not divisible by new_dim",
                InvalidArgumentError)
        seqs.append(seg.reshape(-1, new_dim))
    return LoDTensor.from_sequences(seqs)


def sequence_scatter(x, index: LoDTensor, updates: LoDTensor):
    """sequence_scatter_op: add each sequence's updates into row i of x at
    the given column indices."""
    from ..core.errors import InvalidArgumentError, enforce
    out = np.asarray(_t(x).data).copy()
    idx = np.asarray(index.data).reshape(-1)
    upd = np.asarray(updates.data).reshape(-1)
    last = index.lod[-1]
    enforce(len(last) - 1 == out.shape[0],
            f"sequence_scatter: index holds {len(last) - 1} sequences but "
            f"x has {out.shape[0]} rows", InvalidArgumentError)
    enforce(idx.shape == upd.shape,
            f"sequence_scatter: index payload {idx.shape} != updates "
            f"payload {upd.shape}", InvalidArgumentError)
    enforce(index.lod[-1] == updates.lod[-1],
            "sequence_scatter: index and updates must share the same lod "
            f"({index.lod[-1]} vs {updates.lod[-1]})", InvalidArgumentError)
    enforce(len(idx) == 0 or (idx.min() >= 0
                              and idx.max() < out.shape[1]),
            "sequence_scatter: column index out of range",
            InvalidArgumentError)
    for i, (a, b) in enumerate(zip(last, last[1:])):
        np.add.at(out[i], idx[a:b].astype(np.int64), upd[a:b])
    from .creation import to_tensor
    return to_tensor(out)


def sequence_conv(x: LoDTensor, filter, context_length: int,
                  context_start=None, bias=None):
    """sequence_conv_op.cc (+ math/context_project.h): per sequence, slide
    a context window of context_length frames, concatenate the window
    feature-wise (zeros outside the sequence) and project by
    filter [context_length*D, O]. Returns a LoDTensor with x's lod."""
    if context_start is None:
        context_start = -(context_length // 2)
    d = np.asarray(x.data, np.float32)
    w = np.asarray(filter.data if hasattr(filter, "data") else filter,
                   np.float32)
    b = None if bias is None else np.asarray(
        bias.data if hasattr(bias, "data") else bias, np.float32)
    D = d.shape[1]
    last = x.lod[-1]
    rows = []
    for a, e in zip(last, last[1:]):
        seg = d[a:e]
        T = len(seg)
        ctx = np.zeros((T, context_length * D), np.float32)
        for t in range(T):
            for k in range(context_length):
                src = t + context_start + k
                if 0 <= src < T:
                    ctx[t, k * D:(k + 1) * D] = seg[src]
        rows.append(ctx)
    out = (np.concatenate(rows, axis=0) if rows
           else np.zeros((0, context_length * D), np.float32)) @ w
    if b is not None:
        out = out + b
    return LoDTensor(out, [list(last)])


def sequence_topk_avg_pooling(x: LoDTensor, row_lod, col_lod, topks,
                              channel_num: int):
    """sequence_topk_avg_pooling_op.cc: x packs per-pair score matrices of
    channel_num channels ([rows_i * channel_num, cols_i] blocks, the
    match_matrix_tensor layout). For each row position and channel, sum
    the top-k column scores and divide by k (the kernel divides by the
    FULL k even when fewer columns exist, sequence_topk_avg_pooling_op.h:
    164). Output layout is channel-major — per row, channel c occupies the
    contiguous len(topks) columns [c*k_num, (c+1)*k_num) (op.h:147).
    Returns [total_rows, channel_num * len(topks)]."""
    d = np.asarray(x.data, np.float32)
    k_num = len(topks)
    outs = []
    for (ra, rb), (ca, cb) in zip(zip(row_lod, row_lod[1:]),
                                  zip(col_lod, col_lod[1:])):
        n_row, n_col = rb - ra, cb - ca
        block = d[ra * channel_num: rb * channel_num, :n_col]
        block = block.reshape(channel_num, n_row, n_col)
        feats = np.zeros((n_row, channel_num * k_num), np.float32)
        srt = -np.sort(-block, axis=2)  # descending per row
        for ki, k in enumerate(topks):
            kk = min(k, n_col)
            s = srt[:, :, :kk].sum(axis=2) if kk else \
                np.zeros((channel_num, n_row), np.float32)
            feats[:, ki::k_num] = (s / float(k)).T
        outs.append(feats)
    out = (np.concatenate(outs, axis=0) if outs
           else np.zeros((0, channel_num * len(topks)), np.float32))
    from .creation import to_tensor
    return to_tensor(out)
