"""Elementwise & reduction math ops (reference: python/paddle/tensor/math.py).

Every function routes through core.tensor.apply so eager autograd records it; under
jit tracing the same code path runs on tracers with the tape disabled.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core import dtypes
from ..core.tensor import Tensor, apply
from .creation import _t


def _binary(fn):
    def op(x, y, name=None):
        return apply(fn, _t(x), _t(y))
    return op


def _unary(fn):
    def op(x, name=None):
        return apply(fn, _t(x))
    return op


add = _binary(jnp.add)
subtract = _binary(jnp.subtract)
multiply = _binary(jnp.multiply)
divide = _binary(jnp.divide)
floor_divide = _binary(lambda a, b: jnp.floor_divide(a, b))
mod = _binary(jnp.mod)
remainder = mod
floor_mod = mod
pow = _binary(jnp.power)
maximum = _binary(jnp.maximum)
minimum = _binary(jnp.minimum)
fmax = _binary(jnp.fmax)
fmin = _binary(jnp.fmin)
_atan2_impl = _binary(jnp.arctan2)


def atan2(y, x, name=None):
    """paddle.atan2(y, x): quadrant-aware arctan(y/x) — the reference
    names the FIRST operand y (math.py:2502), so keyword callers pass
    y=..., x=..."""
    return _atan2_impl(y, x)
hypot = _binary(jnp.hypot)

exp = _unary(jnp.exp)
expm1 = _unary(jnp.expm1)
log = _unary(jnp.log)
log2 = _unary(jnp.log2)
log10 = _unary(jnp.log10)
log1p = _unary(jnp.log1p)
sqrt = _unary(jnp.sqrt)
rsqrt = _unary(lambda a: jax.lax.rsqrt(a))
square = _unary(jnp.square)
abs = _unary(jnp.abs)
sign = _unary(jnp.sign)
floor = _unary(jnp.floor)
ceil = _unary(jnp.ceil)
round = _unary(jnp.round)
_trunc_impl = _unary(jnp.trunc)


def trunc(input, name=None):
    """paddle.trunc(input): the reference names the operand `input`
    (math.py trunc), unlike the x-named unary family."""
    return _trunc_impl(input)
sin = _unary(jnp.sin)
cos = _unary(jnp.cos)
tan = _unary(jnp.tan)
asin = _unary(jnp.arcsin)
acos = _unary(jnp.arccos)
atan = _unary(jnp.arctan)
sinh = _unary(jnp.sinh)
cosh = _unary(jnp.cosh)
tanh = _unary(jnp.tanh)
asinh = _unary(jnp.arcsinh)
acosh = _unary(jnp.arccosh)
atanh = _unary(jnp.arctanh)
reciprocal = _unary(lambda a: 1.0 / a)
neg = _unary(jnp.negative)
erf = _unary(jax.scipy.special.erf)
erfinv = _unary(jax.scipy.special.erfinv)
lgamma = _unary(jax.scipy.special.gammaln)
digamma = _unary(jax.scipy.special.digamma)
sigmoid = _unary(jax.nn.sigmoid)
logit = _unary(lambda a: jnp.log(a / (1 - a)))
frac = _unary(lambda a: a - jnp.trunc(a))
angle = _unary(jnp.angle)
conj = _unary(jnp.conj)
real = _unary(jnp.real)
imag = _unary(jnp.imag)
isnan = _unary(jnp.isnan)
isinf = _unary(jnp.isinf)
isfinite = _unary(jnp.isfinite)


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    x = _t(x)
    if bias_after_scale:
        out = apply(lambda a: a * scale + bias, x)
    else:
        out = apply(lambda a: (a + bias) * scale, x)
    if act:
        from ..nn import functional as F
        out = getattr(F, act)(out)
    return out


def increment(x, value=1.0):
    x.set_value(x.data + value)
    return x


def clip(x, min=None, max=None, name=None):
    x = _t(x)
    lo = min.item() if isinstance(min, Tensor) else min
    hi = max.item() if isinstance(max, Tensor) else max
    return apply(lambda a: jnp.clip(a, lo, hi), x)


def lerp(x, y, weight, name=None):
    w = weight.data if isinstance(weight, Tensor) else weight
    return apply(lambda a, b: a + w * (b - a), _t(x), _t(y))


def stanh(x, scale_a=0.67, scale_b=1.7159, name=None):
    return apply(lambda a: scale_b * jnp.tanh(scale_a * a), _t(x))


def multiplex(inputs, index, name=None):
    idx = _t(index)
    ins = [_t(i) for i in inputs]
    return apply(
        lambda i, *xs: jnp.stack(xs, 0)[i.reshape(-1), jnp.arange(xs[0].shape[0])],
        idx, *ins)


# ---- reductions ----

def _axis(axis):
    if axis is None:
        return None
    if isinstance(axis, Tensor):
        axis = axis.tolist()
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return int(axis)


def sum(x, axis=None, dtype=None, keepdim=False, name=None):
    d = dtypes.convert_dtype(dtype) if dtype else None
    return apply(lambda a: jnp.sum(a, axis=_axis(axis), dtype=d, keepdims=keepdim),
                 _t(x))


def mean(x, axis=None, keepdim=False, name=None):
    return apply(lambda a: jnp.mean(a, axis=_axis(axis), keepdims=keepdim), _t(x))


def max(x, axis=None, keepdim=False, name=None):
    return apply(lambda a: jnp.max(a, axis=_axis(axis), keepdims=keepdim), _t(x))


def min(x, axis=None, keepdim=False, name=None):
    return apply(lambda a: jnp.min(a, axis=_axis(axis), keepdims=keepdim), _t(x))


def prod(x, axis=None, keepdim=False, dtype=None, name=None):
    d = dtypes.convert_dtype(dtype) if dtype else None
    return apply(lambda a: jnp.prod(a, axis=_axis(axis), dtype=d, keepdims=keepdim),
                 _t(x))


def amax(x, axis=None, keepdim=False, name=None):
    return max(x, axis, keepdim)


def amin(x, axis=None, keepdim=False, name=None):
    return min(x, axis, keepdim)


def logsumexp(x, axis=None, keepdim=False, name=None):
    return apply(lambda a: jax.scipy.special.logsumexp(
        a, axis=_axis(axis), keepdims=keepdim), _t(x))


def all(x, axis=None, keepdim=False, name=None):
    return apply(lambda a: jnp.all(a, axis=_axis(axis), keepdims=keepdim), _t(x))


def any(x, axis=None, keepdim=False, name=None):
    return apply(lambda a: jnp.any(a, axis=_axis(axis), keepdims=keepdim), _t(x))


def cumsum(x, axis=None, dtype=None, name=None):
    d = dtypes.convert_dtype(dtype) if dtype else None

    def f(a):
        if axis is None:
            return jnp.cumsum(a.reshape(-1), dtype=d)
        return jnp.cumsum(a, axis=int(axis), dtype=d)

    return apply(f, _t(x))


def cumprod(x, dim=None, dtype=None, name=None):
    d = dtypes.convert_dtype(dtype) if dtype else None
    return apply(lambda a: jnp.cumprod(a, axis=dim, dtype=d), _t(x))


def _cum_extreme(x, axis, dtype, cum, eq_first):
    def f(a):
        src = a.reshape(-1) if axis is None else a
        ax = 0 if axis is None else int(axis)
        vals = cum(src, axis=ax)
        shape = [1] * src.ndim
        shape[ax] = src.shape[ax]
        pos = jnp.arange(src.shape[ax]).reshape(shape)
        mark = jnp.where(src == vals, pos, -1)
        ind = jax.lax.cummax(mark, axis=ax)
        return vals, ind.astype(dtypes.convert_dtype(dtype))

    return apply(f, _t(x))


def cummax(x, axis=None, dtype="int64", name=None):
    return _cum_extreme(x, axis, dtype, jax.lax.cummax, True)


def cummin(x, axis=None, dtype="int64", name=None):
    return _cum_extreme(x, axis, dtype, jax.lax.cummin, True)


def nanmean(x, axis=None, keepdim=False, name=None):
    return apply(lambda a: jnp.nanmean(a, axis=_axis(axis), keepdims=keepdim), _t(x))


def nansum(x, axis=None, dtype=None, keepdim=False, name=None):
    d = dtypes.convert_dtype(dtype) if dtype else None
    return apply(lambda a: jnp.nansum(a, axis=_axis(axis), dtype=d,
                                      keepdims=keepdim), _t(x))


def count_nonzero(x, axis=None, keepdim=False, name=None):
    return apply(lambda a: jnp.count_nonzero(a, axis=_axis(axis),
                                             keepdims=keepdim), _t(x))


def trace(x, offset=0, axis1=0, axis2=1, name=None):
    return apply(lambda a: jnp.trace(a, offset=offset, axis1=axis1, axis2=axis2),
                 _t(x))


def diff(x, n=1, axis=-1, prepend=None, append=None, name=None):
    args = [_t(x)]
    has_pre = prepend is not None
    has_app = append is not None
    if has_pre:
        args.append(_t(prepend))
    if has_app:
        args.append(_t(append))

    def f(a, *extra):
        kw = {}
        i = 0
        if has_pre:
            kw["prepend"] = extra[i]
            i += 1
        if has_app:
            kw["append"] = extra[i]
        return jnp.diff(a, n=n, axis=axis, **kw)

    return apply(f, *args)


def kron(x, y, name=None):
    return apply(jnp.kron, _t(x), _t(y))


def inner(x, y, name=None):
    return apply(jnp.inner, _t(x), _t(y))


def outer(x, y, name=None):
    return apply(lambda a, b: jnp.outer(a, b), _t(x), _t(y))


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    return apply(lambda i, a, b: beta * i + alpha * (a @ b),
                 _t(input), _t(x), _t(y))


def gcd(x, y, name=None):
    return apply(jnp.gcd, _t(x), _t(y))


def lcm(x, y, name=None):
    return apply(jnp.lcm, _t(x), _t(y))


def broadcast_shape(x_shape, y_shape):
    return list(jnp.broadcast_shapes(tuple(x_shape), tuple(y_shape)))


def add_n(inputs, name=None):
    """paddle.add_n (sum_op.cc): elementwise sum of a tensor list."""
    if not isinstance(inputs, (list, tuple)):
        inputs = [inputs]
    ts = [_t(x) for x in inputs]

    def f(*xs):  # NB: `sum` here is this module's reduction, not builtins'
        acc = xs[0]
        for x in xs[1:]:
            acc = acc + x
        return acc

    return apply(f, *ts)


def tanh_(x, name=None):
    """In-place tanh (paddle.tanh_), traced through the tape."""
    from ..core.tensor import _rebind_inplace, inplace_guard
    t = _t(x)
    inplace_guard(t, "tanh_")
    _rebind_inplace(t, apply(jnp.tanh, t))
    return t


def rad2deg(x, name=None):
    return apply(lambda a: a * (180.0 / jnp.pi), _t(x))


def deg2rad(x, name=None):
    return apply(lambda a: a * (jnp.pi / 180.0), _t(x))


def heaviside(x, y, name=None):
    """heaviside_op: 0 for x<0, y for x==0, 1 for x>0."""
    return apply(lambda a, b: jnp.where(
        a < 0, 0.0, jnp.where(a == 0, b, 1.0)).astype(a.dtype),
        _t(x), _t(y))


# ---- in-place mutation ops (reference varbase_patch_methods) ----

def _inplace_binary(op):
    def fn(x, y, name=None):
        from ..core.tensor import _rebind_inplace, inplace_guard
        t = _t(x)
        inplace_guard(t)
        _rebind_inplace(t, op(t, y))
        return t
    return fn


add_ = _inplace_binary(lambda a, b: add(a, b))
subtract_ = _inplace_binary(lambda a, b: subtract(a, b))


def clip_(x, min=None, max=None, name=None):
    from ..core.tensor import _rebind_inplace, inplace_guard
    t = _t(x)
    inplace_guard(t, "clip_")
    _rebind_inplace(t, clip(t, min=min, max=max))
    return t


def _overwrite_inplace(t, fill_fn, opname):
    """fill_/zero_ overwrite the tensor with a constant: on a traced non-leaf
    this must go through the tape (the overwrite BLOCKS upstream gradients,
    like scatter_ overwrite); on leaves/no-grad it is a raw storage write."""
    from ..core.tensor import _rebind_inplace, inplace_guard, is_grad_enabled
    if is_grad_enabled() and not t.stop_gradient:
        inplace_guard(t, opname)
        _rebind_inplace(t, apply(fill_fn, t))
    else:
        t.data = fill_fn(t.data)
    return t


def fill_(x, value):
    t = _t(x)
    return _overwrite_inplace(t, lambda a: jnp.full_like(a, value), "fill_")


def zero_(x):
    t = _t(x)
    return _overwrite_inplace(t, jnp.zeros_like, "zero_")


def clip_by_norm(x, max_norm, name=None):
    """clip_by_norm_op: scale x so its L2 norm is at most max_norm."""
    t = _t(x)

    def f(a):
        n = jnp.sqrt(jnp.sum(jnp.square(a.astype(jnp.float32))))
        scale = jnp.minimum(max_norm / jnp.maximum(n, 1e-12), 1.0)
        return (a.astype(jnp.float32) * scale).astype(a.dtype)

    return apply(f, t)


def nan_to_num(x, nan=0.0, posinf=None, neginf=None, name=None):
    """paddle.nan_to_num (2.x tail; no fluid ancestor): replace NaN/±inf
    with finite values (dtype max/min when posinf/neginf are None)."""
    return apply(lambda a: jnp.nan_to_num(a, nan=nan, posinf=posinf,
                                          neginf=neginf), _t(x))


def logcumsumexp(x, axis=None, dtype=None, name=None):
    """paddle.logcumsumexp: running log(sum(exp)) along axis (flattened
    when axis is None), computed stably via an associative logaddexp scan
    — never materializes exp(x)."""
    import jax

    def f(a):
        if dtype is not None:
            from ..core.dtype import convert_dtype
            a = a.astype(convert_dtype(dtype))
        b = a.reshape(-1) if axis is None else a
        ax = 0 if axis is None else axis
        return jax.lax.associative_scan(jnp.logaddexp, b, axis=ax)

    return apply(f, _t(x))


def trapezoid(y, x=None, dx=None, axis=-1, name=None):
    """paddle.trapezoid: trapezoidal-rule integral along axis (numpy.trapz
    semantics; spacing from x, dx, or 1.0)."""
    if x is not None and dx is not None:
        raise ValueError(
            "trapezoid accepts x or dx, not both (conflicting spacings)")
    args = [_t(y)] + ([_t(x)] if x is not None else [])

    def f(yv, *maybe_x):
        yv = yv.astype(jnp.float32)
        n = yv.shape[axis]
        y0 = jnp.take(yv, jnp.arange(n - 1), axis=axis)
        y1 = jnp.take(yv, jnp.arange(1, n), axis=axis)
        if maybe_x:
            xv = maybe_x[0].astype(jnp.float32)
            if xv.ndim == 1:
                shape = [1] * yv.ndim
                shape[axis] = n
                xv = xv.reshape(shape)
            d = jnp.take(xv, jnp.arange(1, n), axis=axis) - \
                jnp.take(xv, jnp.arange(n - 1), axis=axis)
        else:
            d = dx if dx is not None else 1.0
        return jnp.sum((y0 + y1) * 0.5 * d, axis=axis)

    return apply(f, *args)


def renorm(x, p, axis, max_norm, name=None):
    """paddle.renorm: every slice along `axis` whose p-norm exceeds
    max_norm is rescaled to have p-norm exactly max_norm."""
    def f(a):
        af = a.astype(jnp.float32)
        ax = axis % a.ndim  # negative axis must still exclude its dim
        reduce_axes = tuple(i for i in range(a.ndim) if i != ax)
        if p == float("inf"):
            norms = jnp.max(jnp.abs(af), axis=reduce_axes, keepdims=True)
        else:
            norms = jnp.power(
                jnp.sum(jnp.power(jnp.abs(af), p), axis=reduce_axes,
                        keepdims=True), 1.0 / p)
        scale = jnp.where(norms > max_norm,
                          max_norm / jnp.maximum(norms, 1e-12), 1.0)
        return (af * scale).astype(a.dtype)

    return apply(f, _t(x))


def _inplace_unary(x, fn, opname):
    """Shared body of the 2.x in-place unary variants (exp_/sqrt_/...):
    one tape-rebind protocol (manipulation._inplace_via_tape) for all
    in-place ops, so the semantics live in one place."""
    from .manipulation import _inplace_via_tape
    t = _t(x)
    return _inplace_via_tape(t, fn(t), opname)


def exp_(x, name=None):
    return _inplace_unary(x, exp, "exp_")


def sqrt_(x, name=None):
    return _inplace_unary(x, sqrt, "sqrt_")


def rsqrt_(x, name=None):
    return _inplace_unary(x, rsqrt, "rsqrt_")


def ceil_(x, name=None):
    return _inplace_unary(x, ceil, "ceil_")


def floor_(x, name=None):
    return _inplace_unary(x, floor, "floor_")


def round_(x, name=None):
    return _inplace_unary(x, round, "round_")


def reciprocal_(x, name=None):
    return _inplace_unary(x, reciprocal, "reciprocal_")


_scale_fn = scale


def scale_(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None,
           name=None):
    def fn(t):
        out = _scale_fn(t, scale, bias, bias_after_scale)
        if act is not None:  # legacy fused-activation arg (scale_ op attr)
            from ..nn import functional as F
            out = getattr(F, act)(out)
        return out

    return _inplace_unary(x, fn, "scale_")
