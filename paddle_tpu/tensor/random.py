"""Random sampling ops (reference: python/paddle/tensor/random.py).

All draws derive from core.random.next_key() so paddle_tpu.seed() makes runs
reproducible and the TP RNGStatesTracker controls per-axis streams.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core import dtypes
from ..core.random import next_key
from ..core.tensor import Tensor
from .creation import _shape, _t


def _d(dtype):
    return (dtypes.convert_dtype(dtype) if dtype is not None
            else dtypes.get_default_dtype())


def uniform(shape, dtype=None, min=-1.0, max=1.0, seed=0, name=None) -> Tensor:
    key = jax.random.PRNGKey(seed) if seed else next_key()
    return Tensor(jax.random.uniform(key, _shape(shape), _d(dtype),
                                     minval=min, maxval=max))


def rand(shape, dtype=None, name=None) -> Tensor:
    return uniform(shape, dtype, 0.0, 1.0)


def normal(mean=0.0, std=1.0, shape=None, name=None) -> Tensor:
    if isinstance(mean, Tensor) or isinstance(std, Tensor):
        m = _t(mean).data if isinstance(mean, Tensor) else mean
        s = _t(std).data if isinstance(std, Tensor) else std
        shp = jnp.broadcast_shapes(
            getattr(m, "shape", ()), getattr(s, "shape", ()))
        return Tensor(m + s * jax.random.normal(next_key(), shp))
    return Tensor(mean + std * jax.random.normal(
        next_key(), _shape(shape), dtypes.get_default_dtype()))


def randn(shape, dtype=None, name=None) -> Tensor:
    return Tensor(jax.random.normal(next_key(), _shape(shape), _d(dtype)))


def standard_normal(shape, dtype=None, name=None) -> Tensor:
    return randn(shape, dtype)


def randint(low=0, high=None, shape=(1,), dtype="int64", name=None) -> Tensor:
    if high is None:
        low, high = 0, low
    return Tensor(jax.random.randint(next_key(), _shape(shape), low, high,
                                     dtype=dtypes.convert_dtype(dtype)))


def randint_like(x, low=0, high=None, dtype=None, name=None) -> Tensor:
    x = _t(x)
    return randint(low, high, x.shape, dtype or x.dtype)


def randperm(n, dtype="int64", name=None) -> Tensor:
    return Tensor(jax.random.permutation(next_key(), n).astype(
        dtypes.convert_dtype(dtype)))


def multinomial(x, num_samples=1, replacement=False, name=None) -> Tensor:
    x = _t(x)

    logits = jnp.log(jnp.maximum(x.data, 1e-30))
    if replacement:
        out = jax.random.categorical(next_key(), logits, axis=-1,
                                     shape=(num_samples,) + x.data.shape[:-1])
        if x.data.ndim == 2:
            out = jnp.moveaxis(out, 0, -1)
        return Tensor(out.astype(jnp.int64))
    # without replacement: Gumbel top-k
    g = jax.random.gumbel(next_key(), x.data.shape)
    _, idx = jax.lax.top_k(logits + g, num_samples)
    return Tensor(idx.astype(jnp.int64))


def bernoulli(x, name=None) -> Tensor:
    x = _t(x)
    return Tensor(jax.random.bernoulli(next_key(), x.data).astype(x.data.dtype))


def poisson(x, name=None) -> Tensor:
    x = _t(x)
    return Tensor(jax.random.poisson(next_key(), x.data).astype(x.data.dtype))


def exponential_(x, lam=1.0, name=None) -> Tensor:
    x = _t(x)
    x.data = jax.random.exponential(next_key(), x.data.shape,
                                    x.data.dtype) / lam
    return x


def uniform_(x, min=-1.0, max=1.0, name=None) -> Tensor:
    x.data = jax.random.uniform(next_key(), x.data.shape, x.data.dtype,
                                minval=min, maxval=max)
    return x


def normal_(x, mean=0.0, std=1.0, name=None) -> Tensor:
    x.data = mean + std * jax.random.normal(next_key(), x.data.shape,
                                            x.data.dtype)
    return x
