"""Linear algebra ops (reference: python/paddle/tensor/linalg.py).

matmul is THE op on TPU: it maps to the MXU. Keep operands batched and let XLA tile;
no hand-written GEMM here.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.amp import autocast_inputs
from ..core.tensor import Tensor, apply
from .creation import _t


def matmul(x, y, transpose_x=False, transpose_y=False, name=None):
    x, y = _t(x), _t(y)

    def f(a, b):
        a, b = autocast_inputs("matmul", a, b)
        if transpose_x:
            a = jnp.swapaxes(a, -1, -2) if a.ndim > 1 else a
        if transpose_y:
            b = jnp.swapaxes(b, -1, -2) if b.ndim > 1 else b
        return jnp.matmul(a, b)

    return apply(f, x, y)


def mm(input, mat2, name=None):
    return matmul(input, mat2)


def bmm(x, y, name=None):
    return matmul(x, y)


def dot(x, y, name=None):
    return apply(lambda a, b: jnp.sum(a * b, axis=-1), _t(x), _t(y))


def t(input, name=None):
    return apply(lambda a: jnp.swapaxes(a, -1, -2) if a.ndim >= 2 else a, _t(input))


def cross(x, y, axis=9, name=None):
    ax = axis if axis != 9 else None

    def f(a, b):
        if ax is None:
            use = next(i for i, s in enumerate(a.shape) if s == 3)
        else:
            use = ax
        return jnp.cross(a, b, axis=use)

    return apply(f, _t(x), _t(y))


def norm(x, p="fro", axis=None, keepdim=False, name=None):
    x = _t(x)

    def f(a):
        if p == "fro" and axis is None:
            return jnp.sqrt(jnp.sum(a * a))
        if axis is None:
            flat = a.reshape(-1)
            return jnp.linalg.norm(flat, ord=p)
        if isinstance(axis, (list, tuple)):
            return jnp.linalg.norm(a, ord=p if p != "fro" else "fro",
                                   axis=tuple(axis), keepdims=keepdim)
        if p == "fro":
            return jnp.sqrt(jnp.sum(a * a, axis=axis, keepdims=keepdim))
        if p == float("inf"):
            return jnp.max(jnp.abs(a), axis=axis, keepdims=keepdim)
        if p == float("-inf"):
            return jnp.min(jnp.abs(a), axis=axis, keepdims=keepdim)
        if p == 0:
            return jnp.sum((a != 0).astype(a.dtype), axis=axis, keepdims=keepdim)
        return jnp.sum(jnp.abs(a) ** p, axis=axis, keepdims=keepdim) ** (1.0 / p)

    return apply(f, x)


def dist(x, y, p=2, name=None):
    return norm(apply(jnp.subtract, _t(x), _t(y)), p=float(p))


def cond(x, p=None, name=None):
    return apply(lambda a: jnp.linalg.cond(a, p=p), _t(x))


def det(x, name=None):
    return apply(jnp.linalg.det, _t(x))


def slogdet(x, name=None):
    def f(a):
        sign, logabs = jnp.linalg.slogdet(a)
        return jnp.stack([sign, logabs])

    return apply(f, _t(x))


def inv(x, name=None):
    return apply(jnp.linalg.inv, _t(x))


def pinv(x, rcond=1e-15, hermitian=False, name=None):
    return apply(lambda a: jnp.linalg.pinv(a, rtol=rcond, hermitian=hermitian), _t(x))


def solve(x, y, name=None):
    return apply(jnp.linalg.solve, _t(x), _t(y))


def triangular_solve(x, y, upper=True, transpose=False, unitriangular=False,
                     name=None):
    return apply(
        lambda a, b: jax.scipy.linalg.solve_triangular(
            a, b, lower=not upper, trans=1 if transpose else 0,
            unit_diagonal=unitriangular),
        _t(x), _t(y))


def cholesky(x, upper=False, name=None):
    def f(a):
        L = jnp.linalg.cholesky(a)
        return jnp.swapaxes(L, -1, -2) if upper else L

    return apply(f, _t(x))


def cholesky_solve(x, y, upper=False, name=None):
    return apply(lambda b, L: jax.scipy.linalg.cho_solve((L, not upper), b),
                 _t(x), _t(y))


def lu(x, pivot=True, get_infos=False, name=None):
    def f(a):
        lu_, piv = jax.scipy.linalg.lu_factor(a)
        return lu_, piv.astype(jnp.int32) + 1  # paddle pivots are 1-based

    out = apply(f, _t(x))
    if get_infos:
        from .creation import zeros
        return out[0], out[1], zeros([1], dtype="int32")
    return out


def qr(x, mode="reduced", name=None):
    def f(a):
        return tuple(jnp.linalg.qr(a, mode=mode))

    return apply(f, _t(x))


def svd(x, full_matrices=False, name=None):
    """paddle.linalg.svd convention: returns (U, S, VH) with VH the
    transpose of V, shape [..., K, N] — so x == U @ diag(S) @ VH (the
    reference snapshot predates linalg.svd; the 2.x public contract is
    the anchor)."""
    def f(a):
        return jnp.linalg.svd(a, full_matrices=full_matrices)

    return apply(f, _t(x))


def eig(x, name=None):
    def f(a):
        return tuple(jnp.linalg.eig(a))

    return apply(f, _t(x))


def eigh(x, UPLO="L", name=None):
    def f(a):
        w, v = jnp.linalg.eigh(a, UPLO=UPLO)
        return w, v

    return apply(f, _t(x))


def eigvals(x, name=None):
    return apply(jnp.linalg.eigvals, _t(x))


def eigvalsh(x, UPLO="L", name=None):
    return apply(lambda a: jnp.linalg.eigvalsh(a, UPLO=UPLO), _t(x))


def matrix_power(x, n, name=None):
    return apply(lambda a: jnp.linalg.matrix_power(a, n), _t(x))


def matrix_rank(x, tol=None, hermitian=False, name=None):
    return apply(lambda a: jnp.linalg.matrix_rank(a), _t(x))


def multi_dot(x, name=None):
    tensors = [_t(t) for t in x]
    return apply(lambda *xs: jnp.linalg.multi_dot(xs), *tensors)


def histogram(input, bins=100, min=0, max=0, name=None):
    def f(a):
        lo, hi = (min, max) if (min != 0 or max != 0) else (a.min(), a.max())
        h, _ = jnp.histogram(a, bins=bins, range=(lo, hi))
        return h.astype(jnp.int64)

    return apply(f, _t(input))


def bincount(x, weights=None, minlength=0, name=None):
    x = _t(x)
    # jnp.bincount IGNORES minlength once `length` is passed (the static-
    # shape form) — fold it into length so minlength really pads
    length = max(int(x.numpy().max()) + 1 if x.size else 0, int(minlength))
    if weights is None:
        return apply(lambda a: jnp.bincount(a, length=length), x)
    return apply(lambda a, w: jnp.bincount(a, w, length=length),
                 x, _t(weights))


def corrcoef(x, rowvar=True, name=None):
    return apply(lambda a: jnp.corrcoef(a, rowvar=rowvar), _t(x))


def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None, name=None):
    return apply(lambda a: jnp.cov(a, rowvar=rowvar, ddof=1 if ddof else 0), _t(x))


def mv(x, vec, name=None):
    """Matrix-vector product (mv_op.cc)."""
    return apply(lambda a, b: a @ b, _t(x), _t(vec))


def inverse(x, name=None):
    """paddle.inverse alias of linalg.inv (inverse_op.cc)."""
    return inv(x)


def tensordot(x, y, axes=2, name=None):
    """paddle.tensordot (tensordot semantics over jnp)."""
    import numpy as _np

    def norm_axes(ax):
        if isinstance(ax, Tensor):
            ax = _np.asarray(ax.data).tolist()
        return ax

    return apply(lambda a, b: jnp.tensordot(a, b, axes=norm_axes(axes)),
                 _t(x), _t(y))
