"""Shape/layout manipulation ops (reference: python/paddle/tensor/manipulation.py)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core import dtypes
from ..core.tensor import Tensor, apply
from .creation import _t

_py_slice = slice  # `slice` is shadowed by the paddle-named op below


def _static_shape(shape):
    if isinstance(shape, Tensor):
        return tuple(int(s) for s in shape.numpy())
    return tuple(int(s.item()) if isinstance(s, Tensor) else int(s) for s in shape)


def reshape(x, shape, name=None):
    s = _static_shape(shape)
    return apply(lambda a: jnp.reshape(a, s), _t(x))


def reshape_(x, shape, name=None):
    return _inplace_via_tape(_t(x), reshape(x, shape))


def flatten(x, start_axis=0, stop_axis=-1, name=None):
    x = _t(x)

    def f(a):
        nd = a.ndim
        s0 = start_axis % nd
        s1 = stop_axis % nd
        new_shape = a.shape[:s0] + (-1,) + a.shape[s1 + 1:]
        return jnp.reshape(a, new_shape)

    return apply(f, x)


def transpose(x, perm, name=None):
    return apply(lambda a: jnp.transpose(a, tuple(perm)), _t(x))


def moveaxis(x, source, destination, name=None):
    return apply(lambda a: jnp.moveaxis(a, source, destination), _t(x))


def swapaxes(x, axis1, axis2, name=None):
    return apply(lambda a: jnp.swapaxes(a, axis1, axis2), _t(x))


def concat(x, axis=0, name=None):
    tensors = [_t(t) for t in x]
    if isinstance(axis, Tensor):
        axis = int(axis.item())
    return apply(lambda *xs: jnp.concatenate(xs, axis=axis), *tensors)


def stack(x, axis=0, name=None):
    tensors = [_t(t) for t in x]
    return apply(lambda *xs: jnp.stack(xs, axis=axis), *tensors)


def unstack(x, axis=0, num=None):
    x = _t(x)
    n = num or x.shape[axis]
    outs = apply(lambda a: tuple(jnp.moveaxis(a, axis, 0)[i] for i in range(n)), x)
    return list(outs) if isinstance(outs, tuple) else [outs]


def split(x, num_or_sections, axis=0, name=None):
    x = _t(x)
    if isinstance(axis, Tensor):
        axis = int(axis.item())
    dim = x.shape[axis]
    if isinstance(num_or_sections, int):
        if dim % num_or_sections != 0:
            raise ValueError(
                f"split: dimension {axis} (size {dim}) is not divisible by "
                f"num_or_sections={num_or_sections}; pass explicit section "
                "sizes instead")
        sizes = [dim // num_or_sections] * num_or_sections
    else:
        sizes = [int(s) for s in num_or_sections]
        n_unknown = builtins_sum(1 for s in sizes if s < 0)
        if n_unknown:
            known = builtins_sum(s for s in sizes if s >= 0)
            sizes = [s if s >= 0 else dim - known for s in sizes]
    offsets = np.cumsum([0] + sizes)

    def f(a):
        return tuple(
            jax.lax.slice_in_dim(a, int(offsets[i]), int(offsets[i + 1]), axis=axis)
            for i in range(len(sizes)))

    outs = apply(f, x)
    return list(outs) if isinstance(outs, tuple) else [outs]


def builtins_sum(it):
    total = 0
    for v in it:
        total += v
    return total


def chunk(x, chunks, axis=0, name=None):
    return split(x, chunks, axis)


def squeeze(x, axis=None, name=None):
    x = _t(x)

    def f(a):
        if axis is None:
            return jnp.squeeze(a)
        axes = (axis,) if isinstance(axis, int) else tuple(axis)
        axes = tuple(ax % a.ndim for ax in axes)
        axes = tuple(ax for ax in axes if a.shape[ax] == 1)
        return jnp.squeeze(a, axis=axes) if axes else a

    return apply(f, x)


def unsqueeze(x, axis, name=None):
    x = _t(x)
    axes = (axis,) if isinstance(axis, int) else tuple(
        int(a.item()) if isinstance(a, Tensor) else int(a) for a in axis)
    return apply(lambda a: jnp.expand_dims(a, axes), x)


def expand(x, shape, name=None):
    s = _static_shape(shape)
    x = _t(x)

    def f(a):
        tgt = list(s)
        # -1 means keep original dim (paddle semantics)
        off = len(tgt) - a.ndim
        for i in range(len(tgt)):
            if tgt[i] == -1:
                tgt[i] = a.shape[i - off]
        return jnp.broadcast_to(a, tuple(tgt))

    return apply(f, x)


def broadcast_to(x, shape, name=None):
    return apply(lambda a: jnp.broadcast_to(a, _static_shape(shape)), _t(x))


def expand_as(x, y, name=None):
    return broadcast_to(x, _t(y).shape)


def broadcast_tensors(input, name=None):
    tensors = [_t(t) for t in input]
    outs = apply(lambda *xs: tuple(jnp.broadcast_arrays(*xs)), *tensors)
    return list(outs)


def tile(x, repeat_times, name=None):
    reps = _static_shape(repeat_times)
    return apply(lambda a: jnp.tile(a, reps), _t(x))


def roll(x, shifts, axis=None, name=None):
    return apply(lambda a: jnp.roll(a, shifts, axis=axis), _t(x))


def flip(x, axis, name=None):
    return apply(lambda a: jnp.flip(a, axis=axis), _t(x))


def rot90(x, k=1, axes=(0, 1), name=None):
    return apply(lambda a: jnp.rot90(a, k=k, axes=tuple(axes)), _t(x))


def gather(x, index, axis=0, name=None):
    x, index = _t(x), _t(index)
    if isinstance(axis, Tensor):
        axis = int(axis.item())
    return apply(lambda a, i: jnp.take(a, i.astype(jnp.int32), axis=axis), x, index)


def gather_nd(x, index, name=None):
    x, index = _t(x), _t(index)

    def f(a, i):
        idx = tuple(jnp.moveaxis(i, -1, 0))
        return a[idx]

    return apply(f, x, index)


def scatter(x, index, updates, overwrite=True, name=None):
    x, index, updates = _t(x), _t(index), _t(updates)

    def f(a, i, u):
        i = i.reshape(-1).astype(jnp.int32)
        if overwrite:
            return a.at[i].set(u)
        # paddle !overwrite: zero the rows then accumulate
        zeroed = a.at[i].set(jnp.zeros_like(u))
        return zeroed.at[i].add(u)

    return apply(f, x, index, updates)


def scatter_nd_add(x, index, updates, name=None):
    x, index, updates = _t(x), _t(index), _t(updates)

    def f(a, i, u):
        idx = tuple(jnp.moveaxis(i, -1, 0))
        return a.at[idx].add(u)

    return apply(f, x, index, updates)


def scatter_nd(index, updates, shape, name=None):
    from .creation import zeros
    base = zeros(shape, dtype=_t(updates).dtype)
    return scatter_nd_add(base, index, updates)


def put_along_axis(x, indices, values, axis, reduce="assign"):
    x, indices = _t(x), _t(indices)
    values = _t(values)

    def f(a, i, v):
        v = jnp.broadcast_to(v, i.shape).astype(a.dtype)
        if reduce == "assign":
            return jnp.put_along_axis(a, i, v, axis=axis, inplace=False)
        elif reduce == "add":
            dims = [jnp.arange(s) for s in i.shape]
            grid = jnp.meshgrid(*dims, indexing="ij")
            grid[axis] = i
            return a.at[tuple(grid)].add(v)
        else:
            raise ValueError(f"unsupported reduce {reduce}")

    return apply(f, x, indices, values)


def take_along_axis(arr, indices, axis, name=None):
    return apply(lambda a, i: jnp.take_along_axis(a, i, axis=axis),
                 _t(arr), _t(indices))


def index_select(x, index, axis=0, name=None):
    return gather(x, index, axis)


def index_sample(x, index):
    x, index = _t(x), _t(index)
    return apply(
        lambda a, i: jnp.take_along_axis(a, i.astype(jnp.int32), axis=1), x, index)


def slice(x, axes, starts, ends):
    x = _t(x)
    axes = [int(a) for a in axes]
    starts = [int(s.item()) if isinstance(s, Tensor) else int(s) for s in starts]
    ends = [int(e.item()) if isinstance(e, Tensor) else int(e) for e in ends]

    def f(a):
        idx = [_py_slice(None)] * a.ndim
        for ax, st, en in zip(axes, starts, ends):
            idx[ax] = _py_slice(st, en)
        return a[tuple(idx)]

    return apply(f, x)


def strided_slice(x, axes, starts, ends, strides):
    x = _t(x)

    def f(a):
        idx = [_py_slice(None)] * a.ndim
        for ax, st, en, sd in zip(axes, starts, ends, strides):
            idx[int(ax)] = _py_slice(int(st), int(en), int(sd))
        return a[tuple(idx)]

    return apply(f, x)


def crop(x, shape=None, offsets=None, name=None):
    x = _t(x)
    s = _static_shape(shape)
    off = [0] * len(s) if offsets is None else [
        int(o.item()) if isinstance(o, Tensor) else int(o) for o in offsets]
    s = [x.shape[i] if s[i] == -1 else s[i] for i in range(len(s))]
    return apply(lambda a: jax.lax.dynamic_slice(a, off, s), x)


def unique(x, return_index=False, return_inverse=False, return_counts=False,
           axis=None, dtype="int64", name=None):
    # Data-dependent output shape: host round-trip (not jittable), like the
    # reference's CPU fallback for unique.
    arr = np.asarray(_t(x).numpy())
    res = np.unique(arr, return_index=return_index, return_inverse=return_inverse,
                    return_counts=return_counts, axis=axis)
    if not isinstance(res, tuple):
        return Tensor(res)
    out = [Tensor(res[0])]
    d = dtypes.convert_dtype(dtype)
    for extra in res[1:]:
        out.append(Tensor(extra.astype(d)))
    return tuple(out)


def unbind(input, axis=0):
    return unstack(input, axis)


def repeat_interleave(x, repeats, axis=None, name=None):
    r = repeats.data if isinstance(repeats, Tensor) else repeats
    return apply(lambda a: jnp.repeat(a, r, axis=axis), _t(x))


def as_complex(x, name=None):
    return apply(lambda a: jax.lax.complex(a[..., 0], a[..., 1]), _t(x))


def as_real(x, name=None):
    return apply(lambda a: jnp.stack([jnp.real(a), jnp.imag(a)], axis=-1), _t(x))


def view(x, shape_or_dtype, name=None):
    if isinstance(shape_or_dtype, (list, tuple)):
        return reshape(x, shape_or_dtype)
    return apply(lambda a: a.view(dtypes.convert_dtype(shape_or_dtype)), _t(x))


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None):
    x = _t(x)
    if isinstance(pad, Tensor):
        pad = pad.tolist()
    pad = [int(p) for p in pad]

    def f(a):
        nd = a.ndim
        if len(pad) == 2 * nd:
            cfg = [(pad[2 * i], pad[2 * i + 1]) for i in range(nd)]
        else:
            # paddle nn.functional.pad convention: pads innermost dims, reversed
            # pairs; e.g. NCHW with pad=[l,r,t,b] pads W then H.
            n_spatial = len(pad) // 2
            cfg = [(0, 0)] * nd
            if data_format.endswith("C"):  # NHWC / NLC / NDHWC: spatial before C
                spatial_axes = list(range(1, 1 + n_spatial))
            else:
                spatial_axes = list(range(nd - n_spatial, nd))
            for i, ax in enumerate(reversed(spatial_axes)):
                cfg[ax] = (pad[2 * i], pad[2 * i + 1])
        jmode = {"constant": "constant", "reflect": "reflect",
                 "replicate": "edge", "circular": "wrap"}[mode]
        if jmode == "constant":
            return jnp.pad(a, cfg, mode="constant", constant_values=value)
        return jnp.pad(a, cfg, mode=jmode)

    return apply(f, x)


# ---- root-namespace parity fns (reference python/paddle/__init__.py) ----

def cast(x, dtype):
    """paddle.cast (cast_op.cc)."""
    return _t(x).astype(dtype)


def diagonal(x, offset=0, axis1=0, axis2=1, name=None):
    return apply(lambda a: jnp.diagonal(a, offset=offset, axis1=axis1,
                                        axis2=axis2), _t(x))


def numel(x, name=None):
    from .creation import to_tensor
    import numpy as _np
    return to_tensor(_np.asarray(int(_t(x).data.size), _np.int64))


def rank(input, name=None):
    from .creation import to_tensor
    import numpy as _np
    return to_tensor(_np.asarray(int(_t(input).data.ndim), _np.int32))


def shape(input, name=None):
    """paddle.shape: the runtime shape as an int32 tensor (shape_op.cc)."""
    from .creation import to_tensor
    import numpy as _np
    return to_tensor(_np.asarray(_t(input).data.shape, _np.int32))


def _inplace_via_tape(t, out, opname=None):
    """Apply a traced result as an in-place update on `t`."""
    from ..core.tensor import _rebind_inplace, inplace_guard
    inplace_guard(t, opname) if opname else inplace_guard(t)
    _rebind_inplace(t, out)
    return t


def scatter_(x, index, updates, overwrite=True, name=None):
    """In-place scatter (paddle.scatter_): x[index] = / += updates."""
    t = _t(x)
    return _inplace_via_tape(t, scatter(t, index, updates, overwrite=overwrite))


def squeeze_(x, axis=None, name=None):
    t = _t(x)
    return _inplace_via_tape(t, squeeze(t, axis=axis))


def unsqueeze_(x, axis, name=None):
    t = _t(x)
    return _inplace_via_tape(t, unsqueeze(t, axis))


def tolist(x):
    """paddle.tolist (varbase_patch_methods tolist)."""
    return _t(x).tolist()


def shard_index(input, index_num, nshards, shard_id, ignore_value=-1):
    """shard_index_op.cc: map global indices to shard-local ones; indices
    outside this shard become ignore_value (used to build vocab-sharded
    softmax labels)."""
    if shard_id < 0 or shard_id >= nshards:
        raise ValueError(
            f"shard_id {shard_id} out of range [0, {nshards})")
    shard_size = (index_num + nshards - 1) // nshards

    def f(ids):
        lo = shard_id * shard_size
        inside = (ids // shard_size) == shard_id
        return jnp.where(inside, ids - lo, ignore_value)

    return apply(f, _t(input))


def reverse(x, axis, name=None):
    """Pre-2.x alias of flip (reverse_op.cc; kept for fluid parity)."""
    return flip(x, axis)


# ---- LoDTensorArray ops (lod_tensor_array ops + control-flow arrays;
# reference tensor_array_read_write.cc). Dygraph semantics: the array is a
# Python list of Tensors, exactly the reference's dygraph behavior. ----

def create_array(dtype="float32", initialized_list=None):
    arr = list(initialized_list) if initialized_list is not None else []
    for v in arr:
        if not isinstance(v, Tensor):
            raise TypeError(
                "create_array initialized_list must contain Tensors, got "
                f"{type(v).__name__}")
    return arr


def array_write(x, i, array=None):
    """Write x at index i (extending like the reference: writing at
    i == len appends; i > len errors)."""
    idx = int(i.item() if hasattr(i, "item") else i)
    if array is None:
        array = []
    if idx < 0 or idx > len(array):
        raise IndexError(
            f"array_write: index {idx} out of range for array length "
            f"{len(array)} (negative indices are rejected, matching the "
            "reference)")
    if idx == len(array):
        array.append(x)
    else:
        array[idx] = x
    return array


def array_read(array, i):
    idx = int(i.item() if hasattr(i, "item") else i)
    return array[idx]


def array_length(array):
    from .creation import to_tensor
    return to_tensor(np.asarray(len(array), np.int64))


def index_add(x, index, axis, value, name=None):
    """paddle.index_add (2.x tail): out = x with value's rows added at the
    given indices along axis (duplicate indices accumulate)."""
    def f(a, idx, v):
        moved = jnp.moveaxis(a, axis, 0)
        vm = jnp.moveaxis(v.astype(a.dtype), axis, 0)
        out = moved.at[idx.astype(jnp.int32)].add(vm)
        return jnp.moveaxis(out, 0, axis)

    return apply(f, _t(x), _t(index), _t(value))


def index_fill(x, index, axis, value, name=None):
    """paddle.index_fill: out = x with the indexed slices along axis set
    to value."""
    def f(a, idx):
        moved = jnp.moveaxis(a, axis, 0)
        out = moved.at[idx.astype(jnp.int32)].set(
            jnp.asarray(value, a.dtype))
        return jnp.moveaxis(out, 0, axis)

    return apply(f, _t(x), _t(index))


def masked_fill(x, mask, value, name=None):
    """paddle.masked_fill: out = x with value written where the (broadcast)
    boolean mask is True."""
    def f(a, m):
        return jnp.where(m.astype(bool), jnp.asarray(value, a.dtype), a)

    return apply(f, _t(x), _t(mask))


def take(x, index, mode="raise", name=None):
    """paddle.take: gather from the FLATTENED tensor by integer index, with
    'raise'(clips under jit — documented paddle behavior is raise; XLA has
    no data-dependent raise, so out-of-range behaves like 'clip'),
    'wrap' (modulo), or 'clip' semantics. Output keeps index's shape."""
    if mode not in ("raise", "wrap", "clip"):
        raise ValueError(f"take mode must be raise/wrap/clip, got {mode!r}")

    def f(a, idx):
        flat = a.reshape(-1)
        n = flat.shape[0]
        ii = idx.astype(jnp.int64)
        if mode == "wrap":
            ii = ((ii % n) + n) % n
        elif mode == "clip":
            # clip disables negative indexing: clamp straight to [0, n-1]
            ii = jnp.clip(ii, 0, n - 1)
        else:  # raise: negative indices count from the end
            ii = jnp.clip(ii, -n, n - 1)
            ii = jnp.where(ii < 0, ii + n, ii)
        return jnp.take(flat, ii.astype(jnp.int32))

    return apply(f, _t(x), _t(index))


def unique_consecutive(x, return_inverse=False, return_counts=False,
                       axis=None, dtype="int64", name=None):
    """paddle.unique_consecutive: collapse ADJACENT duplicates (host-side
    eager — the output length is data-dependent, like unique)."""
    from .creation import to_tensor
    idx_dtype = dtypes.convert_dtype(dtype)
    a = np.asarray(_t(x).data)
    if axis is None:
        a = a.reshape(-1)
        n = len(a)
        change = np.concatenate([[True], a[1:] != a[:-1]]) if n \
            else np.zeros(0, bool)
    else:
        a = np.moveaxis(a, axis, 0)
        n = a.shape[0]
        flat = a.reshape(n, -1)
        change = np.concatenate(
            [[True], np.any(flat[1:] != flat[:-1], axis=1)]) if n \
            else np.zeros(0, bool)
    starts = np.nonzero(change)[0]
    out = a[starts]
    if axis is not None:
        out = np.moveaxis(out, 0, axis)
    res = [to_tensor(out)]
    if return_inverse:
        inv = np.cumsum(change) - 1
        res.append(to_tensor(inv.astype(idx_dtype)))
    if return_counts:
        counts = np.diff(np.concatenate([starts, [n]]))
        res.append(to_tensor(counts.astype(idx_dtype)))
    return res[0] if len(res) == 1 else tuple(res)


def unflatten(x, axis, shape, name=None):
    """paddle.unflatten: expand one axis into the given shape (one -1
    entry is inferred)."""
    def f(a):
        ax = axis % a.ndim
        shp = list(_static_shape(shape))
        if shp.count(-1) > 1:
            raise ValueError(
                f"unflatten shape can infer at most one -1 entry, got {shp}")
        if -1 in shp:
            known = 1
            for s in shp:
                if s != -1:
                    known *= s
            if known == 0 or a.shape[ax] % known:
                raise ValueError(
                    f"unflatten cannot infer -1: axis size {a.shape[ax]} "
                    f"is not divisible by {known}")
            shp[shp.index(-1)] = a.shape[ax] // known
        return a.reshape(a.shape[:ax] + tuple(shp) + a.shape[ax + 1:])

    return apply(f, _t(x))


def as_strided(x, shape, stride, offset=0, name=None):
    """paddle.as_strided: strided view of the underlying buffer. XLA arrays
    are immutable/functional, so this returns a strided GATHER (same
    values; writes through the result do not alias x — in-place aliasing
    is a torch/paddle storage concept with no XLA equivalent)."""
    if len(shape) != len(stride):
        raise ValueError(
            f"as_strided shape ({len(shape)} dims) and stride "
            f"({len(stride)} dims) must have the same length")

    def f(a):
        flat = a.reshape(-1)
        idx = np.full(tuple(shape), int(offset), np.int64)
        for d, (s, st) in enumerate(zip(shape, stride)):
            ar = np.arange(s, dtype=np.int64) * int(st)
            idx = idx + ar.reshape([-1 if i == d else 1
                                    for i in range(len(shape))])
        if idx.size and (idx.min() < 0 or idx.max() >= flat.shape[0]):
            raise ValueError(
                f"as_strided indices span [{idx.min()}, {idx.max()}] "
                f"outside the {flat.shape[0]}-element buffer")
        return jnp.take(flat, jnp.asarray(idx.reshape(-1)),
                        axis=0).reshape(tuple(shape))

    return apply(f, _t(x))


def flatten_(x, start_axis=0, stop_axis=-1, name=None):
    """In-place flatten (2.x flatten_ variant): rebinds through the tape."""
    t = _t(x)
    return _inplace_via_tape(t, flatten(t, start_axis, stop_axis),
                             "flatten_")
