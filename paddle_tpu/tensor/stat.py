"""Statistics ops (reference: python/paddle/tensor/stat.py)."""
from __future__ import annotations

import jax.numpy as jnp

from ..core.tensor import apply
from .creation import _t
from .math import _axis


def mean(x, axis=None, keepdim=False, name=None):
    return apply(lambda a: jnp.mean(a, axis=_axis(axis), keepdims=keepdim), _t(x))


def var(x, axis=None, unbiased=True, keepdim=False, name=None):
    return apply(lambda a: jnp.var(a, axis=_axis(axis), ddof=1 if unbiased else 0,
                                   keepdims=keepdim), _t(x))


def std(x, axis=None, unbiased=True, keepdim=False, name=None):
    return apply(lambda a: jnp.std(a, axis=_axis(axis), ddof=1 if unbiased else 0,
                                   keepdims=keepdim), _t(x))


def median(x, axis=None, keepdim=False, name=None):
    return apply(lambda a: jnp.median(a, axis=_axis(axis), keepdims=keepdim), _t(x))


def nanmedian(x, axis=None, keepdim=False, name=None):
    return apply(lambda a: jnp.nanmedian(a, axis=_axis(axis), keepdims=keepdim),
                 _t(x))


def quantile(x, q, axis=None, keepdim=False, name=None):
    return apply(lambda a: jnp.quantile(a, jnp.asarray(q), axis=_axis(axis),
                                        keepdims=keepdim), _t(x))


def nanquantile(x, q, axis=None, keepdim=False, name=None):
    return apply(lambda a: jnp.nanquantile(a, jnp.asarray(q), axis=_axis(axis),
                                           keepdims=keepdim), _t(x))
