"""Gradient clipping (reference: python/paddle/fluid/clip.py — GradientClipByValue,
GradientClipByNorm, GradientClipByGlobalNorm)."""
from __future__ import annotations

import jax.numpy as jnp

from ..core.tensor import Tensor, apply


class ClipGradBase:
    def __call__(self, params_grads):
        raise NotImplementedError


class ClipGradByValue(ClipGradBase):
    def __init__(self, max, min=None):
        self.max = max
        self.min = -max if min is None else min

    def __call__(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None:
                out.append((p, g))
                continue
            out.append((p, Tensor(jnp.clip(g.data, self.min, self.max))))
        return out


class ClipGradByNorm(ClipGradBase):
    def __init__(self, clip_norm):
        self.clip_norm = clip_norm

    def __call__(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None:
                out.append((p, g))
                continue
            norm = jnp.sqrt(jnp.sum(jnp.square(g.data.astype(jnp.float32))))
            factor = jnp.minimum(self.clip_norm / jnp.maximum(norm, 1e-12), 1.0)
            out.append((p, Tensor((g.data.astype(jnp.float32) * factor).astype(
                g.data.dtype))))
        return out


class ClipGradByGlobalNorm(ClipGradBase):
    """Global-norm clip. Under hybrid parallel the squared-norm is psum'ed across
    the model/sharding axes by HybridParallelClipGrad (distributed layer)."""

    def __init__(self, clip_norm, group_name="default_group"):
        self.clip_norm = clip_norm

    def __call__(self, params_grads):
        sq = [jnp.sum(jnp.square(g.data.astype(jnp.float32)))
              for p, g in params_grads if g is not None
              and getattr(p, "need_clip", True)]
        if not sq:
            return params_grads
        global_norm = jnp.sqrt(sum(sq))
        factor = jnp.minimum(
            self.clip_norm / jnp.maximum(global_norm, self.clip_norm), 1.0)
        out = []
        for p, g in params_grads:
            if g is None or not getattr(p, "need_clip", True):
                out.append((p, g))
                continue
            out.append((p, Tensor((g.data.astype(jnp.float32) * factor).astype(
                g.data.dtype))))
        return out


# legacy fluid aliases
GradientClipByValue = ClipGradByValue
GradientClipByNorm = ClipGradByNorm
GradientClipByGlobalNorm = ClipGradByGlobalNorm


def clip_grad_norm_(parameters, max_norm, norm_type=2.0, error_if_nonfinite=False):
    params = [p for p in parameters if p.grad is not None]
    if not params:
        return Tensor(jnp.zeros(()))
    if norm_type == float("inf"):
        total = jnp.max(jnp.stack(
            [jnp.max(jnp.abs(p.grad.data)) for p in params]))
    else:
        total = jnp.power(
            sum(jnp.sum(jnp.power(jnp.abs(p.grad.data.astype(jnp.float32)),
                                  norm_type)) for p in params),
            1.0 / norm_type)
    if error_if_nonfinite and not bool(jnp.isfinite(total)):
        # torch parity: the default (False) silently scales by the
        # non-finite norm (factor underflows to 0 against inf, and NaN
        # poisons the grads — which the numerics observatory then blames);
        # True turns the condition into an immediate, named failure. The
        # host sync only happens when the caller opted into the check.
        raise RuntimeError(
            f"The total norm of order {norm_type} for gradients from "
            "`parameters` is non-finite, so it cannot be clipped. To "
            "disable this error and scale the gradients with the "
            "non-finite norm anyway, set error_if_nonfinite=False")
    factor = jnp.minimum(max_norm / jnp.maximum(total, 1e-6), 1.0)
    for p in params:
        p.grad.data = (p.grad.data.astype(jnp.float32) * factor).astype(
            p.grad.data.dtype)
    return Tensor(total)
