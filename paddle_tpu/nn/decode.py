"""Beam-search decoding (reference: python/paddle/nn/decode.py re-exporting
fluid/layers/rnn.py — BeamSearchDecoder:866 + dynamic_decode:1584).

TPU-native: the decode loop is a lockstep batched beam sweep over
[batch*beam] states — every step is dense top-k + gathers (XLA-friendly; no
per-beam Python branching), and finished beams are masked rather than
removed so shapes stay static.
"""
from __future__ import annotations

from typing import Optional

import jax.lax
import jax.numpy as jnp

from ..core.tensor import Tensor, no_grad
from ..tensor.creation import _t

_NEG_INF = -1e9


class BeamSearchDecoder:
    """Wraps an RNN cell for beam search (fluid/layers/rnn.py:866 API)."""

    def __init__(self, cell, start_token: int, end_token: int,
                 beam_size: int, embedding_fn=None, output_fn=None):
        self.cell = cell
        self.start_token = start_token
        self.end_token = end_token
        self.beam_size = beam_size
        self.embedding_fn = embedding_fn
        self.output_fn = output_fn

    @staticmethod
    def tile_beam_merge_with_batch(x, beam_size: int):
        """[B, ...] -> [B*beam, ...] with each row repeated beam times."""
        a = _t(x).data
        tiled = jnp.repeat(a[:, None], beam_size, axis=1)
        return Tensor(tiled.reshape((-1,) + a.shape[1:]))


def _gather_beams(tree_arr, beam_idx, batch, beam):
    """Select ancestor beams: arr [B*K, ...] indexed by beam_idx [B, K]."""
    flat_idx = (jnp.arange(batch)[:, None] * beam + beam_idx).reshape(-1)
    return tree_arr[flat_idx]


@no_grad()
def dynamic_decode(decoder: BeamSearchDecoder, inits=None,
                   max_step_num: Optional[int] = 64, batch_size=None,
                   **kwargs):
    """Run beam search to completion (rnn.py dynamic_decode:1584).

    inits: initial cell states [B, H] (or None for zeros; requires
    batch_size). Returns (ids Tensor [B, beam, T] best-first,
    sequence_lengths Tensor [B, beam]).
    """
    import jax

    K = decoder.beam_size
    end = decoder.end_token

    def _leaves(x):
        return jax.tree_util.tree_map(
            lambda t: t.data if isinstance(t, Tensor) else jnp.asarray(t), x,
            is_leaf=lambda t: isinstance(t, Tensor))

    if inits is None:
        if batch_size is None:
            raise ValueError("dynamic_decode needs inits or batch_size")
        B = batch_size
        states = None  # the cell builds its own zeros at [B*K, ...]
    else:
        st = _leaves(inits)
        B = jax.tree_util.tree_leaves(st)[0].shape[0]
        # tile every state leaf to [B*K, ...]; one live beam per row at t=0
        states = jax.tree_util.tree_map(
            lambda a: jnp.repeat(a[:, None], K, axis=1).reshape(
                (B * K,) + a.shape[1:]), st)
    log_probs = jnp.full((B, K), _NEG_INF).at[:, 0].set(0.0)
    finished = jnp.zeros((B, K), bool)
    tokens = jnp.full((B * K,), decoder.start_token, jnp.int32)
    history = []
    lengths = jnp.zeros((B, K), jnp.int32)

    def _wrap_states(s):
        return jax.tree_util.tree_map(Tensor, s) if s is not None else None

    if max_step_num is None:
        # reference default: decode until every beam emits end_token, with a
        # sanity ceiling so a never-ending cell cannot loop forever
        max_step_num = 1024
    for _ in range(max_step_num):
        if decoder.embedding_fn is not None:
            inp = decoder.embedding_fn(Tensor(tokens))
        else:
            inp = Tensor(tokens)
        out, new_states = decoder.cell(inp, _wrap_states(states))
        if decoder.output_fn is not None:
            out = decoder.output_fn(out)
        logits = out.data.astype(jnp.float32)  # [B*K, V]
        V = logits.shape[-1]
        m = logits.max(-1, keepdims=True)
        step_lp = (logits - m) - jnp.log(
            jnp.sum(jnp.exp(logits - m), -1, keepdims=True))
        step_lp = step_lp.reshape(B, K, V)
        # finished beams may only emit end_token at zero cost
        fin_mask = jnp.full((V,), _NEG_INF).at[end].set(0.0)
        step_lp = jnp.where(finished[:, :, None], fin_mask[None, None],
                            step_lp)
        total = log_probs[:, :, None] + step_lp  # [B, K, V]
        flat = total.reshape(B, K * V)
        top_scores, top_idx = jax.lax.top_k(flat, K)
        beam_idx = top_idx // V        # ancestor beam  [B, K]
        tok = (top_idx % V).astype(jnp.int32)
        log_probs = top_scores
        states = jax.tree_util.tree_map(
            lambda a: _gather_beams(a, beam_idx, B, K), _leaves(new_states))
        finished = _gather_beams(finished.reshape(B * K), beam_idx, B,
                                 K).reshape(B, K)
        lengths = _gather_beams(lengths.reshape(B * K), beam_idx, B,
                                K).reshape(B, K)
        lengths = jnp.where(finished, lengths, lengths + 1)
        finished = finished | (tok == end)
        # re-route history through the chosen ancestors
        history = [_gather_beams(hstep.reshape(B * K), beam_idx, B,
                                 K).reshape(B, K) for hstep in history]
        history.append(tok)
        tokens = tok.reshape(B * K)
        if bool(jnp.all(finished)):
            break

    ids = jnp.stack(history, axis=-1) if history else \
        jnp.zeros((B, K, 0), jnp.int32)
    # best-first ordering by final score
    order = jnp.argsort(-log_probs, axis=-1)
    ids = jnp.take_along_axis(ids, order[:, :, None], axis=1)
    lengths = jnp.take_along_axis(lengths, order, axis=1)
    return Tensor(ids), Tensor(lengths)
