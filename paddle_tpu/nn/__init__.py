"""paddle.nn analog — layer zoo + functional + initializers.

Reference surface: python/paddle/nn/__init__.py (100+ layers).
"""
from . import functional  # noqa: F401
from . import initializer  # noqa: F401
from . import utils  # noqa: F401
from .clip import (ClipGradByGlobalNorm, ClipGradByNorm,  # noqa: F401
                   ClipGradByValue, GradientClipByGlobalNorm,
                   GradientClipByNorm, GradientClipByValue, clip_grad_norm_)
from .layer.activation import *  # noqa: F401,F403
from .layer.common import *  # noqa: F401,F403
from .layer.conv import (Conv1D, Conv1DTranspose, Conv2D,  # noqa: F401
                         Conv2DTranspose, Conv3D, Conv3DTranspose)
from .layer.layers import (Layer, LayerDict, LayerList,  # noqa: F401
                           ParamAttr, ParameterList, Sequential)
from .layer.loss import *  # noqa: F401,F403
from .layer.moe import MoELayer  # noqa: F401
from .layer.norm import (BatchNorm, BatchNorm1D, BatchNorm2D,  # noqa: F401
                         BatchNorm3D, GroupNorm, InstanceNorm1D,
                         InstanceNorm2D, InstanceNorm3D, LayerNorm,
                         LocalResponseNorm, SpectralNorm, SyncBatchNorm)
from .layer.pooling import (AdaptiveAvgPool1D, AdaptiveAvgPool2D,  # noqa: F401
                            AdaptiveAvgPool3D, AdaptiveMaxPool1D,
                            AdaptiveMaxPool2D, AdaptiveMaxPool3D, AvgPool1D,
                            AvgPool2D, AvgPool3D, MaxPool1D, MaxPool2D,
                            MaxPool3D)
from .layer.rnn import (GRU, LSTM, BiRNN, GRUCell, LSTMCell, RNN,  # noqa: F401
                        RNNCellBase, SimpleRNN, SimpleRNNCell)
from .layer.transformer import (MultiHeadAttention, Transformer,  # noqa: F401
                                TransformerDecoder, TransformerDecoderLayer,
                                TransformerEncoder, TransformerEncoderLayer)

from .decode import BeamSearchDecoder, dynamic_decode  # noqa: F401,E402
