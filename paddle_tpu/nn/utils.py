"""paddle.nn.utils (reference: python/paddle/nn/utils/weight_norm_hook.py,
spectral_norm_hook.py — reparameterization via forward pre-hooks).

weight_norm: w = g * v / ||v||   (g, v trainable; recomputed pre-forward)
spectral_norm: w = w / sigma_max(w)  (power iteration on a persistent u)
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..core.tensor import Parameter, Tensor
from ..tensor.creation import _t

__all__ = ["weight_norm", "remove_weight_norm", "spectral_norm"]


def _norm_except_dim(v, dim):
    """L2 norm over all axes except `dim`, shaped for broadcast against v."""
    if dim is None:
        return jnp.sqrt(jnp.sum(jnp.square(v)))
    axes = tuple(i for i in range(v.ndim) if i != dim)
    n = jnp.sqrt(jnp.sum(jnp.square(v), axis=axes, keepdims=True))
    return n


def weight_norm(layer, name="weight", dim=0):
    """Replace layer.<name> with (name_g, name_v) and recompute the weight
    before every forward (weight_norm_hook.py)."""
    w = getattr(layer, name)
    if w is None:
        raise ValueError(f"layer has no parameter {name!r}")
    wd = w.data
    g0 = _norm_except_dim(wd.astype(jnp.float32), dim).astype(wd.dtype)
    layer.add_parameter(name + "_g", Parameter(g0))
    layer.add_parameter(name + "_v", Parameter(wd))
    # drop the original parameter; the recomputed weight is a plain tensor
    layer._parameters.pop(name, None)
    object.__setattr__(layer, name, None)

    from ..tensor import math as M

    def hook(lyr, inputs):
        v = getattr(lyr, name + "_v")
        g = getattr(lyr, name + "_g")
        # differentiable recompute through the tape: norm + scale
        def f(vv, gg):
            n = _norm_except_dim(vv.astype(jnp.float32), dim)
            return (vv.astype(jnp.float32) / jnp.maximum(n, 1e-12)
                    * gg.astype(jnp.float32)).astype(vv.dtype)
        from ..core.tensor import apply
        object.__setattr__(lyr, name, apply(f, v, g))
        return None

    helper = layer.register_forward_pre_hook(hook)
    layer.__dict__.setdefault("_weight_norm_hooks", {})[name] = (helper, dim)
    hook(layer, ())  # materialize once so the attr exists pre-forward
    return layer


def remove_weight_norm(layer, name="weight"):
    helpers = layer.__dict__.get("_weight_norm_hooks", {})
    entry = helpers.pop(name, None)
    if entry is None:
        raise ValueError(f"no weight_norm hook on parameter {name!r}")
    helper, dim = entry
    helper.remove()
    v = getattr(layer, name + "_v")
    g = getattr(layer, name + "_g")
    n = _norm_except_dim(v.data.astype(jnp.float32), dim)
    w = (v.data.astype(jnp.float32) / jnp.maximum(n, 1e-12)
         * g.data.astype(jnp.float32)).astype(v.data.dtype)
    layer._parameters.pop(name + "_g", None)
    layer._parameters.pop(name + "_v", None)
    object.__setattr__(layer, name + "_g", None)
    object.__setattr__(layer, name + "_v", None)
    layer.add_parameter(name, Parameter(w))
    return layer


def spectral_norm(layer, name="weight", n_power_iterations=1, eps=1e-12,
                  dim=0):
    """Divide the weight by its largest singular value, estimated by power
    iteration on a persistent left vector u (spectral_norm_hook.py)."""
    w = getattr(layer, name)
    if w is None:
        raise ValueError(f"layer has no parameter {name!r}")
    wd = w.data
    h = wd.shape[dim]
    rng = np.random.RandomState(0)
    state = {"u": jnp.asarray(rng.randn(h).astype(np.float32))}

    def hook(lyr, inputs):
        p = lyr._parameters.get(name + "_orig")
        if p is None:
            p = getattr(lyr, name + "_orig")
        wdat = p.data
        # power iteration on CONCRETE values (u, v are constants w.r.t.
        # the gradient, matching the reference's no-grad power iteration)
        mat = jnp.moveaxis(wdat.astype(jnp.float32), dim, 0).reshape(h, -1)
        u = state["u"]
        if n_power_iterations == 0:
            # reuse the stored u (reference behavior); v must still be
            # computed so sigma = u^T W v is well-defined
            v = mat.T @ u
            v = v / jnp.maximum(jnp.linalg.norm(v), eps)
        for _ in range(n_power_iterations):
            v = mat.T @ u
            v = v / jnp.maximum(jnp.linalg.norm(v), eps)
            u = mat @ v
            u = u / jnp.maximum(jnp.linalg.norm(u), eps)
        state["u"] = u

        from ..core.tensor import apply

        def f(ww):
            # sigma = u^T W v INSIDE the op: d(W/sigma)/dW carries the
            # -W·(u v^T)/sigma^2 term like the reference
            m = jnp.moveaxis(ww.astype(jnp.float32), dim, 0).reshape(h, -1)
            sigma = u @ (m @ v)
            return (ww.astype(jnp.float32)
                    / jnp.maximum(sigma, eps)).astype(ww.dtype)

        object.__setattr__(lyr, name, apply(f, p))
        return None

    layer.add_parameter(name + "_orig", Parameter(wd))
    layer._parameters.pop(name, None)
    object.__setattr__(layer, name, None)
    helper = layer.register_forward_pre_hook(hook)
    layer.__dict__.setdefault("_spectral_norm_hooks", {})[name] = helper
    hook(layer, ())
    return layer
