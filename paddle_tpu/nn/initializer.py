"""Weight initializers (reference: python/paddle/nn/initializer/*, fluid/initializer.py).

Reference initializers emit init ops into the startup program; here each initializer is
a callable (shape, dtype) -> jax.Array evaluated eagerly at Parameter creation.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ..core import dtypes
from ..core.random import next_key


class Initializer:
    def __call__(self, shape, dtype=None):
        raise NotImplementedError


class Constant(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def __call__(self, shape, dtype=None):
        return jnp.full(tuple(shape), self.value,
                        dtypes.convert_dtype(dtype) or dtypes.get_default_dtype())


class Uniform(Initializer):
    def __init__(self, low=-1.0, high=1.0):
        self.low, self.high = low, high

    def __call__(self, shape, dtype=None):
        d = dtypes.convert_dtype(dtype) or dtypes.get_default_dtype()
        return jax.random.uniform(next_key(), tuple(shape), d, self.low, self.high)


class Normal(Initializer):
    def __init__(self, mean=0.0, std=1.0):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype=None):
        d = dtypes.convert_dtype(dtype) or dtypes.get_default_dtype()
        return self.mean + self.std * jax.random.normal(next_key(), tuple(shape), d)


class TruncatedNormal(Initializer):
    def __init__(self, mean=0.0, std=1.0):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype=None):
        d = dtypes.convert_dtype(dtype) or dtypes.get_default_dtype()
        return self.mean + self.std * jax.random.truncated_normal(
            next_key(), -2.0, 2.0, tuple(shape), d)


def _fans(shape):
    shape = tuple(shape)
    if len(shape) < 1:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    receptive = int(np.prod(shape[2:])) if len(shape) > 2 else 1
    # paddle Linear weights are [in, out]
    fan_in = shape[0] * receptive if len(shape) == 2 else shape[1] * receptive
    fan_out = shape[1] * receptive if len(shape) == 2 else shape[0] * receptive
    return fan_in, fan_out


class XavierNormal(Initializer):
    # reference signature: (fan_in, fan_out, name); gain is a later-2.x
    # extension kept at the keyword tail
    def __init__(self, fan_in=None, fan_out=None, name=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype=None):
        fi, fo = _fans(shape)
        fi = self.fan_in or fi
        fo = self.fan_out or fo
        std = self.gain * math.sqrt(2.0 / (fi + fo))
        return Normal(0.0, std)(shape, dtype)


class XavierUniform(Initializer):
    def __init__(self, fan_in=None, fan_out=None, name=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype=None):
        fi, fo = _fans(shape)
        fi = self.fan_in or fi
        fo = self.fan_out or fo
        limit = self.gain * math.sqrt(6.0 / (fi + fo))
        return Uniform(-limit, limit)(shape, dtype)


class KaimingNormal(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in = fan_in
        self.negative_slope = negative_slope

    def __call__(self, shape, dtype=None):
        fi, _ = _fans(shape)
        fi = self.fan_in or fi
        gain = math.sqrt(2.0 / (1 + self.negative_slope ** 2))
        return Normal(0.0, gain / math.sqrt(fi))(shape, dtype)


class KaimingUniform(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in = fan_in
        self.negative_slope = negative_slope

    def __call__(self, shape, dtype=None):
        fi, _ = _fans(shape)
        fi = self.fan_in or fi
        gain = math.sqrt(2.0 / (1 + self.negative_slope ** 2))
        limit = gain * math.sqrt(3.0 / fi)
        return Uniform(-limit, limit)(shape, dtype)


class Assign(Initializer):
    def __init__(self, value):
        self.value = value

    def __call__(self, shape, dtype=None):
        d = dtypes.convert_dtype(dtype) or dtypes.get_default_dtype()
        arr = jnp.asarray(np.asarray(
            self.value.numpy() if hasattr(self.value, "numpy") else self.value))
        return arr.reshape(tuple(shape)).astype(d)


class Orthogonal(Initializer):
    def __init__(self, gain=1.0):
        self.gain = gain

    def __call__(self, shape, dtype=None):
        d = dtypes.convert_dtype(dtype) or dtypes.get_default_dtype()
        return jax.nn.initializers.orthogonal(self.gain)(
            next_key(), tuple(shape), d)


class Dirac(Initializer):
    def __init__(self, groups=1):
        self.groups = groups

    def __call__(self, shape, dtype=None):
        d = dtypes.convert_dtype(dtype) or dtypes.get_default_dtype()
        out = np.zeros(tuple(shape), np.dtype(d) if np.dtype(d) != np.dtype(
            dtypes.bfloat16) else np.float32)
        oc, ic = shape[0], shape[1]
        centers = [s // 2 for s in shape[2:]]
        for i in range(min(oc, ic * self.groups)):
            idx = (i, i % ic) + tuple(centers)
            out[idx] = 1.0
        return jnp.asarray(out).astype(d)


# default initializer used by Layer.create_parameter when attr is None
_GLOBAL_DEFAULT = [XavierUniform()]


def set_global_initializer(weight_init, bias_init=None):
    _GLOBAL_DEFAULT[0] = weight_init


def calculate_gain(nonlinearity, param=None):
    gains = {"sigmoid": 1.0, "linear": 1.0, "conv1d": 1.0, "conv2d": 1.0,
             "conv3d": 1.0, "tanh": 5.0 / 3, "relu": math.sqrt(2.0),
             "leaky_relu": math.sqrt(2.0 / (1 + (param or 0.01) ** 2)),
             "selu": 3.0 / 4}
    return gains[nonlinearity]


class Bilinear(Initializer):
    """initializer.Bilinear (fluid/initializer.py BilinearInitializer):
    the classic bilinear-upsampling kernel for transposed-conv weights
    [C_out, C_in, k, k]: w[y, x] = (1 - |x/f - c|) * (1 - |y/f - c|)
    with f = ceil(k / 2), c = (2f - 1 - f % 2) / (2f)."""

    def __call__(self, shape, dtype=None):
        import numpy as np
        shape = tuple(int(s) for s in shape)
        if len(shape) != 4:
            raise ValueError("Bilinear initializer expects a 4-D "
                             f"conv weight shape, got {shape}")
        k = shape[-1]
        if shape[-2] != k:
            raise ValueError("Bilinear initializer expects square "
                             f"kernels, got {shape[-2:]}")
        f = int(np.ceil(k / 2.0))
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        xs = np.arange(k)
        w1d = 1 - np.abs(xs / f - c)
        kern = np.outer(w1d, w1d).astype(np.float32)
        out = np.zeros(shape, np.float32)
        out[...] = kern
        from ..core.dtype import convert_dtype, get_default_dtype
        return jnp.asarray(out, convert_dtype(dtype)
                           if dtype else get_default_dtype())
