"""Normalization functionals (reference: operators/batch_norm_op.*, layer_norm_op.*).

layer_norm computes in fp32 regardless of input dtype (matching the reference's CUDA
kernel behavior) — essential for bf16 training stability on TPU.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core.tensor import Tensor, apply
from ...tensor.creation import _t


def layer_norm(x, normalized_shape, weight=None, bias=None, epsilon=1e-05,
               name=None):
    x = _t(x)
    if isinstance(normalized_shape, int):
        normalized_shape = [normalized_shape]
    n_axes = len(normalized_shape)

    def f(a, *wb):
        from ...ops import layernorm as _ln
        if _ln.eligible(a.shape, n_axes, weight is not None,
                        bias is not None) and a.ndim - n_axes >= 1:
            # one-pass Pallas kernel on TPU (fp32 stats, fused affine + vjp)
            return _ln.fused_layer_norm(a, wb[0], wb[1], epsilon)
        axes = tuple(range(a.ndim - n_axes, a.ndim))
        orig = a.dtype
        h = a.astype(jnp.float32)
        mu = jnp.mean(h, axis=axes, keepdims=True)
        var = jnp.mean(jnp.square(h - mu), axis=axes, keepdims=True)
        out = (h - mu) * jax.lax.rsqrt(var + epsilon)
        i = 0
        if weight is not None:
            out = out * wb[i].astype(jnp.float32)
            i += 1
        if bias is not None:
            out = out + wb[i].astype(jnp.float32)
        return out.astype(orig)

    args = [x]
    if weight is not None:
        args.append(_t(weight))
    if bias is not None:
        args.append(_t(bias))
    return apply(f, *args)


def batch_norm(x, running_mean, running_var, weight=None, bias=None,
               training=False, momentum=0.9, epsilon=1e-05, data_format="NCHW",
               use_global_stats=None, name=None):
    x = _t(x)
    rm, rv = _t(running_mean), _t(running_var)
    use_batch_stats = training and not use_global_stats

    def f(a, *wb):
        ch_axis = a.ndim - 1 if data_format[-1] == "C" and a.ndim > 2 else 1
        if a.ndim <= 2:
            ch_axis = 1 if a.ndim == 2 else 0
        reduce_axes = tuple(i for i in range(a.ndim) if i != ch_axis)
        orig = a.dtype
        h = a.astype(jnp.float32)
        if use_batch_stats:
            mu = jnp.mean(h, axis=reduce_axes)
            var = jnp.var(h, axis=reduce_axes)
        else:
            mu = wb[-2].astype(jnp.float32)
            var = wb[-1].astype(jnp.float32)
        shape = [1] * a.ndim
        shape[ch_axis] = h.shape[ch_axis]
        out = (h - mu.reshape(shape)) / jnp.sqrt(var.reshape(shape) + epsilon)
        i = 0
        if weight is not None:
            out = out * wb[i].astype(jnp.float32).reshape(shape)
            i += 1
        if bias is not None:
            out = out + wb[i].astype(jnp.float32).reshape(shape)
        return out.astype(orig)

    args = [x]
    if weight is not None:
        args.append(_t(weight))
    if bias is not None:
        args.append(_t(bias))
    args.extend([rm, rv])
    out = apply(f, *args)

    # update running stats eagerly (matches reference's in-kernel update)
    if use_batch_stats:
        ch_axis = (x.data.ndim - 1 if data_format[-1] == "C" and x.data.ndim > 2
                   else (1 if x.data.ndim >= 2 else 0))
        reduce_axes = tuple(i for i in range(x.data.ndim) if i != ch_axis)
        h = x.data.astype(jnp.float32)
        mu = jnp.mean(h, axis=reduce_axes)
        n = h.size // h.shape[ch_axis]
        var = jnp.var(h, axis=reduce_axes) * (n / max(n - 1, 1))
        rm.data = (momentum * rm.data.astype(jnp.float32)
                   + (1 - momentum) * mu).astype(rm.data.dtype)
        rv.data = (momentum * rv.data.astype(jnp.float32)
                   + (1 - momentum) * var).astype(rv.data.dtype)
    return out


def instance_norm(x, running_mean=None, running_var=None, weight=None, bias=None,
                  use_input_stats=True, momentum=0.9, eps=1e-05,
                  data_format="NCHW", name=None):
    x = _t(x)

    def f(a, *wb):
        # NC* layout: normalize over spatial dims per (N, C)
        axes = tuple(range(2, a.ndim))
        orig = a.dtype
        h = a.astype(jnp.float32)
        mu = jnp.mean(h, axis=axes, keepdims=True)
        var = jnp.var(h, axis=axes, keepdims=True)
        out = (h - mu) / jnp.sqrt(var + eps)
        shape = [1, a.shape[1]] + [1] * (a.ndim - 2)
        i = 0
        if weight is not None:
            out = out * wb[i].astype(jnp.float32).reshape(shape)
            i += 1
        if bias is not None:
            out = out + wb[i].astype(jnp.float32).reshape(shape)
        return out.astype(orig)

    args = [x]
    if weight is not None:
        args.append(_t(weight))
    if bias is not None:
        args.append(_t(bias))
    return apply(f, *args)


def group_norm(x, num_groups, epsilon=1e-05, weight=None, bias=None,
               data_format="NCHW", name=None):
    x = _t(x)

    def f(a, *wb):
        orig = a.dtype
        h = a.astype(jnp.float32)
        if data_format == "NHWC":
            h = jnp.moveaxis(h, -1, 1)
        N, C = h.shape[0], h.shape[1]
        spatial = h.shape[2:]
        g = h.reshape(N, num_groups, C // num_groups, *spatial)
        axes = tuple(range(2, g.ndim))
        mu = jnp.mean(g, axis=axes, keepdims=True)
        var = jnp.var(g, axis=axes, keepdims=True)
        out = ((g - mu) / jnp.sqrt(var + epsilon)).reshape(N, C, *spatial)
        shape = [1, C] + [1] * len(spatial)
        i = 0
        if weight is not None:
            out = out * wb[i].astype(jnp.float32).reshape(shape)
            i += 1
        if bias is not None:
            out = out + wb[i].astype(jnp.float32).reshape(shape)
        if data_format == "NHWC":
            out = jnp.moveaxis(out, 1, -1)
        return out.astype(orig)

    args = [x]
    if weight is not None:
        args.append(_t(weight))
    if bias is not None:
        args.append(_t(bias))
    return apply(f, *args)


def local_response_norm(x, size, alpha=1e-4, beta=0.75, k=1.0,
                        data_format="NCHW", name=None):
    x = _t(x)

    def f(a):
        sq = jnp.square(a)
        half = size // 2
        pad_cfg = [(0, 0)] * a.ndim
        pad_cfg[1] = (half, size - 1 - half)
        padded = jnp.pad(sq, pad_cfg)
        acc = sum(padded[:, i:i + a.shape[1]] for i in range(size))
        # 2.x convention (nn/functional/norm.py local_response_norm in the
        # reference builds the window with avg_pool): alpha scales the
        # window MEAN, matching torch — the fluid lrn_op scaled the sum
        return a / jnp.power(k + alpha * acc / size, beta)

    return apply(f, x)
