"""paddle.nn.functional analog."""
from .activation import *  # noqa: F401,F403
from .common import *  # noqa: F401,F403
from .conv import (conv1d, conv1d_transpose, conv2d,  # noqa: F401
                   conv2d_transpose, conv3d, conv3d_transpose, fold, unfold)
from .loss import *  # noqa: F401,F403
from .norm import (batch_norm, group_norm, instance_norm,  # noqa: F401
                   layer_norm, local_response_norm)
from .pooling import *  # noqa: F401,F403
from ...tensor.manipulation import pad  # noqa: F401
