"""Convolutions via lax.conv_general_dilated — XLA maps these onto the MXU.

Reference op: paddle/fluid/operators/conv_op.* (cuDNN); here the layout is carried as
dimension_numbers so NCHW (paddle default) and NHWC (TPU-preferred) both work with no
transposes in user code.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core.amp import autocast_inputs
from ...core.tensor import Tensor, apply
from ...tensor.creation import _t


def _norm_tuple(v, n):
    if isinstance(v, int):
        return (v,) * n
    return tuple(int(x) for x in v)


def _padding(padding, n):
    if isinstance(padding, str):
        return padding.upper()  # SAME / VALID
    if isinstance(padding, int):
        return [(padding, padding)] * n
    padding = list(padding)
    if len(padding) == n and all(isinstance(p, int) for p in padding):
        return [(p, p) for p in padding]
    if len(padding) == 2 * n:
        return [(padding[2 * i], padding[2 * i + 1]) for i in range(n)]
    return [tuple(p) for p in padding]


def _dim_numbers(n, channel_last):
    if n == 1:
        return ("NCH", "OIH", "NCH") if not channel_last else ("NHC", "HIO", "NHC")
    if n == 2:
        return (("NCHW", "OIHW", "NCHW") if not channel_last
                else ("NHWC", "HWIO", "NHWC"))
    return (("NCDHW", "OIDHW", "NCDHW") if not channel_last
            else ("NDHWC", "DHWIO", "NDHWC"))


def _conv_nd(x, weight, bias, stride, padding, dilation, groups, n, data_format):
    channel_last = data_format in ("NHWC", "NLC", "NDHWC", "NHC")
    strides = _norm_tuple(stride, n)
    dil = _norm_tuple(dilation, n)
    pad = _padding(padding, n)
    dn_str = _dim_numbers(n, channel_last)

    def f(a, w, *maybe_bias):
        a, w, *maybe_bias = autocast_inputs(f"conv{n}d", a, w, *maybe_bias)
        # weight layout is paddle's OIHW... convert for channel_last spec
        lhs_spec, rhs_spec, out_spec = dn_str
        if channel_last:
            # paddle weights stay OIHW-like: [out, in/groups, *k]; transpose to HWIO
            perm = tuple(range(2, 2 + n)) + (1, 0)
            w = jnp.transpose(w, perm)
        dn = jax.lax.conv_dimension_numbers(a.shape, w.shape,
                                            (lhs_spec, rhs_spec, out_spec))
        # no preferred_element_type: the MXU accumulates bf16 convs in f32
        # natively, and forcing f32 output breaks the vjp transpose rule
        # (cotangent f32 vs bf16 primal in _conv_general_dilated_transpose_rhs)
        out = jax.lax.conv_general_dilated(
            a, w, window_strides=strides, padding=pad,
            rhs_dilation=dil, dimension_numbers=dn,
            feature_group_count=groups)
        if maybe_bias:
            b = maybe_bias[0]
            shape = [1] * out.ndim
            ch_axis = out.ndim - 1 if channel_last else 1
            shape[ch_axis] = b.shape[0]
            out = out + b.reshape(shape)
        return out

    if bias is not None:
        return apply(f, _t(x), _t(weight), _t(bias))
    return apply(f, _t(x), _t(weight))


def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCL", name=None):
    fmt = "NHC" if data_format == "NLC" else "NCH"
    return _conv_nd(x, weight, bias, stride, padding, dilation, groups, 1, fmt)


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCHW", name=None):
    return _conv_nd(x, weight, bias, stride, padding, dilation, groups, 2,
                    data_format)


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCDHW", name=None):
    return _conv_nd(x, weight, bias, stride, padding, dilation, groups, 3,
                    data_format)


def _conv_transpose_nd(x, weight, bias, stride, padding, output_padding,
                       dilation, groups, n, data_format, output_size):
    channel_last = data_format in ("NHWC", "NLC", "NDHWC")
    strides = _norm_tuple(stride, n)
    dil = _norm_tuple(dilation, n)
    pad = _padding(padding, n)
    opad = _norm_tuple(output_padding or 0, n)

    def f(a, w, *maybe_bias):
        # paddle transpose-conv weight: [in, out/groups, *k]. Express the
        # transposed conv as a direct conv over the stride-dilated input:
        # flip the kernel spatially and regroup [in, out/g] -> [out, in/g]
        # (the old lax transpose_kernel=True flag did this internally; it
        # no longer exists).
        lhs_spec = ("NCH", "NCHW", "NCDHW")[n - 1] if not channel_last else \
            ("NHC", "NHWC", "NDHWC")[n - 1]
        rhs_spec = ("OIH", "OIHW", "OIDHW")[n - 1]
        out_spec = lhs_spec
        ks = [w.shape[i] for i in range(2, 2 + n)]
        in_ch, out_pg = w.shape[0], w.shape[1]
        wt = jnp.flip(w, axis=tuple(range(2, 2 + n)))
        wt = wt.reshape((groups, in_ch // groups, out_pg) + tuple(ks))
        wt = jnp.moveaxis(wt, 2, 1)  # [g, out/g, in/g, *k]
        wt = wt.reshape((groups * out_pg, in_ch // groups) + tuple(ks))
        dn = jax.lax.conv_dimension_numbers(
            a.shape, wt.shape, (lhs_spec, rhs_spec, out_spec))
        if isinstance(pad, str):
            padding_cfg = pad
        else:
            # grad-of-conv padding: k' = dilated kernel; p' = k'-1-p
            padding_cfg = [
                (dil[i] * (ks[i] - 1) - pad[i][0],
                 dil[i] * (ks[i] - 1) - pad[i][1] + opad[i])
                for i in range(n)]
        out = jax.lax.conv_general_dilated(
            a, wt, window_strides=(1,) * n, padding=padding_cfg,
            lhs_dilation=strides, rhs_dilation=dil, dimension_numbers=dn,
            feature_group_count=1 if groups == 1 else groups)
        if maybe_bias:
            b = maybe_bias[0]
            shape = [1] * out.ndim
            ch_axis = out.ndim - 1 if channel_last else 1
            shape[ch_axis] = b.shape[0]
            out = out + b.reshape(shape)
        return out

    if bias is not None:
        return apply(f, _t(x), _t(weight), _t(bias))
    return apply(f, _t(x), _t(weight))


def conv1d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1,
                     output_size=None, data_format="NCL", name=None):
    # NB reference argument order: groups BEFORE dilation for the 1d/3d
    # transposes, the opposite of conv2d_transpose (functional/conv.py:553
    # vs :809) — positional parity requires mirroring the inconsistency
    return _conv_transpose_nd(x, weight, bias, stride, padding, output_padding,
                              dilation, groups, 1, data_format, output_size)


def conv2d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, dilation=1, groups=1, output_size=None,
                     data_format="NCHW", name=None):
    return _conv_transpose_nd(x, weight, bias, stride, padding, output_padding,
                              dilation, groups, 2, data_format, output_size)


def conv3d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1,
                     output_size=None, data_format="NCDHW", name=None):
    return _conv_transpose_nd(x, weight, bias, stride, padding, output_padding,
                              dilation, groups, 3, data_format, output_size)


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    ks = _norm_tuple(kernel_sizes, 2)
    st = _norm_tuple(strides, 2)
    di = _norm_tuple(dilations, 2)
    pd = _padding(paddings, 2)

    def f(a):
        N, C, H, W = a.shape
        patches = jax.lax.conv_general_dilated_patches(
            a, filter_shape=ks, window_strides=st, padding=pd,
            rhs_dilation=di, dimension_numbers=("NCHW", "OIHW", "NCHW"))
        # patches: [N, C*kh*kw, oh, ow] -> [N, C*kh*kw, L]
        return patches.reshape(N, patches.shape[1], -1)

    return apply(f, _t(x))


def fold(x, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1,
         name=None):
    """col2im (fold_op / the inverse of unfold): x [N, C*kh*kw, L] →
    [N, C, H, W], overlapping patches SUMMED back into place. Implemented
    as a scatter-add over the same patch index grid unfold reads from."""
    import jax.numpy as jnp
    oh_w = _norm_tuple(output_sizes, 2)
    ks = _norm_tuple(kernel_sizes, 2)
    st = _norm_tuple(strides, 2)
    di = _norm_tuple(dilations, 2)
    pd = _padding(paddings, 2)
    if isinstance(pd, str):
        raise ValueError("fold requires explicit paddings, not " + pd)

    def f(a):
        N, CK, L = a.shape
        kh, kw = ks
        C = CK // (kh * kw)
        H, W = oh_w
        (pt, pb), (pl, pr) = pd
        Hp, Wp = H + pt + pb, W + pl + pr
        oh = (Hp - (kh - 1) * di[0] - 1) // st[0] + 1
        ow = (Wp - (kw - 1) * di[1] - 1) // st[1] + 1
        assert oh * ow == L, (oh, ow, L)
        cols = a.reshape(N, C, kh, kw, oh, ow)
        # padded-canvas row index of (ki, oy): oy*stride + ki*dilation
        ys = (jnp.arange(oh)[None, :] * st[0]
              + jnp.arange(kh)[:, None] * di[0])          # [kh, oh]
        xs = (jnp.arange(ow)[None, :] * st[1]
              + jnp.arange(kw)[:, None] * di[1])          # [kw, ow]
        canvas = jnp.zeros((N, C, Hp, Wp), a.dtype)
        yi = jnp.broadcast_to(ys[:, None, :, None], (kh, kw, oh, ow))
        xi = jnp.broadcast_to(xs[None, :, None, :], (kh, kw, oh, ow))
        canvas = canvas.at[:, :, yi, xi].add(cols)
        return canvas[:, :, pt:pt + H, pl:pl + W]

    return apply(f, _t(x))
