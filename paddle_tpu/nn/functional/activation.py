"""Activation functionals (reference: python/paddle/nn/functional/activation.py)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core.amp import autocast_inputs
from ...core.tensor import Tensor, apply
from ...tensor.creation import _t


def _unary(fn):
    def op(x, name=None):
        return apply(fn, _t(x))
    return op


relu = _unary(jax.nn.relu)
relu6 = _unary(jax.nn.relu6)
sigmoid = _unary(jax.nn.sigmoid)
tanh = _unary(jnp.tanh)
silu = _unary(jax.nn.silu)
swish = silu
mish = _unary(lambda a: a * jnp.tanh(jax.nn.softplus(a)))
hardswish = _unary(jax.nn.hard_swish)
def hardsigmoid(x, slope=0.1666667, offset=0.5, name=None):
    """hard_sigmoid_op: clip(slope * x + offset, 0, 1); the reference
    exposes slope/offset (functional/activation.py hardsigmoid), default
    slope 1/6."""
    return _unary(lambda a: jnp.clip(a * slope + offset, 0.0, 1.0))(x)
tanhshrink = _unary(lambda a: a - jnp.tanh(a))
softsign = _unary(jax.nn.soft_sign)
log_sigmoid = _unary(jax.nn.log_sigmoid)


def gelu(x, approximate=False, name=None):
    return apply(lambda a: jax.nn.gelu(a, approximate=approximate), _t(x))


def leaky_relu(x, negative_slope=0.01, name=None):
    return apply(lambda a: jax.nn.leaky_relu(a, negative_slope), _t(x))


def elu(x, alpha=1.0, name=None):
    return apply(lambda a: jax.nn.elu(a, alpha), _t(x))


def celu(x, alpha=1.0, name=None):
    return apply(lambda a: jax.nn.celu(a, alpha), _t(x))


def selu(x, scale=1.0507009873554805, alpha=1.6732632423543772, name=None):
    return apply(lambda a: scale * jnp.where(a > 0, a, alpha * jnp.expm1(a)), _t(x))


def prelu(x, weight, data_format="NCHW", name=None):
    x, w = _t(x), _t(weight)

    def f(a, ww):
        if ww.size == 1:
            return jnp.where(a >= 0, a, ww.reshape(()) * a)
        shape = [1] * a.ndim
        ch_axis = 1 if data_format[1] == "C" or data_format == "NCHW" else a.ndim - 1
        ch_axis = 1 if data_format.startswith("NC") else a.ndim - 1
        shape[ch_axis] = ww.size
        return jnp.where(a >= 0, a, ww.reshape(shape) * a)

    return apply(f, x, w)


def rrelu(x, lower=0.125, upper=0.333, training=True, name=None):
    x = _t(x)
    if training:
        from ...core.random import next_key
        slope = jax.random.uniform(next_key(), x.data.shape, x.data.dtype,
                                   lower, upper)
    else:
        slope = (lower + upper) / 2.0
    return apply(lambda a: jnp.where(a >= 0, a, slope * a), x)


def softplus(x, beta=1.0, threshold=20.0, name=None):
    return apply(
        lambda a: jnp.where(a * beta > threshold, a,
                            jax.nn.softplus(a * beta) / beta), _t(x))


def softshrink(x, threshold=0.5, name=None):
    return apply(lambda a: jnp.where(a > threshold, a - threshold,
                                     jnp.where(a < -threshold, a + threshold,
                                               0.0)), _t(x))


def hardshrink(x, threshold=0.5, name=None):
    return apply(lambda a: jnp.where(jnp.abs(a) > threshold, a, 0.0), _t(x))


def hardtanh(x, min=-1.0, max=1.0, name=None):
    return apply(lambda a: jnp.clip(a, min, max), _t(x))


def thresholded_relu(x, threshold=1.0, name=None):
    return apply(lambda a: jnp.where(a > threshold, a, 0.0), _t(x))


def maxout(x, groups, axis=1, name=None):
    def f(a):
        ax = axis % a.ndim
        c = a.shape[ax]
        new_shape = a.shape[:ax] + (c // groups, groups) + a.shape[ax + 1:]
        return jnp.max(a.reshape(new_shape), axis=ax + 1)

    return apply(f, _t(x))


def softmax(x, axis=-1, dtype=None, name=None):
    x = _t(x)
    if dtype is not None:
        x = x.astype(dtype)

    def f(a):
        (a,) = autocast_inputs("softmax", a)
        return jax.nn.softmax(a, axis=axis)

    return apply(f, x)


def log_softmax(x, axis=-1, dtype=None, name=None):
    x = _t(x)
    if dtype is not None:
        x = x.astype(dtype)

    def f(a):
        (a,) = autocast_inputs("log_softmax", a)
        return jax.nn.log_softmax(a, axis=axis)

    return apply(f, x)


def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1, name=None):
    from ...core.random import next_key
    x = _t(x)
    g = jax.random.gumbel(next_key(), x.data.shape, x.data.dtype)

    def f(a):
        y = jax.nn.softmax((a + g) / temperature, axis=axis)
        if hard:
            idx = jnp.argmax(y, axis=axis, keepdims=True)
            onehot = jnp.zeros_like(y).at[...].set(0.0)
            onehot = jnp.put_along_axis(onehot, idx, 1.0, axis=axis, inplace=False)
            y = onehot - jax.lax.stop_gradient(y) + y
        return y

    return apply(f, x)


def glu(x, axis=-1, name=None):
    return apply(lambda a: jax.nn.glu(a, axis=axis), _t(x))


def normalize(x, p=2, axis=1, epsilon=1e-12, name=None):
    return apply(
        lambda a: a / jnp.maximum(
            jnp.linalg.norm(a, ord=p, axis=axis, keepdims=True), epsilon), _t(x))


def _inplace(op):
    """In-place variant: runs the op through the tape and rebinds the
    tensor to the op's output node (mirroring Tensor.__setitem__'s rebind)
    so gradients include the activation derivative."""
    def fn(x, *args, **kwargs):
        from ...core.tensor import _rebind_inplace, inplace_guard
        t = _t(x)
        inplace_guard(t, f"{op.__name__}_")
        _rebind_inplace(t, op(t, *args, **kwargs))
        return t
    return fn


# in-place variants (reference exports relu_/elu_/tanh_/softmax_ which
# mutate the input VarBase)
relu_ = _inplace(relu)
elu_ = _inplace(elu)
tanh_ = _inplace(tanh)
softmax_ = _inplace(softmax)
