"""Common functionals: linear, dropout, embedding, one_hot, interpolate...

(reference: python/paddle/nn/functional/common.py, input.py)
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core import dtypes
from ...core.amp import autocast_inputs
from ...core.random import next_key
from ...core.tensor import Tensor, apply
from ...tensor.creation import _t


def linear(x, weight, bias=None, name=None):
    # paddle weight layout: [in_features, out_features] → x @ W + b, one MXU matmul
    if bias is not None:
        def f(a, w, b):
            a, w, b = autocast_inputs("linear", a, w, b)
            return jnp.matmul(a, w) + b
        return apply(f, _t(x), _t(weight), _t(bias))

    def f(a, w):
        a, w = autocast_inputs("linear", a, w)
        return jnp.matmul(a, w)
    return apply(f, _t(x), _t(weight))


def dropout(x, p=0.5, axis=None, training=True, mode="upscale_in_train",
            name=None):
    x = _t(x)
    if not training or p == 0.0:
        if mode == "downscale_in_infer" and not training:
            return apply(lambda a: a * (1 - p), x)
        return x
    if p == 1.0:
        return apply(lambda a: jnp.zeros_like(a), x)
    shape = list(x.data.shape)
    if axis is not None:
        axes = [axis] if isinstance(axis, int) else list(axis)
        shape = [s if i in axes else 1 for i, s in enumerate(shape)]
    keep = jax.random.bernoulli(next_key(), 1.0 - p, tuple(shape))

    def f(a):
        if mode == "upscale_in_train":
            return jnp.where(keep, a / (1.0 - p), 0.0).astype(a.dtype)
        return jnp.where(keep, a, 0.0).astype(a.dtype)

    return apply(f, x)


def dropout2d(x, p=0.5, training=True, data_format="NCHW", name=None):
    axis = [0, 1] if data_format == "NCHW" else [0, 3]
    return dropout(x, p, axis=axis, training=training)


def dropout3d(x, p=0.5, training=True, data_format="NCDHW", name=None):
    axis = [0, 1] if data_format == "NCDHW" else [0, 4]
    return dropout(x, p, axis=axis, training=training)


def alpha_dropout(x, p=0.5, training=True, name=None):
    x = _t(x)
    if not training or p == 0.0:
        return x
    alpha = 1.6732632423543772
    scale = 1.0507009873554805
    alpha_p = -alpha * scale
    keep = jax.random.bernoulli(next_key(), 1.0 - p, tuple(x.data.shape))
    a_coef = ((1 - p) * (1 + p * alpha_p ** 2)) ** -0.5
    b_coef = -a_coef * p * alpha_p

    def f(a):
        return (a_coef * jnp.where(keep, a, alpha_p) + b_coef).astype(a.dtype)

    return apply(f, x)


def embedding(x, weight, padding_idx=None, sparse=False, name=None):
    x, weight = _t(x), _t(weight)

    def f(ids, w):
        out = jnp.take(w, ids.astype(jnp.int32), axis=0)
        if padding_idx is not None:
            mask = (ids == padding_idx)[..., None]
            out = jnp.where(mask, 0.0, out)
        return out

    from ...core import tensor as _ct
    if (sparse and _ct.is_grad_enabled() and not weight.stop_gradient
            and weight._node is None and not _ct._is_tracer(weight.data)):
        # lookup_table_grad is_sparse=True analog: the backward emits a
        # SelectedRows (rows=ids, values=cotangent) instead of scattering
        # into a dense [V, H] buffer. Only for leaf weights — a derived
        # weight needs a dense cotangent flowing further up the tape.
        from ...core.selected_rows import SelectedRows
        ids_arr = x.data
        V = weight.data.shape[0]
        out_arr = f(ids_arr, weight.data)

        def sparse_vjp(cot):
            vals = cot.reshape(-1, cot.shape[-1])
            rows = ids_arr.reshape(-1).astype(jnp.int32)
            if padding_idx is not None:
                vals = jnp.where((rows == padding_idx)[:, None], 0.0, vals)
            return (SelectedRows(rows, vals, V),)

        out_t = Tensor(out_arr, stop_gradient=False)
        _ct._STATE.seq += 1
        node = _ct._Node(sparse_vjp, [weight], [out_t], single=True,
                         seq=_ct._STATE.seq)
        out_t._node = node
        out_t._out_index = 0
        return out_t

    return apply(f, x, weight)


def one_hot(x, num_classes, name=None):
    return apply(lambda a: jax.nn.one_hot(a.astype(jnp.int32), num_classes,
                                          dtype=dtypes.get_default_dtype()),
                 _t(x))


def label_smooth(label, prior_dist=None, epsilon=0.1, name=None):
    label = _t(label)

    def f(y, *pd):
        k = y.shape[-1]
        if pd:
            return (1 - epsilon) * y + epsilon * pd[0]
        return (1 - epsilon) * y + epsilon / k

    if prior_dist is not None:
        return apply(f, label, _t(prior_dist))
    return apply(f, label)


def interpolate(x, size=None, scale_factor=None, mode="nearest",
                align_corners=False, align_mode=0, data_format="NCHW",
                name=None):
    x = _t(x)
    channel_last = data_format[-1] == "C"
    nd = x.data.ndim - 2
    spatial = (x.data.shape[1:-1] if channel_last else x.data.shape[2:])
    if size is None:
        if isinstance(scale_factor, (int, float)):
            scale_factor = [scale_factor] * nd
        size = [int(s * f) for s, f in zip(spatial, scale_factor)]
    else:
        if isinstance(size, Tensor):
            size = [int(v) for v in size.numpy()]
        size = [int(s.item()) if isinstance(s, Tensor) else int(s) for s in size]

    jmode = {"nearest": "nearest", "bilinear": "linear", "linear": "linear",
             "trilinear": "linear", "bicubic": "cubic", "area": "linear"}[mode]

    def f(a):
        if channel_last:
            out_shape = (a.shape[0],) + tuple(size) + (a.shape[-1],)
        else:
            out_shape = a.shape[:2] + tuple(size)
        if jmode == "nearest":
            # jax.image nearest matches paddle align_corners=False
            return jax.image.resize(a, out_shape, method="nearest")
        if align_corners:
            # build index grid with corner alignment, gather per spatial dim
            out = a
            spatial_axes = (list(range(1, 1 + nd)) if channel_last
                            else list(range(2, 2 + nd)))
            for ax, s_out in zip(spatial_axes, size):
                s_in = out.shape[ax]
                if s_out == 1:
                    idx = jnp.zeros((1,), jnp.float32)
                else:
                    idx = jnp.linspace(0.0, s_in - 1, s_out)
                lo = jnp.floor(idx).astype(jnp.int32)
                hi = jnp.minimum(lo + 1, s_in - 1)
                wgt = (idx - lo).astype(a.dtype)
                shape = [1] * out.ndim
                shape[ax] = s_out
                wgt = wgt.reshape(shape)
                out = (jnp.take(out, lo, axis=ax) * (1 - wgt)
                       + jnp.take(out, hi, axis=ax) * wgt)
            return out
        return jax.image.resize(a, out_shape, method=jmode)

    return apply(f, x)


def upsample(x, size=None, scale_factor=None, mode="nearest",
             align_corners=False, align_mode=0, data_format="NCHW", name=None):
    return interpolate(x, size, scale_factor, mode, align_corners, align_mode,
                       data_format)


def pixel_shuffle(x, upscale_factor, data_format="NCHW", name=None):
    r = upscale_factor

    def f(a):
        if data_format == "NCHW":
            N, C, H, W = a.shape
            out = a.reshape(N, C // (r * r), r, r, H, W)
            out = jnp.transpose(out, (0, 1, 4, 2, 5, 3))
            return out.reshape(N, C // (r * r), H * r, W * r)
        N, H, W, C = a.shape
        out = a.reshape(N, H, W, r, r, C // (r * r))
        out = jnp.transpose(out, (0, 1, 3, 2, 4, 5))
        return out.reshape(N, H * r, W * r, C // (r * r))

    return apply(f, _t(x))


def pixel_unshuffle(x, downscale_factor, data_format="NCHW", name=None):
    r = downscale_factor

    def f(a):
        if data_format == "NCHW":
            N, C, H, W = a.shape
            out = a.reshape(N, C, H // r, r, W // r, r)
            out = jnp.transpose(out, (0, 1, 3, 5, 2, 4))
            return out.reshape(N, C * r * r, H // r, W // r)
        N, H, W, C = a.shape
        out = a.reshape(N, H // r, r, W // r, r, C)
        out = jnp.transpose(out, (0, 2, 4, 1, 3, 5)).reshape(
            N, H // r, W // r, C * r * r)
        return out

    return apply(f, _t(x))


def channel_shuffle(x, groups, data_format="NCHW", name=None):
    def f(a):
        if data_format == "NCHW":
            N, C, H, W = a.shape
            out = a.reshape(N, groups, C // groups, H, W)
            return jnp.swapaxes(out, 1, 2).reshape(N, C, H, W)
        N, H, W, C = a.shape
        out = a.reshape(N, H, W, groups, C // groups)
        return jnp.swapaxes(out, 3, 4).reshape(N, H, W, C)

    return apply(f, _t(x))


def cosine_similarity(x1, x2, axis=1, eps=1e-8):
    return apply(
        lambda a, b: jnp.sum(a * b, axis=axis) / jnp.maximum(
            jnp.linalg.norm(a, axis=axis) * jnp.linalg.norm(b, axis=axis), eps),
        _t(x1), _t(x2))


def bilinear(x1, x2, weight, bias=None, name=None):
    args = [_t(x1), _t(x2), _t(weight)]

    def f(a, b, w, *maybe_bias):
        out = jnp.einsum("bi,oij,bj->bo", a, w, b)
        if maybe_bias:
            out = out + maybe_bias[0]
        return out

    if bias is not None:
        args.append(_t(bias))
    return apply(f, *args)


def temporal_shift(x, seg_num, shift_ratio=0.25, data_format="NCHW", name=None):
    def f(a):
        N_T, C, H, W = a.shape
        a5 = a.reshape(-1, seg_num, C, H, W)
        fold = int(C * shift_ratio)
        left = jnp.pad(a5[:, 1:, :fold], ((0, 0), (0, 1), (0, 0), (0, 0), (0, 0)))
        right = jnp.pad(a5[:, :-1, fold:2 * fold],
                        ((0, 0), (1, 0), (0, 0), (0, 0), (0, 0)))
        rest = a5[:, :, 2 * fold:]
        return jnp.concatenate([left, right, rest], axis=2).reshape(N_T, C, H, W)

    return apply(f, _t(x))
