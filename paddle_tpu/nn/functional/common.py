"""Common functionals: linear, dropout, embedding, one_hot, interpolate...

(reference: python/paddle/nn/functional/common.py, input.py)
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core import dtypes
from ...core.amp import autocast_inputs
from ...core.random import next_key
from ...core.tensor import Tensor, apply
from ...tensor.creation import _t


def linear(x, weight, bias=None, name=None):
    # paddle weight layout: [in_features, out_features] → x @ W + b, one MXU matmul
    if bias is not None:
        def f(a, w, b):
            a, w, b = autocast_inputs("linear", a, w, b)
            return jnp.matmul(a, w) + b
        return apply(f, _t(x), _t(weight), _t(bias))

    def f(a, w):
        a, w = autocast_inputs("linear", a, w)
        return jnp.matmul(a, w)
    return apply(f, _t(x), _t(weight))


def dropout(x, p=0.5, axis=None, training=True, mode="upscale_in_train",
            name=None):
    x = _t(x)
    if not training or p == 0.0:
        if mode == "downscale_in_infer" and not training:
            return apply(lambda a: a * (1 - p), x)
        return x
    if p == 1.0:
        return apply(lambda a: jnp.zeros_like(a), x)
    shape = list(x.data.shape)
    if axis is not None:
        axes = [axis] if isinstance(axis, int) else list(axis)
        shape = [s if i in axes else 1 for i, s in enumerate(shape)]
    keep = jax.random.bernoulli(next_key(), 1.0 - p, tuple(shape))

    def f(a):
        if mode == "upscale_in_train":
            return jnp.where(keep, a / (1.0 - p), 0.0).astype(a.dtype)
        return jnp.where(keep, a, 0.0).astype(a.dtype)

    return apply(f, x)


def dropout2d(x, p=0.5, training=True, data_format="NCHW", name=None):
    axis = [0, 1] if data_format == "NCHW" else [0, 3]
    return dropout(x, p, axis=axis, training=training)


def dropout3d(x, p=0.5, training=True, data_format="NCDHW", name=None):
    axis = [0, 1] if data_format == "NCDHW" else [0, 4]
    return dropout(x, p, axis=axis, training=training)


def alpha_dropout(x, p=0.5, training=True, name=None):
    x = _t(x)
    if not training or p == 0.0:
        return x
    alpha = 1.6732632423543772
    scale = 1.0507009873554805
    alpha_p = -alpha * scale
    keep = jax.random.bernoulli(next_key(), 1.0 - p, tuple(x.data.shape))
    a_coef = ((1 - p) * (1 + p * alpha_p ** 2)) ** -0.5
    b_coef = -a_coef * p * alpha_p

    def f(a):
        return (a_coef * jnp.where(keep, a, alpha_p) + b_coef).astype(a.dtype)

    return apply(f, x)


def embedding(x, weight, padding_idx=None, sparse=False, name=None):
    x, weight = _t(x), _t(weight)

    def f(ids, w):
        out = jnp.take(w, ids.astype(jnp.int32), axis=0)
        if padding_idx is not None:
            mask = (ids == padding_idx)[..., None]
            out = jnp.where(mask, 0.0, out)
        return out

    from ...core import tensor as _ct
    if (sparse and _ct.is_grad_enabled() and not weight.stop_gradient
            and weight._node is None and not _ct._is_tracer(weight.data)):
        # lookup_table_grad is_sparse=True analog: the backward emits a
        # SelectedRows (rows=ids, values=cotangent) instead of scattering
        # into a dense [V, H] buffer. Only for leaf weights — a derived
        # weight needs a dense cotangent flowing further up the tape.
        from ...core.selected_rows import SelectedRows
        ids_arr = x.data
        V = weight.data.shape[0]
        out_arr = f(ids_arr, weight.data)

        def sparse_vjp(cot):
            vals = cot.reshape(-1, cot.shape[-1])
            rows = ids_arr.reshape(-1).astype(jnp.int32)
            if padding_idx is not None:
                vals = jnp.where((rows == padding_idx)[:, None], 0.0, vals)
            return (SelectedRows(rows, vals, V),)

        out_t = Tensor(out_arr, stop_gradient=False)
        _ct._STATE.seq += 1
        node = _ct._Node(sparse_vjp, [weight], [out_t], single=True,
                         seq=_ct._STATE.seq)
        out_t._node = node
        out_t._out_index = 0
        return out_t

    return apply(f, x, weight)


def one_hot(x, num_classes, name=None):
    return apply(lambda a: jax.nn.one_hot(a.astype(jnp.int32), num_classes,
                                          dtype=dtypes.get_default_dtype()),
                 _t(x))


def label_smooth(label, prior_dist=None, epsilon=0.1, name=None):
    label = _t(label)

    def f(y, *pd):
        k = y.shape[-1]
        if pd:
            return (1 - epsilon) * y + epsilon * pd[0]
        return (1 - epsilon) * y + epsilon / k

    if prior_dist is not None:
        return apply(f, label, _t(prior_dist))
    return apply(f, label)


def interpolate(x, size=None, scale_factor=None, mode="nearest",
                align_corners=False, align_mode=0, data_format="NCHW",
                name=None):
    x = _t(x)
    channel_last = data_format[-1] == "C"
    nd = x.data.ndim - 2
    spatial = (x.data.shape[1:-1] if channel_last else x.data.shape[2:])
    if size is None:
        if isinstance(scale_factor, (int, float)):
            scale_factor = [scale_factor] * nd
        size = [int(s * f) for s, f in zip(spatial, scale_factor)]
    else:
        if isinstance(size, Tensor):
            size = [int(v) for v in size.numpy()]
        size = [int(s.item()) if isinstance(s, Tensor) else int(s) for s in size]

    jmode = {"nearest": "nearest", "bilinear": "linear", "linear": "linear",
             "trilinear": "linear", "bicubic": "cubic", "area": "linear"}[mode]

    def _cubic_axis(out, ax, s_out, corners):
        """Separable Keys-cubic (a = -0.75, the paddle/torch/OpenCV bicubic
        convention — bicubic_interp_v2_op uses the same kernel; jax.image's
        'cubic' is Catmull-Rom a = -0.5, which differs by ~0.4%)."""
        A = -0.75
        s_in = out.shape[ax]
        if corners:
            # out size 1 under align_corners maps to source index 0 (ratio
            # is defined as 0 when out==1 in bicubic_interp_v2), not to the
            # half-pixel window center
            src = jnp.arange(s_out, dtype=jnp.float32) * (s_in - 1) \
                / max(s_out - 1, 1)
        else:
            src = (jnp.arange(s_out, dtype=jnp.float32) + 0.5) \
                * (s_in / s_out) - 0.5
        s0 = jnp.floor(src).astype(jnp.int32)
        t = (src - s0).astype(out.dtype)

        def k(d):
            ad = jnp.abs(d)
            return jnp.where(
                ad <= 1.0, ((A + 2) * ad - (A + 3)) * ad * ad + 1,
                jnp.where(ad < 2.0,
                          ((A * ad - 5 * A) * ad + 8 * A) * ad - 4 * A,
                          0.0))

        acc = 0
        for off in (-1, 0, 1, 2):
            idx = jnp.clip(s0 + off, 0, s_in - 1)
            w = k(t - off)
            shape = [1] * out.ndim
            shape[ax] = s_out
            acc = acc + jnp.take(out, idx, axis=ax) * w.reshape(shape)
        return acc

    def f(a):
        if channel_last:
            out_shape = (a.shape[0],) + tuple(size) + (a.shape[-1],)
        else:
            out_shape = a.shape[:2] + tuple(size)
        if jmode == "nearest":
            # jax.image nearest matches paddle align_corners=False
            return jax.image.resize(a, out_shape, method="nearest")
        spatial_axes_all = (list(range(1, 1 + nd)) if channel_last
                            else list(range(2, 2 + nd)))
        if jmode == "cubic":
            out = a
            for ax, s_out in zip(spatial_axes_all, size):
                out = _cubic_axis(out, ax, s_out, align_corners)
            return out
        if align_corners:
            # build index grid with corner alignment, gather per spatial dim
            out = a
            spatial_axes = (list(range(1, 1 + nd)) if channel_last
                            else list(range(2, 2 + nd)))
            for ax, s_out in zip(spatial_axes, size):
                s_in = out.shape[ax]
                if s_out == 1:
                    idx = jnp.zeros((1,), jnp.float32)
                else:
                    idx = jnp.linspace(0.0, s_in - 1, s_out)
                lo = jnp.floor(idx).astype(jnp.int32)
                hi = jnp.minimum(lo + 1, s_in - 1)
                wgt = (idx - lo).astype(a.dtype)
                shape = [1] * out.ndim
                shape[ax] = s_out
                wgt = wgt.reshape(shape)
                out = (jnp.take(out, lo, axis=ax) * (1 - wgt)
                       + jnp.take(out, hi, axis=ax) * wgt)
            return out
        return jax.image.resize(a, out_shape, method=jmode)

    return apply(f, x)


def upsample(x, size=None, scale_factor=None, mode="nearest",
             align_corners=False, align_mode=0, data_format="NCHW", name=None):
    return interpolate(x, size, scale_factor, mode, align_corners, align_mode,
                       data_format)


def pixel_shuffle(x, upscale_factor, data_format="NCHW", name=None):
    r = upscale_factor

    def f(a):
        if data_format == "NCHW":
            N, C, H, W = a.shape
            out = a.reshape(N, C // (r * r), r, r, H, W)
            out = jnp.transpose(out, (0, 1, 4, 2, 5, 3))
            return out.reshape(N, C // (r * r), H * r, W * r)
        N, H, W, C = a.shape
        out = a.reshape(N, H, W, r, r, C // (r * r))
        out = jnp.transpose(out, (0, 1, 3, 2, 4, 5))
        return out.reshape(N, H * r, W * r, C // (r * r))

    return apply(f, _t(x))


def pixel_unshuffle(x, downscale_factor, data_format="NCHW", name=None):
    r = downscale_factor

    def f(a):
        if data_format == "NCHW":
            N, C, H, W = a.shape
            out = a.reshape(N, C, H // r, r, W // r, r)
            out = jnp.transpose(out, (0, 1, 3, 5, 2, 4))
            return out.reshape(N, C * r * r, H // r, W // r)
        N, H, W, C = a.shape
        out = a.reshape(N, H // r, r, W // r, r, C)
        out = jnp.transpose(out, (0, 2, 4, 1, 3, 5)).reshape(
            N, H // r, W // r, C * r * r)
        return out

    return apply(f, _t(x))


def channel_shuffle(x, groups, data_format="NCHW", name=None):
    def f(a):
        if data_format == "NCHW":
            N, C, H, W = a.shape
            out = a.reshape(N, groups, C // groups, H, W)
            return jnp.swapaxes(out, 1, 2).reshape(N, C, H, W)
        N, H, W, C = a.shape
        out = a.reshape(N, H, W, groups, C // groups)
        return jnp.swapaxes(out, 3, 4).reshape(N, H, W, C)

    return apply(f, _t(x))


def cosine_similarity(x1, x2, axis=1, eps=1e-8):
    return apply(
        lambda a, b: jnp.sum(a * b, axis=axis) / jnp.maximum(
            jnp.linalg.norm(a, axis=axis) * jnp.linalg.norm(b, axis=axis), eps),
        _t(x1), _t(x2))


def bilinear(x1, x2, weight, bias=None, name=None):
    args = [_t(x1), _t(x2), _t(weight)]

    def f(a, b, w, *maybe_bias):
        out = jnp.einsum("bi,oij,bj->bo", a, w, b)
        if maybe_bias:
            out = out + maybe_bias[0]
        return out

    if bias is not None:
        args.append(_t(bias))
    return apply(f, *args)


def temporal_shift(x, seg_num, shift_ratio=0.25, data_format="NCHW", name=None):
    def f(a):
        N_T, C, H, W = a.shape
        a5 = a.reshape(-1, seg_num, C, H, W)
        fold = int(C * shift_ratio)
        left = jnp.pad(a5[:, 1:, :fold], ((0, 0), (0, 1), (0, 0), (0, 0), (0, 0)))
        right = jnp.pad(a5[:, :-1, fold:2 * fold],
                        ((0, 0), (1, 0), (0, 0), (0, 0), (0, 0)))
        rest = a5[:, :, 2 * fold:]
        return jnp.concatenate([left, right, rest], axis=2).reshape(N_T, C, H, W)

    return apply(f, _t(x))


def sequence_mask(x, maxlen=None, dtype="int64", name=None):
    """sequence_mask op: lengths [..] -> mask [.., maxlen]."""
    x = _t(x)
    from ...core import dtypes as _d

    def f(lens):
        m = maxlen if maxlen is not None else int(lens.max())
        ar = jnp.arange(m)
        return (ar[None, :] < lens.reshape(-1, 1)).reshape(
            *lens.shape, m).astype(_d.convert_dtype(dtype))

    return apply(f, x)


def diag_embed(input, offset=0, dim1=-2, dim2=-1, name=None):
    """diag_embed op: place the last dim on a diagonal plane (dim1, dim2)."""
    x = _t(input)

    def f(a):
        n = a.shape[-1]
        size = n + abs(offset)
        out = jnp.zeros(a.shape[:-1] + (size, size), a.dtype)
        idx = jnp.arange(n)
        r = idx + max(-offset, 0)
        c = idx + max(offset, 0)
        out = out.at[..., r, c].set(a)
        d1 = dim1 % out.ndim
        d2 = dim2 % out.ndim
        # the ROW axis goes to dim1 and the COLUMN axis to dim2: swapped
        # dims transpose the plane (sub- vs super-diagonal for offset != 0)
        return jnp.moveaxis(out, (-2, -1), (d1, d2))

    return apply(f, x)


def affine_grid(theta, out_shape, align_corners=True, name=None):
    """affine_grid_op.cc: theta [N,2,3] -> sampling grid [N,H,W,2] in
    normalized [-1,1] coords."""
    import numpy as np
    theta = _t(theta)
    N, C, H, W = [int(s) for s in (
        out_shape if not isinstance(out_shape, Tensor)
        else np.asarray(out_shape.data))]

    def f(th):
        if align_corners:
            ys = jnp.linspace(-1.0, 1.0, H)
            xs = jnp.linspace(-1.0, 1.0, W)
        else:
            ys = (jnp.arange(H) * 2 + 1) / H - 1.0
            xs = (jnp.arange(W) * 2 + 1) / W - 1.0
        gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
        ones = jnp.ones_like(gx)
        base = jnp.stack([gx, gy, ones], axis=-1)      # [H,W,3]
        return jnp.einsum("hwk,nck->nhwc", base, th)   # [N,H,W,2]

    return apply(f, theta)


def grid_sample(x, grid, mode="bilinear", padding_mode="zeros",
                align_corners=True, name=None):
    """grid_sample_op.cc: sample x [N,C,H,W] at grid [N,Ho,Wo,2]
    (normalized [-1,1] xy)."""
    x = _t(x)
    grid = _t(grid)

    if padding_mode not in ("zeros", "border", "reflection"):
        raise ValueError(f"unsupported padding_mode {padding_mode!r}")
    if mode not in ("bilinear", "nearest"):
        raise ValueError(f"unsupported mode {mode!r}")

    def f(img, g):
        N, C, H, W = img.shape

        def unnorm(coord, size):
            if align_corners:
                return (coord + 1) * (size - 1) / 2
            return ((coord + 1) * size - 1) / 2

        def reflect(coord, size):
            if size == 1:
                return jnp.zeros_like(coord)
            if align_corners:  # reflect over [0, size-1]
                period = 2.0 * (size - 1)
                c = jnp.abs(coord) % period
                return jnp.where(c > size - 1, period - c, c)
            # reflect over [-0.5, size-0.5]
            period = 2.0 * size
            c = jnp.abs(coord + 0.5) % period
            c = jnp.where(c > size, period - c, c) - 0.5
            return jnp.clip(c, 0, size - 1)

        gx = unnorm(g[..., 0], W)
        gy = unnorm(g[..., 1], H)
        if padding_mode == "border":
            gx = jnp.clip(gx, 0, W - 1)
            gy = jnp.clip(gy, 0, H - 1)
        elif padding_mode == "reflection":
            gx = reflect(gx, W)
            gy = reflect(gy, H)
        if mode == "nearest":
            xi = jnp.clip(jnp.round(gx).astype(jnp.int32), 0, W - 1)
            yi = jnp.clip(jnp.round(gy).astype(jnp.int32), 0, H - 1)
            out = jax.vmap(lambda im, yy, xx: im[:, yy, xx])(img, yi, xi)
            if padding_mode == "zeros":
                inb = ((gx >= 0) & (gx <= W - 1) & (gy >= 0)
                       & (gy <= H - 1))
                out = out * inb[:, None]
            return out

        x0 = jnp.floor(gx)
        y0 = jnp.floor(gy)
        wx1 = gx - x0
        wy1 = gy - y0

        def tap(im, yy, xx):
            yi = jnp.clip(yy.astype(jnp.int32), 0, H - 1)
            xi = jnp.clip(xx.astype(jnp.int32), 0, W - 1)
            v = im[:, yi, xi]                      # [C, Ho, Wo]
            if padding_mode == "zeros":
                inb = ((xx >= 0) & (xx <= W - 1) & (yy >= 0)
                       & (yy <= H - 1))
                v = v * inb[None]
            return v

        def one(im, y0_, x0_, wy, wx):
            v00 = tap(im, y0_, x0_)
            v01 = tap(im, y0_, x0_ + 1)
            v10 = tap(im, y0_ + 1, x0_)
            v11 = tap(im, y0_ + 1, x0_ + 1)
            return (v00 * ((1 - wy) * (1 - wx))[None]
                    + v01 * ((1 - wy) * wx)[None]
                    + v10 * (wy * (1 - wx))[None]
                    + v11 * (wy * wx)[None])

        return jax.vmap(one)(img, y0, x0, wy1, wx1)

    return apply(f, x, grid)


def gather_tree(ids, parents):
    """gather_tree_op.cc: beam-search back-tracing. ids/parents
    [T, B, beam] -> full sequences [T, B, beam]."""
    ids = _t(ids)
    parents = _t(parents)

    def f(i, p):
        T = i.shape[0]

        def body(carry, t):
            beam_idx = carry                      # [B, beam]
            step_ids = jnp.take_along_axis(i[t], beam_idx, axis=-1)
            parent = jnp.take_along_axis(p[t], beam_idx, axis=-1)
            return parent, step_ids

        init = jnp.broadcast_to(
            jnp.arange(i.shape[2])[None, :], i.shape[1:]).astype(i.dtype)
        _, rev = jax.lax.scan(body, init, jnp.arange(T - 1, -1, -1))
        return rev[::-1]

    return apply(f, ids, parents)


def add_position_encoding(input, alpha=1.0, beta=1.0, name=None):
    """add_position_encoding_op: out = alpha*x + beta*sinusoid(pos, dim)
    over [B, S, D] (even dims sin, odd dims cos, Transformer convention)."""
    x = _t(input)

    def f(a):
        B, S, D = a.shape
        half = D // 2
        pos = jnp.arange(S, dtype=jnp.float32)[:, None]
        div = jnp.power(10000.0, jnp.arange(half, dtype=jnp.float32)
                        / max(half, 1))
        angles = pos / div[None, :]          # [S, D/2]
        enc = jnp.zeros((S, D), jnp.float32)
        enc = enc.at[:, :half].set(jnp.sin(angles))
        enc = enc.at[:, half:2 * half].set(jnp.cos(angles))
        return (alpha * a.astype(jnp.float32)
                + beta * enc[None]).astype(a.dtype)

    return apply(f, x)


def edit_distance(input, label, input_length=None, label_length=None,
                  normalized=True, name=None):
    """edit_distance_op: per-pair Levenshtein distance between token
    sequences. input/label [B, S*] int (padded); lengths select the live
    prefix. Host-side eager op (the reference kernel is CPU-only too).
    Returns (distance [B, 1] float, sequence_num [1])."""
    import numpy as np_
    a = np_.asarray(_t(input).data)
    b = np_.asarray(_t(label).data)
    B = a.shape[0]
    la = (np_.asarray(_t(input_length).data).astype(np_.int64)
          if input_length is not None
          else np_.full((B,), a.shape[1], np_.int64))
    lb = (np_.asarray(_t(label_length).data).astype(np_.int64)
          if label_length is not None
          else np_.full((B,), b.shape[1], np_.int64))
    out = np_.zeros((B, 1), np_.float32)
    for i in range(B):
        s, t = a[i, :la[i]], b[i, :lb[i]]
        m, n = len(s), len(t)
        dp = np_.arange(n + 1, dtype=np_.int64)
        for r in range(1, m + 1):
            prev_diag = dp[0]
            dp[0] = r
            for c in range(1, n + 1):
                cur = dp[c]
                dp[c] = min(dp[c] + 1, dp[c - 1] + 1,
                            prev_diag + (0 if s[r - 1] == t[c - 1] else 1))
                prev_diag = cur
        d = float(dp[n])
        if normalized:
            d = d / max(float(n), 1.0)
        out[i, 0] = d
    from ...tensor.creation import to_tensor
    return to_tensor(out), to_tensor(np_.asarray([B], np_.int64))
