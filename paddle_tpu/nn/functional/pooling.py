"""Pooling functionals via lax.reduce_window (reference: operators/pool_op.*)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...core.tensor import apply
from ...tensor.creation import _t
from .conv import _norm_tuple, _padding


def _pool(x, fn, init, kernel, stride, padding, n, data_format, ceil_mode=False,
          average=False, exclusive=True, divisor_override=None):
    channel_last = data_format in ("NHWC", "NLC", "NDHWC")
    ks = _norm_tuple(kernel, n)
    st = _norm_tuple(stride if stride is not None else kernel, n)
    pad = _padding(padding, n)
    if ceil_mode and isinstance(pad, str) and pad.upper() == "VALID":
        raise ValueError(
            'When padding is "VALID", ceil_mode must be False '
            "(reference pooling contract)")

    def f(a):
        nd = a.ndim
        if channel_last:
            window = (1,) + ks + (1,)
            strides = (1,) + st + (1,)
            pads = pad if isinstance(pad, str) else [(0, 0)] + list(pad) + [(0, 0)]
        else:
            window = (1, 1) + ks
            strides = (1, 1) + st
            pads = pad if isinstance(pad, str) else [(0, 0), (0, 0)] + list(pad)
        if isinstance(pads, str):
            pads = jax.lax.padtype_to_pads(a.shape, window, strides, pads)
        if ceil_mode:
            # pool_op ceil formula: out = ceil((in + pads - k) / s) + 1 —
            # extend the high-side pad so the trailing partial window is
            # emitted; reduce_window pads with `init` (the identity), so
            # max stays -inf-padded and avg's exclusive counts stay true
            pads = list(pads)
            for dim in range(nd):
                k, s_ = window[dim], strides[dim]
                if k == 1 and s_ == 1:
                    continue
                lo, hi = pads[dim]
                span = a.shape[dim] + lo + hi
                out_floor = (span - k) // s_ + 1
                out_ceil = -((span - k) // -s_) + 1
                # caffe/paddle clamp: the last window must START inside
                # input + left pad — a window lying entirely in padding
                # would produce -inf (max) or 0/0 = NaN (exclusive avg)
                if (out_ceil - 1) * s_ >= a.shape[dim] + lo:
                    out_ceil -= 1
                if out_ceil > out_floor:
                    pads[dim] = (lo, hi + (out_ceil - 1) * s_ + k - span)
        out = jax.lax.reduce_window(a, init, fn, window, strides, pads)
        if average:
            if divisor_override is not None:
                out = out / float(divisor_override)
            elif exclusive and any(p != (0, 0) for p in pads):
                ones = jnp.ones_like(a)
                counts = jax.lax.reduce_window(ones, 0.0, jax.lax.add, window,
                                               strides, pads)
                out = out / counts
            else:
                out = out / float(np.prod(ks))
        return out

    return apply(f, _t(x))


def _max_pool_mask(x, kernel, stride, padding, n):
    """Max pool that also returns the argmax as flat indices into the
    flattened input spatial volume per (N, C) — pool_with_index_op.cc's
    MaxPoolWithIndex contract (what max_unpool consumes). NC*-layout only,
    matching the reference kernel. Windows are materialized per kernel
    offset (K = prod(kernel) slices, K is small and static), the
    TPU-friendly alternative to a scatter-per-window argmax."""
    ks = _norm_tuple(kernel, n)
    st = _norm_tuple(stride if stride is not None else kernel, n)
    pad = _padding(padding, n)
    if isinstance(pad, str):
        raise ValueError("return_mask does not support string padding")

    def f(a):
        spatial = a.shape[2:]
        neg = jnp.finfo(a.dtype).min if jnp.issubdtype(a.dtype, jnp.floating) \
            else jnp.iinfo(a.dtype).min
        a_pad = jnp.pad(a, [(0, 0), (0, 0)] + list(pad),
                        constant_values=neg)
        out_sizes = tuple(
            (spatial[d] + sum(pad[d]) - ks[d]) // st[d] + 1
            for d in range(n))
        vals, flats = [], []
        for offs in np.ndindex(*ks):
            sl = [slice(None), slice(None)]
            for d in range(n):
                sl.append(slice(offs[d],
                                offs[d] + (out_sizes[d] - 1) * st[d] + 1,
                                st[d]))
            vals.append(a_pad[tuple(sl)])
            # flat index of this window position in the UNPADDED volume;
            # padded (out-of-range) cells never win (value is dtype-min)
            flat = jnp.zeros(out_sizes, jnp.int32)
            for d in range(n):
                coord = (jnp.arange(out_sizes[d]) * st[d] - pad[d][0]
                         + offs[d]).astype(jnp.int32)
                coord = coord.reshape((-1,) + (1,) * (n - 1 - d))
                flat = flat * spatial[d] + coord
            flats.append(jnp.broadcast_to(flat, out_sizes))
        stack_v = jnp.stack(vals, axis=2)       # [B, C, K, *out]
        stack_i = jnp.stack(flats, axis=0)      # [K, *out]
        best = jnp.argmax(stack_v, axis=2)      # [B, C, *out]
        out = jnp.max(stack_v, axis=2)
        mask = jnp.take_along_axis(
            jnp.broadcast_to(stack_i[None, None],
                             out.shape[:2] + stack_i.shape),
            best[:, :, None], axis=2)[:, :, 0].astype(jnp.int32)
        return out, mask

    return apply(f, _t(x))


def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, name=None):
    if return_mask:
        if ceil_mode:
            raise NotImplementedError("return_mask with ceil_mode")
        return _max_pool_mask(x, kernel_size, stride, padding, 1)
    return _pool(x, jax.lax.max, -jnp.inf, kernel_size, stride, padding, 1, "NCL",
                 ceil_mode)


def max_pool2d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCHW", name=None):
    if return_mask:
        if ceil_mode or data_format != "NCHW":
            raise NotImplementedError(
                "return_mask supports NCHW floor-mode only "
                "(pool_with_index_op.cc parity)")
        return _max_pool_mask(x, kernel_size, stride, padding, 2)
    return _pool(x, jax.lax.max, -jnp.inf, kernel_size, stride, padding, 2,
                 data_format, ceil_mode)


def max_pool3d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCDHW", name=None):
    if return_mask:
        if ceil_mode or data_format != "NCDHW":
            raise NotImplementedError(
                "return_mask supports NCDHW floor-mode only")
        return _max_pool_mask(x, kernel_size, stride, padding, 3)
    return _pool(x, jax.lax.max, -jnp.inf, kernel_size, stride, padding, 3,
                 data_format, ceil_mode)


def _max_unpool(x, indices, kernel, stride, padding, n, output_size):
    """Inverse of max_pool*(return_mask=True): scatter each pooled value
    back to its argmax position (unpool_op.cc Unpool2dMax). indices are
    flat positions in the output spatial volume."""
    ks = _norm_tuple(kernel, n)
    st = _norm_tuple(stride if stride is not None else kernel, n)
    pad = _padding(padding, n)
    if isinstance(pad, str):
        raise ValueError("max_unpool does not support string padding")

    def f(a, idx):
        spatial_in = a.shape[2:]
        if output_size is not None:
            spatial_out = tuple(output_size[-n:])
        else:
            spatial_out = tuple(
                (spatial_in[d] - 1) * st[d] - pad[d][0] - pad[d][1] + ks[d]
                for d in range(n))
        B, C = a.shape[:2]
        flat_n = int(np.prod(spatial_in))
        flat_out = int(np.prod(spatial_out))
        v = a.reshape(B * C, flat_n)
        i = idx.reshape(B * C, flat_n)
        rows = jnp.arange(B * C)[:, None]
        out = jnp.zeros((B * C, flat_out), a.dtype).at[rows, i].set(v)
        return out.reshape((B, C) + spatial_out)

    return apply(f, _t(x), _t(indices))


def max_unpool1d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCL", output_size=None, name=None):
    if data_format != "NCL":
        raise NotImplementedError("max_unpool1d supports NCL only")
    return _max_unpool(x, indices, kernel_size, stride, padding, 1,
                       output_size)


def max_unpool2d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCHW", output_size=None, name=None):
    if data_format != "NCHW":
        raise NotImplementedError("max_unpool2d supports NCHW only")
    return _max_unpool(x, indices, kernel_size, stride, padding, 2,
                       output_size)


def max_unpool3d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCDHW", output_size=None, name=None):
    if data_format != "NCDHW":
        raise NotImplementedError("max_unpool3d supports NCDHW only")
    return _max_unpool(x, indices, kernel_size, stride, padding, 3,
                       output_size)


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, name=None):
    return _pool(x, jax.lax.add, 0.0, kernel_size, stride, padding, 1, "NCL",
                 ceil_mode, average=True, exclusive=exclusive)


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCHW",
               name=None):
    return _pool(x, jax.lax.add, 0.0, kernel_size, stride, padding, 2,
                 data_format, ceil_mode, average=True, exclusive=exclusive,
                 divisor_override=divisor_override)


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCDHW",
               name=None):
    return _pool(x, jax.lax.add, 0.0, kernel_size, stride, padding, 3,
                 data_format, ceil_mode, average=True, exclusive=exclusive,
                 divisor_override=divisor_override)


def _adaptive_axes(in_size, out_size):
    # split each spatial dim into out_size nearly-equal windows
    return [(int(np.floor(i * in_size / out_size)),
             int(np.ceil((i + 1) * in_size / out_size))) for i in range(out_size)]


def _adaptive_pool(x, output_size, n, reduce_fn, data_format):
    channel_last = data_format in ("NHWC", "NLC", "NDHWC")
    out_sizes = _norm_tuple(output_size, n)

    def f(a):
        spatial_start = 1 if channel_last else 2
        out = a
        for d in range(n):
            ax = spatial_start + d
            in_size = a.shape[ax]
            o = out_sizes[d]
            if o is None:
                continue
            if in_size % o == 0:
                # even split: reshape + reduce (fast path, static)
                k = in_size // o
                new_shape = out.shape[:ax] + (o, k) + out.shape[ax + 1:]
                out = reduce_fn(out.reshape(new_shape), axis=ax + 1)
            else:
                segs = _adaptive_axes(in_size, o)
                pieces = [reduce_fn(jax.lax.slice_in_dim(out, s, e, axis=ax),
                                    axis=ax, keepdims=True) for s, e in segs]
                out = jnp.concatenate(pieces, axis=ax)
        return out

    return apply(f, _t(x))


def adaptive_avg_pool1d(x, output_size, name=None):
    return _adaptive_pool(x, output_size, 1, jnp.mean, "NCL")


def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    return _adaptive_pool(x, output_size, 2, jnp.mean, data_format)


def adaptive_avg_pool3d(x, output_size, data_format="NCDHW", name=None):
    return _adaptive_pool(x, output_size, 3, jnp.mean, data_format)


def _adaptive_max_pool_mask(x, output_size, n):
    """Adaptive max pool returning (out, mask) with mask = flat argmax
    into the input spatial volume (max_pool*_with_index adaptive mode).
    NC*-layout; output grids are small and static, so the per-cell slice
    loop stays a fixed set of fused XLA ops."""
    sizes = _norm_tuple(output_size, n)

    def f(a):
        spatial = a.shape[2:]
        segs = [_adaptive_axes(spatial[d], sizes[d]) for d in range(n)]
        outs, masks = [], []
        for cell in np.ndindex(*sizes):
            sl = [slice(None), slice(None)]
            starts = []
            for d in range(n):
                s0, e0 = segs[d][cell[d]]
                sl.append(slice(s0, e0))
                starts.append(s0)
            win = a[tuple(sl)]
            w_spatial = win.shape[2:]
            flat = win.reshape(win.shape[0], win.shape[1], -1)
            best = jnp.argmax(flat, axis=-1)
            outs.append(jnp.max(flat, axis=-1))
            # local flat idx -> global flat idx over the input volume
            g = jnp.zeros_like(best)
            rem = best
            for d in range(n - 1, -1, -1):
                coord = rem % w_spatial[d] + starts[d]
                rem = rem // w_spatial[d]
                mult = 1
                for dd in range(d + 1, n):
                    mult *= spatial[dd]
                g = g + coord * mult
            masks.append(g.astype(jnp.int32))
        out = jnp.stack(outs, axis=-1).reshape(a.shape[:2] + sizes)
        mask = jnp.stack(masks, axis=-1).reshape(a.shape[:2] + sizes)
        return out, mask

    return apply(f, _t(x))


def adaptive_max_pool1d(x, output_size, return_mask=False, name=None):
    if return_mask:
        return _adaptive_max_pool_mask(x, output_size, 1)
    return _adaptive_pool(x, output_size, 1, jnp.max, "NCL")


def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    if return_mask:
        return _adaptive_max_pool_mask(x, output_size, 2)
    return _adaptive_pool(x, output_size, 2, jnp.max, "NCHW")


def adaptive_max_pool3d(x, output_size, return_mask=False, name=None):
    if return_mask:
        return _adaptive_max_pool_mask(x, output_size, 3)
    return _adaptive_pool(x, output_size, 3, jnp.max, "NCDHW")
