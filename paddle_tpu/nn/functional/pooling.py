"""Pooling functionals via lax.reduce_window (reference: operators/pool_op.*)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...core.tensor import apply
from ...tensor.creation import _t
from .conv import _norm_tuple, _padding


def _pool(x, fn, init, kernel, stride, padding, n, data_format, ceil_mode=False,
          average=False, exclusive=True):
    channel_last = data_format in ("NHWC", "NLC", "NDHWC")
    ks = _norm_tuple(kernel, n)
    st = _norm_tuple(stride if stride is not None else kernel, n)
    pad = _padding(padding, n)

    def f(a):
        nd = a.ndim
        if channel_last:
            window = (1,) + ks + (1,)
            strides = (1,) + st + (1,)
            pads = pad if isinstance(pad, str) else [(0, 0)] + list(pad) + [(0, 0)]
        else:
            window = (1, 1) + ks
            strides = (1, 1) + st
            pads = pad if isinstance(pad, str) else [(0, 0), (0, 0)] + list(pad)
        if isinstance(pads, str):
            pads = jax.lax.padtype_to_pads(a.shape, window, strides, pads)
        out = jax.lax.reduce_window(a, init, fn, window, strides, pads)
        if average:
            if exclusive and any(p != (0, 0) for p in pads):
                ones = jnp.ones_like(a)
                counts = jax.lax.reduce_window(ones, 0.0, jax.lax.add, window,
                                               strides, pads)
                out = out / counts
            else:
                out = out / float(np.prod(ks))
        return out

    return apply(f, _t(x))


def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, name=None):
    return _pool(x, jax.lax.max, -jnp.inf, kernel_size, stride, padding, 1, "NCL",
                 ceil_mode)


def max_pool2d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCHW", name=None):
    return _pool(x, jax.lax.max, -jnp.inf, kernel_size, stride, padding, 2,
                 data_format, ceil_mode)


def max_pool3d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCDHW", name=None):
    return _pool(x, jax.lax.max, -jnp.inf, kernel_size, stride, padding, 3,
                 data_format, ceil_mode)


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, name=None):
    return _pool(x, jax.lax.add, 0.0, kernel_size, stride, padding, 1, "NCL",
                 ceil_mode, average=True, exclusive=exclusive)


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCHW",
               name=None):
    return _pool(x, jax.lax.add, 0.0, kernel_size, stride, padding, 2,
                 data_format, ceil_mode, average=True, exclusive=exclusive)


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCDHW",
               name=None):
    return _pool(x, jax.lax.add, 0.0, kernel_size, stride, padding, 3,
                 data_format, ceil_mode, average=True, exclusive=exclusive)


def _adaptive_axes(in_size, out_size):
    # split each spatial dim into out_size nearly-equal windows
    return [(int(np.floor(i * in_size / out_size)),
             int(np.ceil((i + 1) * in_size / out_size))) for i in range(out_size)]


def _adaptive_pool(x, output_size, n, reduce_fn, data_format):
    channel_last = data_format in ("NHWC", "NLC", "NDHWC")
    out_sizes = _norm_tuple(output_size, n)

    def f(a):
        spatial_start = 1 if channel_last else 2
        out = a
        for d in range(n):
            ax = spatial_start + d
            in_size = a.shape[ax]
            o = out_sizes[d]
            if o is None:
                continue
            if in_size % o == 0:
                # even split: reshape + reduce (fast path, static)
                k = in_size // o
                new_shape = out.shape[:ax] + (o, k) + out.shape[ax + 1:]
                out = reduce_fn(out.reshape(new_shape), axis=ax + 1)
            else:
                segs = _adaptive_axes(in_size, o)
                pieces = [reduce_fn(jax.lax.slice_in_dim(out, s, e, axis=ax),
                                    axis=ax, keepdims=True) for s, e in segs]
                out = jnp.concatenate(pieces, axis=ax)
        return out

    return apply(f, _t(x))


def adaptive_avg_pool1d(x, output_size, name=None):
    return _adaptive_pool(x, output_size, 1, jnp.mean, "NCL")


def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    return _adaptive_pool(x, output_size, 2, jnp.mean, data_format)


def adaptive_avg_pool3d(x, output_size, data_format="NCDHW", name=None):
    return _adaptive_pool(x, output_size, 3, jnp.mean, data_format)


def adaptive_max_pool1d(x, output_size, return_mask=False, name=None):
    return _adaptive_pool(x, output_size, 1, jnp.max, "NCL")


def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    return _adaptive_pool(x, output_size, 2, jnp.max, "NCHW")


def adaptive_max_pool3d(x, output_size, return_mask=False, name=None):
    return _adaptive_pool(x, output_size, 3, jnp.max, "NCDHW")
