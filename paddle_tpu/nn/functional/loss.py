"""Loss functionals (reference: python/paddle/nn/functional/loss.py).

cross_entropy computes logsumexp in fp32 — bf16-safe on TPU.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core.tensor import Tensor, apply
from ...tensor.creation import _t


def _reduce(out, reduction):
    if reduction == "mean":
        return jnp.mean(out)
    if reduction == "sum":
        return jnp.sum(out)
    return out


def cross_entropy(input, label, weight=None, ignore_index=-100, reduction="mean",
                  soft_label=False, axis=-1, use_softmax=True,
                  label_smoothing=0.0, name=None):
    input, label = _t(input), _t(label)

    def f(logits, lab, *maybe_w):
        h = logits.astype(jnp.float32)
        if use_softmax:
            logp = jax.nn.log_softmax(h, axis=axis)
        else:
            logp = jnp.log(jnp.maximum(h, 1e-30))
        n_classes = logits.shape[axis]
        if soft_label:
            soft = lab.astype(jnp.float32)
            loss = -jnp.sum(soft * logp, axis=axis)
        else:
            li = lab.astype(jnp.int32)
            squeeze_last = (li.ndim == logp.ndim and li.shape[-1] == 1)
            if squeeze_last:
                li = li[..., 0]
            if label_smoothing > 0.0:
                onehot = jax.nn.one_hot(li, n_classes, axis=axis,
                                        dtype=jnp.float32)
                soft = onehot * (1 - label_smoothing) + label_smoothing / n_classes
                loss = -jnp.sum(soft * logp, axis=axis)
            else:
                picked = jnp.take_along_axis(
                    logp, jnp.expand_dims(li, axis), axis=axis)
                loss = -jnp.squeeze(picked, axis)
            mask = (li != ignore_index)
            loss = jnp.where(mask, loss, 0.0)
            if maybe_w:
                w = maybe_w[0].astype(jnp.float32)
                wv = jnp.take(w, jnp.maximum(li, 0))
                loss = loss * jnp.where(mask, wv, 0.0)
                if reduction == "mean":
                    denom = jnp.maximum(
                        jnp.sum(jnp.where(mask, wv, 0.0)), 1e-12)
                    return jnp.sum(loss) / denom
            if reduction == "mean":
                denom = jnp.maximum(jnp.sum(mask.astype(jnp.float32)), 1.0)
                return jnp.sum(loss) / denom
        return _reduce(loss, reduction)

    args = [input, label]
    if weight is not None:
        args.append(_t(weight))
    return apply(f, *args)


def softmax_with_cross_entropy(logits, label, soft_label=False,
                               ignore_index=-100, numeric_stable_mode=True,
                               return_softmax=False, axis=-1):
    out = cross_entropy(logits, label, soft_label=soft_label,
                        ignore_index=ignore_index, reduction="none", axis=axis)
    # keep label's trailing-1 dim convention
    lbl = _t(label)
    if not soft_label and lbl.data.ndim == _t(logits).data.ndim:
        out = apply(lambda a: jnp.expand_dims(a, axis), out)
    if return_softmax:
        from .activation import softmax as _softmax
        return out, _softmax(logits, axis=axis)
    return out


def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean",
             name=None):
    """paddle.nn.functional.nll_loss (nll_loss_op.cc): input is
    LOG-probabilities [N, C, d...], loss = -input[label] (routing through
    cross_entropy(use_softmax=False) would log() the already-log input).
    Weighted mean divides by the summed weights of non-ignored targets."""
    args = [_t(input), _t(label)]
    if weight is not None:
        args.append(_t(weight))

    def f(logp, lab, *maybe_w):
        logp = logp.astype(jnp.float32)
        li = lab.astype(jnp.int32)
        picked = jnp.take_along_axis(
            logp, jnp.expand_dims(jnp.maximum(li, 0), 1), axis=1)
        loss = -jnp.squeeze(picked, 1)
        mask = li != ignore_index
        w = maybe_w[0].astype(jnp.float32)[jnp.maximum(li, 0)]             if maybe_w else jnp.ones_like(loss)
        w = jnp.where(mask, w, 0.0)
        loss = loss * w
        if reduction == "mean":
            return jnp.sum(loss) / jnp.maximum(jnp.sum(w), 1e-12)
        if reduction == "sum":
            return jnp.sum(loss)
        return loss

    return apply(f, *args)


def mse_loss(input, label, reduction="mean", name=None):
    return apply(lambda a, b: _reduce(jnp.square(a - b), reduction),
                 _t(input), _t(label))


def l1_loss(input, label, reduction="mean", name=None):
    return apply(lambda a, b: _reduce(jnp.abs(a - b), reduction),
                 _t(input), _t(label))


def smooth_l1_loss(input, label, reduction="mean", delta=1.0, name=None):
    def f(a, b):
        d = jnp.abs(a - b)
        loss = jnp.where(d < delta, 0.5 * d * d / delta, d - 0.5 * delta)
        return _reduce(loss, reduction)

    return apply(f, _t(input), _t(label))


def binary_cross_entropy(input, label, weight=None, reduction="mean", name=None):
    def f(p, y, *maybe_w):
        p32 = jnp.clip(p.astype(jnp.float32), 1e-12, 1 - 1e-7)
        loss = -(y * jnp.log(p32) + (1 - y) * jnp.log1p(-p32))
        if maybe_w:
            loss = loss * maybe_w[0]
        return _reduce(loss, reduction)

    args = [_t(input), _t(label)]
    if weight is not None:
        args.append(_t(weight))
    return apply(f, *args)


def binary_cross_entropy_with_logits(logit, label, weight=None, reduction="mean",
                                     pos_weight=None, name=None):
    def f(z, y, *extra):
        z32 = z.astype(jnp.float32)
        y32 = y.astype(jnp.float32)
        i = 0
        pw = None
        if weight is not None:
            i += 1
        if pos_weight is not None:
            pw = extra[i]
        # stable: max(z,0) - z*y + log(1+exp(-|z|)), with pos_weight folding
        if pw is None:
            loss = jnp.maximum(z32, 0) - z32 * y32 + jnp.log1p(
                jnp.exp(-jnp.abs(z32)))
        else:
            log_w = (pw - 1) * y32 + 1
            loss = (1 - y32) * z32 + log_w * (
                jnp.log1p(jnp.exp(-jnp.abs(z32))) + jnp.maximum(-z32, 0))
        if weight is not None:
            loss = loss * extra[0]
        return _reduce(loss, reduction)

    args = [_t(logit), _t(label)]
    if weight is not None:
        args.append(_t(weight))
    if pos_weight is not None:
        args.append(_t(pos_weight))
    return apply(f, *args)


def kl_div(input, label, reduction="mean", name=None):
    def f(logp, y):
        loss = y * (jnp.log(jnp.maximum(y, 1e-30)) - logp)
        if reduction == "batchmean":
            return jnp.sum(loss) / logp.shape[0]
        return _reduce(loss, reduction)

    return apply(f, _t(input), _t(label))


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean",
                        name=None):
    return apply(
        lambda a, b, y: _reduce(jnp.maximum(-y * (a - b) + margin, 0), reduction),
        _t(input), _t(other), _t(label))


def hinge_embedding_loss(input, label, margin=1.0, reduction="mean", name=None):
    return apply(
        lambda a, y: _reduce(
            jnp.where(y == 1, a, jnp.maximum(margin - a, 0)), reduction),
        _t(input), _t(label))


def cosine_embedding_loss(input1, input2, label, margin=0, reduction="mean",
                          name=None):
    def f(a, b, y):
        cos = jnp.sum(a * b, -1) / (
            jnp.linalg.norm(a, axis=-1) * jnp.linalg.norm(b, axis=-1) + 1e-12)
        loss = jnp.where(y == 1, 1 - cos, jnp.maximum(cos - margin, 0))
        return _reduce(loss, reduction)

    return apply(f, _t(input1), _t(input2), _t(label))


def triplet_margin_loss(input, positive, negative, margin=1.0, p=2.0,
                        epsilon=1e-6, swap=False, reduction="mean", name=None):
    def f(a, pos, neg):
        dp = jnp.linalg.norm(a - pos + epsilon, ord=p, axis=-1)
        dn = jnp.linalg.norm(a - neg + epsilon, ord=p, axis=-1)
        if swap:
            dn2 = jnp.linalg.norm(pos - neg + epsilon, ord=p, axis=-1)
            dn = jnp.minimum(dn, dn2)
        return _reduce(jnp.maximum(dp - dn + margin, 0), reduction)

    return apply(f, _t(input), _t(positive), _t(negative))


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction="mean", norm_by_times=False):
    # warpctc analog (operators/warpctc_op.*) — dynamic-program in pure jax.
    log_probs, labels = _t(log_probs), _t(labels)
    input_lengths, label_lengths = _t(input_lengths), _t(label_lengths)

    def f(lp, lab, ilen, llen):
        # lp: [T, B, C] log-probs (paddle feeds logits; normalize here)
        lp = jax.nn.log_softmax(lp.astype(jnp.float32), axis=-1)
        T, B, C = lp.shape
        S = lab.shape[1]
        ext = jnp.full((B, 2 * S + 1), blank, dtype=jnp.int32)
        ext = ext.at[:, 1::2].set(lab.astype(jnp.int32))
        L = 2 * llen.astype(jnp.int32) + 1
        neg_inf = jnp.float32(-1e30)
        alpha0 = jnp.full((B, 2 * S + 1), neg_inf)
        alpha0 = alpha0.at[:, 0].set(lp[0, :, blank])
        alpha0 = alpha0.at[:, 1].set(
            jnp.take_along_axis(lp[0], ext[:, 1:2], axis=1)[:, 0])

        def step(alpha, lp_t):
            prev1 = jnp.pad(alpha[:, :-1], ((0, 0), (1, 0)),
                            constant_values=neg_inf)
            prev2 = jnp.pad(alpha[:, :-2], ((0, 0), (2, 0)),
                            constant_values=neg_inf)
            ext_shift = jnp.pad(ext[:, :-2], ((0, 0), (2, 0)),
                                constant_values=-1)
            allow_skip = (ext != blank) & (ext != ext_shift)
            cand = jnp.logaddexp(alpha, prev1)
            cand = jnp.where(allow_skip, jnp.logaddexp(cand, prev2), cand)
            emit = jnp.take_along_axis(lp_t, ext, axis=1)
            return cand + emit, None

        def scan_body(carry, t):
            alpha = carry
            new_alpha, _ = step(alpha, lp[t])
            alpha = jnp.where((t < ilen)[:, None], new_alpha, alpha)
            return alpha, None

        alpha, _ = jax.lax.scan(scan_body, alpha0, jnp.arange(1, T))
        idx_last = jnp.stack([L - 1, L - 2], axis=1)
        vals = jnp.take_along_axis(alpha, idx_last, axis=1)
        loss = -jax.nn.logsumexp(vals, axis=1)
        if reduction == "mean":
            return jnp.mean(loss / jnp.maximum(llen.astype(jnp.float32), 1.0))
        return _reduce(loss, reduction)

    return apply(f, log_probs, labels, input_lengths, label_lengths)


def square_error_cost(input, label):
    """square_error_cost op: (input - label)^2, no reduction."""
    return apply(lambda a, b: jnp.square(a - b), _t(input), _t(label))


def log_loss(input, label, epsilon=1e-4, name=None):
    """log_loss_op.cc: -y log(p+eps) - (1-y) log(1-p+eps)."""
    return apply(
        lambda p, y: -y * jnp.log(p + epsilon)
        - (1.0 - y) * jnp.log(1.0 - p + epsilon),
        _t(input), _t(label))


def dice_loss(input, label, epsilon=1e-5, name=None):
    """dice_loss (layers/loss.py): 1 - 2|X∩Y| / (|X|+|Y|). input: [N,...,C]
    probabilities; label: [N,...,1] class ids."""
    input = _t(input)
    label = _t(label)

    def f(p, y):
        nc = p.shape[-1]
        onehot = jax.nn.one_hot(y[..., 0].astype(jnp.int32), nc,
                                dtype=p.dtype)
        red = tuple(range(1, p.ndim))
        inter = jnp.sum(p * onehot, axis=red)
        union = jnp.sum(p, axis=red) + jnp.sum(onehot, axis=red)
        return jnp.mean(1.0 - 2.0 * inter / (union + epsilon))

    return apply(f, input, label)


def npair_loss(anchor, positive, labels, l2_reg=0.002):
    """npair_loss (layers/loss.py): cross-entropy over anchor·positiveᵀ
    similarities with same-label targets + L2 on the embeddings."""
    anchor = _t(anchor)
    positive = _t(positive)
    labels = _t(labels)

    def f(a, p, y):
        y = y.reshape(-1)
        sim = a @ p.T  # [B, B]
        same = (y[:, None] == y[None, :]).astype(jnp.float32)
        targets = same / jnp.sum(same, axis=1, keepdims=True)
        logp = jax.nn.log_softmax(sim, axis=1)
        ce = jnp.mean(-jnp.sum(targets * logp, axis=1))
        reg = jnp.mean(jnp.sum(jnp.square(a), 1)) + \
            jnp.mean(jnp.sum(jnp.square(p), 1))
        return ce + l2_reg * reg * 0.25

    return apply(f, anchor, positive, labels)


def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0,
                       reduction="sum", name=None):
    """sigmoid_focal_loss (RetinaNet): FL = -alpha_t (1-p_t)^gamma log(p_t)."""
    logit = _t(logit)
    label = _t(label)

    def f(x, y, *n):
        p = jax.nn.sigmoid(x)
        ce = -(y * jax.nn.log_sigmoid(x)
               + (1 - y) * jax.nn.log_sigmoid(-x))
        p_t = p * y + (1 - p) * (1 - y)
        a_t = alpha * y + (1 - alpha) * (1 - y)
        loss = a_t * jnp.power(1 - p_t, gamma) * ce
        if n:
            loss = loss / n[0]
        return _reduce(loss, reduction)

    if normalizer is not None:
        return apply(f, logit, label, _t(normalizer))
    return apply(f, logit, label)


def hsigmoid_loss(input, label, num_classes, weight, bias=None,
                  path_table=None, path_code=None, is_sparse=False,
                  name=None):
    """Hierarchical sigmoid (hierarchical_sigmoid_op.cc). Default tree: the
    complete binary tree over num_classes leaves whose internal nodes are
    addressed by the bits of (label + num_classes) walking down from the
    root — the reference's default coding. Custom trees come in via
    path_table/path_code [N, L] PER-SAMPLE tables (padded with -1),
    exactly the reference's custom-tree layout."""
    input = _t(input)
    label = _t(label)
    weight = _t(weight)
    args = [input, label, weight]
    if bias is not None:
        args.append(_t(bias))

    import numpy as np
    if path_table is None:
        depth = max(int(np.ceil(np.log2(max(num_classes, 2)))), 1)
        # complete-tree addressing: internal node ids 1..num_classes-1
        # (heap order), leaf for class c sits at heap index c+num_classes
        def paths_for(codes):
            idx = codes + num_classes
            tables, cds = [], []
            for _ in range(depth):
                parent = idx // 2
                bit = idx % 2
                tables.append(parent - 1)   # weight row of the node
                cds.append(bit)
                idx = parent
            t = jnp.stack(tables[::-1], axis=-1)
            c = jnp.stack(cds[::-1], axis=-1)
            valid = t >= 0
            return jnp.where(valid, t, 0), c, valid
    else:
        pt = _t(path_table)
        pc = _t(path_code)

    def f(x, y, w, *b):
        y = y.reshape(-1).astype(jnp.int32)
        if path_table is None:
            t, c, valid = paths_for(y)
        else:
            t = pt.data  # per-sample [N, L] (no shape sniffing: a batch
            c = pc.data  # of size num_classes must not re-gather by label)
            valid = t >= 0
            t = jnp.where(valid, t, 0)
        # logits of each node on the path: x @ w[t]^T (+ bias[t])
        wt = w[t]                       # [N, L, D]
        logits = jnp.einsum("nd,nld->nl", x, wt)
        if b:
            logits = logits + b[0].reshape(-1)[t]
        # code bit 1 -> sigmoid(logit), 0 -> 1 - sigmoid(logit)
        ce = -(c * jax.nn.log_sigmoid(logits)
               + (1 - c) * jax.nn.log_sigmoid(-logits))
        ce = jnp.where(valid, ce, 0.0)
        return jnp.sum(ce, axis=-1, keepdims=True)

    return apply(f, *args)


def linear_chain_crf(emission, label, transition, length=None):
    """Linear-chain CRF negative log-likelihood
    (linear_chain_crf_op.cc). emission [B, S, T]; label [B, S] int;
    transition [T+2, T] with row 0 = start scores, row 1 = stop scores,
    rows 2.. = tag->tag transitions (the reference's parameter layout).
    length [B] masks padded steps. Returns nll [B] (sum over sequences is
    the training loss); differentiable w.r.t. emission and transition."""
    emission, label = _t(emission), _t(label)
    transition = _t(transition)
    args = [emission, label, transition]
    if length is not None:
        args.append(_t(length))

    def f(em, lab, trans, *maybe_len):
        B, S, T = em.shape
        em = em.astype(jnp.float32)
        trans = trans.astype(jnp.float32)
        start, stop, trans_tt = trans[0], trans[1], trans[2:]
        lens = (maybe_len[0].astype(jnp.int32) if maybe_len
                else jnp.full((B,), S, jnp.int32))
        lab = lab.astype(jnp.int32)

        # ---- log partition via forward algorithm ----
        alpha0 = start[None, :] + em[:, 0]          # [B, T]

        def fwd(alpha, t):
            # [B, T, T']: alpha[i] + trans[i, j] + em[t, j]
            scores = alpha[:, :, None] + trans_tt[None] + \
                em[:, t][:, None, :]
            new_alpha = jax.nn.logsumexp(scores, axis=1)
            keep = (t < lens)[:, None]
            return jnp.where(keep, new_alpha, alpha), None

        alpha, _ = jax.lax.scan(fwd, alpha0, jnp.arange(1, S))
        last_tag_scores = alpha + stop[None, :]
        logz = jax.nn.logsumexp(last_tag_scores, axis=1)   # [B]

        # ---- gold path score ----
        pos = jnp.arange(S)[None, :]
        valid = pos < lens[:, None]
        em_score = jnp.sum(
            jnp.where(valid,
                      jnp.take_along_axis(em, lab[..., None], -1)[..., 0],
                      0.0), axis=1)
        prev, cur = lab[:, :-1], lab[:, 1:]
        tvalid = pos[:, 1:] < lens[:, None]
        t_score = jnp.sum(
            jnp.where(tvalid, trans_tt[prev, cur], 0.0), axis=1)
        first = lab[:, 0]
        last = jnp.take_along_axis(lab, (lens - 1)[:, None], 1)[:, 0]
        gold = em_score + t_score + start[first] + stop[last]
        return logz - gold

    return apply(f, *args)


def crf_decoding(emission, transition, length=None):
    """Viterbi decode (crf_decoding_op.cc): returns the max-score tag path
    [B, S] under the linear_chain_crf parameterization (padded steps 0)."""
    emission = _t(emission)
    transition = _t(transition)
    args = [emission, transition]
    if length is not None:
        args.append(_t(length))

    def f(em, trans, *maybe_len):
        B, S, T = em.shape
        em = em.astype(jnp.float32)
        trans = trans.astype(jnp.float32)
        start, stop, trans_tt = trans[0], trans[1], trans[2:]
        lens = (maybe_len[0].astype(jnp.int32) if maybe_len
                else jnp.full((B,), S, jnp.int32))
        alpha0 = start[None, :] + em[:, 0]

        def step(alpha, t):
            scores = alpha[:, :, None] + trans_tt[None] + \
                em[:, t][:, None, :]
            best_prev = jnp.argmax(scores, axis=1)          # [B, T]
            new_alpha = jnp.max(scores, axis=1)
            keep = (t < lens)[:, None]
            return (jnp.where(keep, new_alpha, alpha),
                    jnp.where(keep, best_prev, -1))

        alpha, back = jax.lax.scan(step, alpha0, jnp.arange(1, S))
        # back: [S-1, B, T]; final tag maximizes alpha + stop at each len
        last = jnp.argmax(alpha + stop[None, :], axis=1)    # [B]

        def backtrace(carry, t):
            tag = carry  # [B]
            bp = back[t]  # [B, T]
            prev = jnp.take_along_axis(bp, tag[:, None], 1)[:, 0]
            in_range = (t + 1) < lens
            new_tag = jnp.where(in_range & (prev >= 0), prev, tag)
            return new_tag, new_tag

        _, path_rev = jax.lax.scan(backtrace, last,
                                   jnp.arange(S - 2, -1, -1))
        path = jnp.concatenate(
            [jnp.flip(jnp.swapaxes(path_rev, 0, 1), 1), last[:, None]],
            axis=1)
        pos = jnp.arange(S)[None, :]
        return jnp.where(pos < lens[:, None], path, 0).astype(jnp.int64)

    return apply(f, *args)


def center_loss(input, label, centers, alpha=0.5, update_centers=True):
    """center_loss_op: 0.5 * ||x - c_y||^2 per sample, plus the center
    SGD-style update c_y += alpha * mean(x - c_y) over the batch. Returns
    (loss [B], new_centers) — thread new_centers back as the next step's
    buffer (functional analog of the op's in-place CenterUpdate)."""
    x, y, c = _t(input), _t(label), _t(centers)

    def f(xa, ya, ca):
        ya = ya.astype(jnp.int32).reshape(-1)
        diff = xa.astype(jnp.float32) - ca[ya].astype(jnp.float32)
        loss = 0.5 * jnp.sum(diff * diff, axis=1)
        if not update_centers:
            return loss, ca
        counts = jnp.zeros((ca.shape[0],), jnp.float32).at[ya].add(1.0)
        sums = jnp.zeros_like(ca, dtype=jnp.float32).at[ya].add(diff)
        upd = alpha * sums / jnp.maximum(counts, 1.0)[:, None]
        return loss, (ca.astype(jnp.float32) + upd).astype(ca.dtype)

    return apply(f, x, y, c)


def rnnt_loss(input, label, input_lengths, label_lengths, blank=0,
              fastemit_lambda=0.001, reduction="mean"):
    """RNN-Transducer loss (warprnnt analog; reference ships warpctc via
    operators/warpctc_op.* — rnnt_loss is its 2.5-era sibling backed by
    warp_transducer). Pure-jax dynamic program (Graves 2012):

        alpha[t, u] = logaddexp(alpha[t-1, u] + log P(blank | t-1, u),
                                alpha[t, u-1] + log P(y_u  | t, u-1))
        loss = -(alpha[T-1, U] + log P(blank | T-1, U))

    input: [B, T, U+1, V] raw joint-network logits (log_softmax applied
    internally, as warprnnt does); label [B, U] int; per-sample lengths.
    fastemit_lambda: FastEmit regularization — the label-emission entries
    of the logits gradient are scaled by (1 + lambda), exactly
    warp_transducer's implementation (gradient shaping, not a loss term).
    The outer t-scan carries an inner u-scan (the u recurrence is
    sequential); T*U sequential steps — fine for training-size U, and the
    whole DP lives on-device under jit.
    """
    input, label = _t(input), _t(label)
    input_lengths, label_lengths = _t(input_lengths), _t(label_lengths)
    lam = float(fastemit_lambda)

    def _nll(logits, lab, ilen, ulen):
        """Per-sample negative log-likelihood [B] (standard, no FastEmit)."""
        lp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        B, T, U1, V = lp.shape
        U = U1 - 1
        neg_inf = jnp.float32(-1e30)
        lp_blank = lp[..., blank]                      # [B, T, U+1]
        lab_i = jnp.clip(lab.astype(jnp.int32), 0, V - 1)
        lp_label = jnp.take_along_axis(
            lp[:, :, :U, :],
            jnp.broadcast_to(lab_i[:, None, :, None], (B, T, U, 1)),
            axis=3)[..., 0]                            # [B, T, U]
        base0 = jnp.full((B, U1), neg_inf).at[:, 0].set(0.0)
        # xs[t] = (t, lp_blank[:, t-1], lp_label[:, t]); dummy blank row
        # at t=0 (unused: base switches to base0 there)
        bl_prev = jnp.concatenate(
            [jnp.zeros((1, B, U1)), jnp.swapaxes(lp_blank, 0, 1)[:-1]])
        lab_t = jnp.swapaxes(lp_label, 0, 1)           # [T, B, U]

        def t_step(alpha_prev, x):
            t, blp, lbt = x
            base = jnp.where(t == 0, base0, alpha_prev + blp)  # [B, U+1]

            def u_step(a_left, x2):
                base_u, lab_left = x2                  # [B], [B]
                a = jnp.logaddexp(base_u, a_left + lab_left)
                return a, a

            a0 = base[:, 0]
            _, rest = jax.lax.scan(
                u_step, a0, (base[:, 1:].T, lbt.T))    # rest [U, B]
            alpha = jnp.concatenate([a0[:, None], rest.T], axis=1)
            # freeze rows past each sample's input length so the final
            # gather reads alpha as of t = ilen-1
            alpha = jnp.where((t < ilen)[:, None], alpha, alpha_prev)
            return alpha, None

        alpha, _ = jax.lax.scan(
            t_step, jnp.full((B, U1), neg_inf),
            (jnp.arange(T), bl_prev, lab_t))
        u_fin = jnp.clip(ulen.astype(jnp.int32), 0, U)[:, None]
        a_fin = jnp.take_along_axis(alpha, u_fin, axis=1)[:, 0]
        t_fin = jnp.clip(ilen.astype(jnp.int32) - 1, 0, T - 1)
        bl_fin = jnp.take_along_axis(
            jnp.take_along_axis(
                lp_blank, t_fin[:, None, None], axis=1)[:, 0],
            u_fin, axis=1)[:, 0]
        return -(a_fin + bl_fin)

    @jax.custom_vjp
    def _loss(logits, lab, ilen, ulen):
        return _nll(logits, lab, ilen, ulen)

    def _fwd(logits, lab, ilen, ulen):
        return _nll(logits, lab, ilen, ulen), (logits, lab, ilen, ulen)

    def _bwd(res, g):
        logits, lab, ilen, ulen = res
        _, vjp = jax.vjp(lambda lg: _nll(lg, lab, ilen, ulen), logits)
        (d_logits,) = vjp(g)
        if lam:
            # FastEmit: scale the label-emission gradient entries by
            # (1 + lambda) — warp_transducer's grad shaping
            B, T, U1, V = logits.shape
            U = U1 - 1
            lab_i = jnp.clip(lab.astype(jnp.int32), 0, V - 1)
            onehot = jax.nn.one_hot(lab_i, V, dtype=d_logits.dtype)
            mask = jnp.zeros((B, T, U1, V), d_logits.dtype)
            mask = mask.at[:, :, :U, :].set(
                jnp.broadcast_to(onehot[:, None, :, :], (B, T, U, V)))
            d_logits = d_logits * (1.0 + lam * mask)
        return d_logits, None, None, None

    _loss.defvjp(_fwd, _bwd)

    per_sample = apply(_loss, input, label, input_lengths, label_lengths)
    if reduction == "mean":
        from ...tensor.math import mean as _mean
        return _mean(per_sample)
    if reduction == "sum":
        from ...tensor.math import sum as _sum
        return _sum(per_sample)
    return per_sample


def huber_loss(input, label, delta=1.0, reduction="mean"):
    """huber_loss_op.cc: 0.5*r^2 for |r|<=delta else delta*(|r|-0.5*delta).
    (Differs from smooth_l1_loss by the delta scaling convention.)"""
    input, label = _t(input), _t(label)

    def f(x, y):
        r = jnp.abs(x - y)
        return jnp.where(r <= delta, 0.5 * r * r,
                         delta * (r - 0.5 * delta))

    out = apply(f, input, label)
    return _reduce(out, reduction)


def hinge_loss(logits, labels):
    """hinge_loss_op.cc: max(1 - (2*label - 1) * logits, 0), elementwise
    (labels in {0, 1})."""
    logits, labels = _t(logits), _t(labels)
    return apply(
        lambda x, y: jnp.maximum(
            1.0 - (2.0 * y.astype(x.dtype) - 1.0) * x, 0.0),
        logits, labels)


def bpr_loss(input, label):
    """bpr_loss_op.cc (Bayesian Personalized Ranking, session-based recs):
    for each row of logits, -mean_j log(sigmoid(x[label] - x[j])) over the
    negative items j != label. Returns [N, 1]."""
    input, label = _t(input), _t(label)

    def f(x, y):
        N, C = x.shape
        y = y.reshape(-1).astype(jnp.int32)
        pos = jnp.take_along_axis(x, y[:, None], axis=1)       # [N, 1]
        diff = pos - x                                          # [N, C]
        lsm = jax.nn.log_sigmoid(diff)
        mask = jax.nn.one_hot(y, C, dtype=x.dtype)
        loss = -(jnp.sum(lsm * (1 - mask), axis=1) / (C - 1))
        return loss[:, None]

    return apply(f, input, label)


def ctc_align(input, blank=0, merge_repeated=True, input_length=None,
              padding_value=0):
    """ctc_align_op.cc: collapse a ctc label sequence — merge repeats
    (optionally), strip blanks, left-pack, pad with padding_value.
    input [B, T] int predictions (e.g. argmax over logits)."""
    import numpy as np

    from ...tensor.creation import to_tensor
    x = np.asarray(_t(input).data)
    B, T = x.shape
    lens = (np.asarray(_t(input_length).data).reshape(-1)
            if input_length is not None else np.full(B, T))
    out = np.full((B, T), padding_value, x.dtype)
    out_lens = np.zeros(B, np.int32)
    for b in range(B):
        prev = None
        k = 0
        for t in range(int(lens[b])):
            v = x[b, t]
            if merge_repeated and prev is not None and v == prev:
                continue
            prev = v
            if v != blank:
                out[b, k] = v
                k += 1
        out_lens[b] = k
    return to_tensor(out), to_tensor(out_lens)
