"""Recurrent layers (reference: python/paddle/nn/layer/rnn.py, operators/rnn_op.*).

TPU-native: the time loop is jax.lax.scan over stacked gate matmuls — one fused
[x|h] @ W per step keeps the MXU busy; no cuDNN-style fused kernel needed.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ...core.tensor import Tensor, apply
from ...tensor.creation import _t, zeros
from .. import initializer as I
from .layers import Layer, LayerList


class RNNCellBase(Layer):
    def get_initial_states(self, batch_ref, shape=None, dtype=None,
                           init_value=0.0, batch_dim_idx=0):
        B = batch_ref.shape[batch_dim_idx]
        if isinstance(self.state_shape[0], (list, tuple)):
            return tuple(zeros([B, *s]) for s in self.state_shape)
        return zeros([B, *self.state_shape])


class SimpleRNNCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, activation="tanh",
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.activation = activation
        std = 1.0 / math.sqrt(hidden_size)
        u = I.Uniform(-std, std)
        self.weight_ih = self.create_parameter(
            [hidden_size, input_size], weight_ih_attr, default_initializer=u)
        self.weight_hh = self.create_parameter(
            [hidden_size, hidden_size], weight_hh_attr, default_initializer=u)
        self.bias_ih = self.create_parameter(
            [hidden_size], bias_ih_attr, is_bias=True, default_initializer=u)
        self.bias_hh = self.create_parameter(
            [hidden_size], bias_hh_attr, is_bias=True, default_initializer=u)

    @property
    def state_shape(self):
        return (self.hidden_size,)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        act = jnp.tanh if self.activation == "tanh" else jax.nn.relu

        def f(x, h, wi, wh, bi, bh):
            out = act(x @ wi.T + bi + h @ wh.T + bh)
            return out, out

        out, h = apply(f, _t(inputs), _t(states), self.weight_ih,
                       self.weight_hh, self.bias_ih, self.bias_hh)
        return out, h


class LSTMCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        std = 1.0 / math.sqrt(hidden_size)
        u = I.Uniform(-std, std)
        self.weight_ih = self.create_parameter(
            [4 * hidden_size, input_size], weight_ih_attr,
            default_initializer=u)
        self.weight_hh = self.create_parameter(
            [4 * hidden_size, hidden_size], weight_hh_attr,
            default_initializer=u)
        self.bias_ih = self.create_parameter(
            [4 * hidden_size], bias_ih_attr, is_bias=True,
            default_initializer=u)
        self.bias_hh = self.create_parameter(
            [4 * hidden_size], bias_hh_attr, is_bias=True,
            default_initializer=u)

    @property
    def state_shape(self):
        return ((self.hidden_size,), (self.hidden_size,))

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        h, c = states

        def f(x, hh, cc, wi, wh, bi, bh):
            gates = x @ wi.T + bi + hh @ wh.T + bh
            i, fgt, g, o = jnp.split(gates, 4, axis=-1)
            i = jax.nn.sigmoid(i)
            fgt = jax.nn.sigmoid(fgt)
            g = jnp.tanh(g)
            o = jax.nn.sigmoid(o)
            new_c = fgt * cc + i * g
            new_h = o * jnp.tanh(new_c)
            return new_h, new_h, new_c

        out, new_h, new_c = apply(f, _t(inputs), _t(h), _t(c), self.weight_ih,
                                  self.weight_hh, self.bias_ih, self.bias_hh)
        return out, (new_h, new_c)


class GRUCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        std = 1.0 / math.sqrt(hidden_size)
        u = I.Uniform(-std, std)
        self.weight_ih = self.create_parameter(
            [3 * hidden_size, input_size], weight_ih_attr,
            default_initializer=u)
        self.weight_hh = self.create_parameter(
            [3 * hidden_size, hidden_size], weight_hh_attr,
            default_initializer=u)
        self.bias_ih = self.create_parameter(
            [3 * hidden_size], bias_ih_attr, is_bias=True,
            default_initializer=u)
        self.bias_hh = self.create_parameter(
            [3 * hidden_size], bias_hh_attr, is_bias=True,
            default_initializer=u)

    @property
    def state_shape(self):
        return (self.hidden_size,)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)

        def f(x, h, wi, wh, bi, bh):
            gi = x @ wi.T + bi
            gh = h @ wh.T + bh
            ir, iz, ig = jnp.split(gi, 3, axis=-1)
            hr, hz, hg = jnp.split(gh, 3, axis=-1)
            r = jax.nn.sigmoid(ir + hr)
            z = jax.nn.sigmoid(iz + hz)
            n = jnp.tanh(ig + r * hg)
            out = (1 - z) * n + z * h
            return out, out

        out, h = apply(f, _t(inputs), _t(states), self.weight_ih,
                       self.weight_hh, self.bias_ih, self.bias_hh)
        return out, h


def _scan_rnn(mode, x_arr, init_states, weights, hidden_size, reverse=False):
    """Run one direction of one layer with lax.scan; x_arr [B, T, I]."""
    wi, wh, bi, bh = weights
    xs = jnp.swapaxes(x_arr, 0, 1)  # [T, B, I]
    if reverse:
        xs = jnp.flip(xs, 0)
    # hoist the input matmul out of the scan: [T, B, G]
    x_proj = jnp.einsum("tbi,gi->tbg", xs, wi) + bi

    if mode == "LSTM":
        def step(carry, xp):
            h, c = carry
            gates = xp + h @ wh.T + bh
            i, f, g, o = jnp.split(gates, 4, axis=-1)
            i, f, o = (jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o))
            g = jnp.tanh(g)
            c = f * c + i * g
            h = o * jnp.tanh(c)
            return (h, c), h

        carry, outs = jax.lax.scan(step, init_states, x_proj)
    elif mode == "GRU":
        def step(h, xp):
            gh = h @ wh.T + bh
            ir, iz, ig = jnp.split(xp, 3, axis=-1)
            hr, hz, hg = jnp.split(gh, 3, axis=-1)
            r = jax.nn.sigmoid(ir + hr)
            z = jax.nn.sigmoid(iz + hz)
            n = jnp.tanh(ig + r * hg)
            h = (1 - z) * n + z * h
            return h, h

        carry, outs = jax.lax.scan(step, init_states, x_proj)
    else:
        act = jnp.tanh if mode == "RNN_TANH" else jax.nn.relu

        def step(h, xp):
            h = act(xp + h @ wh.T + bh)
            return h, h

        carry, outs = jax.lax.scan(step, init_states, x_proj)
    if reverse:
        outs = jnp.flip(outs, 0)
    return jnp.swapaxes(outs, 0, 1), carry


class _RNNBase(Layer):
    def __init__(self, mode, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, name=None):
        super().__init__()
        self.mode = mode
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.time_major = time_major
        self.dropout = dropout
        self.bidirect = direction in ("bidirect", "bidirectional")
        self.num_directions = 2 if self.bidirect else 1
        gate_mult = {"LSTM": 4, "GRU": 3, "RNN_TANH": 1, "RNN_RELU": 1}[mode]
        std = 1.0 / math.sqrt(hidden_size)
        u = I.Uniform(-std, std)
        self._all_weights = []
        for layer in range(num_layers):
            for direction in range(self.num_directions):
                in_size = (input_size if layer == 0
                           else hidden_size * self.num_directions)
                suffix = "_reverse" if direction else ""
                wi = self.create_parameter([gate_mult * hidden_size, in_size],
                                           weight_ih_attr,
                                           default_initializer=u)
                wh = self.create_parameter(
                    [gate_mult * hidden_size, hidden_size], weight_hh_attr,
                    default_initializer=u)
                bi = self.create_parameter([gate_mult * hidden_size],
                                           bias_ih_attr, is_bias=True,
                                           default_initializer=u)
                bh = self.create_parameter([gate_mult * hidden_size],
                                           bias_hh_attr, is_bias=True,
                                           default_initializer=u)
                for n, p in zip(["weight_ih", "weight_hh", "bias_ih",
                                 "bias_hh"], [wi, wh, bi, bh]):
                    self.add_parameter(f"{n}_l{layer}{suffix}", p)
                self._all_weights.append((wi, wh, bi, bh))

    def forward(self, inputs, initial_states=None, sequence_length=None):
        x = _t(inputs)
        if self.time_major:
            x = x.transpose([1, 0, 2])
        B = x.shape[0]
        is_lstm = self.mode == "LSTM"
        L = self.num_layers * self.num_directions
        if initial_states is None:
            h0 = zeros([L, B, self.hidden_size])
            c0 = zeros([L, B, self.hidden_size]) if is_lstm else None
        else:
            if is_lstm:
                h0, c0 = initial_states
            else:
                h0, c0 = initial_states, None

        flat_weights = [w for group in self._all_weights for w in group]

        def run(xa, h0a, *rest):
            if is_lstm:
                c0a, flat = rest[0], rest[1:]
            else:
                c0a, flat = None, rest
            out = xa
            final_h, final_c = [], []
            idx = 0
            for layer in range(self.num_layers):
                outs_dir = []
                for d in range(self.num_directions):
                    w = tuple(flat[4 * idx:4 * idx + 4])
                    init = ((h0a[idx], c0a[idx]) if is_lstm else h0a[idx])
                    o, carry = _scan_rnn(self.mode, out, init, w,
                                         self.hidden_size, reverse=bool(d))
                    outs_dir.append(o)
                    if is_lstm:
                        final_h.append(carry[0])
                        final_c.append(carry[1])
                    else:
                        final_h.append(carry)
                    idx += 1
                out = (jnp.concatenate(outs_dir, -1)
                       if self.num_directions == 2 else outs_dir[0])
            fh = jnp.stack(final_h)
            if is_lstm:
                return out, fh, jnp.stack(final_c)
            return out, fh

        args = [x, _t(h0)]
        if is_lstm:
            args.append(_t(c0))
        args.extend(flat_weights)
        res = apply(run, *args)
        if is_lstm:
            out, fh, fc = res
            states = (fh, fc)
        else:
            out, fh = res
            states = fh
        if self.time_major:
            out = out.transpose([1, 0, 2])
        return out, states


class SimpleRNN(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 activation="tanh", **kwargs):
        mode = "RNN_TANH" if activation == "tanh" else "RNN_RELU"
        super().__init__(mode, input_size, hidden_size, num_layers, direction,
                         time_major, dropout, **kwargs)


class LSTM(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0, **kwargs):
        super().__init__("LSTM", input_size, hidden_size, num_layers,
                         direction, time_major, dropout, **kwargs)


class GRU(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0, **kwargs):
        super().__init__("GRU", input_size, hidden_size, num_layers, direction,
                         time_major, dropout, **kwargs)


class RNN(Layer):
    """Wraps a cell into a scan over time (paddle.nn.RNN)."""

    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None):
        x = _t(inputs)
        if not self.time_major:
            x = x.transpose([1, 0, 2])
        T = x.shape[0]
        steps = range(T - 1, -1, -1) if self.is_reverse else range(T)
        states = initial_states
        outs = [None] * T
        for t in steps:
            out, states = self.cell(x[t], states)
            outs[t] = out
        from ...tensor.manipulation import stack
        out = stack(outs, axis=0)
        if not self.time_major:
            out = out.transpose([1, 0, 2])
        return out, states


class BiRNN(Layer):
    def __init__(self, cell_fw, cell_bw, time_major=False):
        super().__init__()
        self.rnn_fw = RNN(cell_fw, False, time_major)
        self.rnn_bw = RNN(cell_bw, True, time_major)

    def forward(self, inputs, initial_states=None, sequence_length=None):
        sf, sb = (initial_states if initial_states is not None else (None, None))
        out_f, st_f = self.rnn_fw(inputs, sf)
        out_b, st_b = self.rnn_bw(inputs, sb)
        from ...tensor.manipulation import concat
        return concat([out_f, out_b], axis=-1), (st_f, st_b)
