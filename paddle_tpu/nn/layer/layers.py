"""Layer: the module base class.

Reference: python/paddle/fluid/dygraph/layers.py (class Layer) — parameter/buffer/
sublayer registries, state_dict, hooks, train/eval. Redesigned for TPU: a Layer is
also a *functional* object — `functional_state` / `functional_call` flatten it to a
pytree of jax arrays and back, which is what jit / grad / pjit consume. The stateful
eager path and the pure path share the same forward() code.
"""
from __future__ import annotations

import contextlib
from collections import OrderedDict
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

import jax
import numpy as np

from ...core import dtypes
from ...core.tensor import Parameter, Tensor, no_grad
from .. import initializer as I


class ParamAttr:
    """paddle.ParamAttr analog: bundles name/initializer/regularizer/lr for a param."""

    def __init__(self, name=None, initializer=None, learning_rate=1.0,
                 regularizer=None, trainable=True, need_clip=True):
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.need_clip = need_clip

    @staticmethod
    def _to_attr(attr):
        if attr is None or attr is True:
            return ParamAttr()
        if attr is False:
            return None
        if isinstance(attr, ParamAttr):
            return attr
        if isinstance(attr, I.Initializer):
            return ParamAttr(initializer=attr)
        if isinstance(attr, str):
            return ParamAttr(name=attr)
        raise TypeError(f"cannot convert {attr!r} to ParamAttr")


class Layer:
    def __init__(self, name_scope=None, dtype="float32"):
        object.__setattr__(self, "_parameters", OrderedDict())
        object.__setattr__(self, "_buffers", OrderedDict())
        object.__setattr__(self, "_sub_layers", OrderedDict())
        self._non_persistable_buffer_names = set()
        self.training = True
        self._dtype = dtype
        self._name_scope = name_scope or self.__class__.__name__.lower()
        self._forward_pre_hooks: "OrderedDict[int, Callable]" = OrderedDict()
        self._forward_post_hooks: "OrderedDict[int, Callable]" = OrderedDict()
        self._hook_id = 0

    # ---- attribute plumbing ----
    def __setattr__(self, name, value):
        params = self.__dict__.get("_parameters")
        layers = self.__dict__.get("_sub_layers")
        buffers = self.__dict__.get("_buffers")
        if isinstance(value, Parameter):
            if params is None:
                raise RuntimeError("call Layer.__init__ before assigning params")
            params[name] = value
            buffers.pop(name, None) if buffers else None
            layers.pop(name, None) if layers else None
            object.__setattr__(self, name, value)
        elif isinstance(value, Layer):
            layers[name] = value
            object.__setattr__(self, name, value)
        elif isinstance(value, Tensor) and buffers is not None and name in buffers:
            buffers[name] = value
            object.__setattr__(self, name, value)
        else:
            if params is not None and name in params and value is None:
                del params[name]
            object.__setattr__(self, name, value)

    # ---- construction helpers ----
    def create_parameter(self, shape, attr=None, dtype=None, is_bias=False,
                         default_initializer=None) -> Parameter:
        attr = ParamAttr._to_attr(attr)
        if attr is None:
            return None
        dtype = dtype or self._dtype
        init = attr.initializer or default_initializer or (
            I.Constant(0.0) if is_bias else I._GLOBAL_DEFAULT[0])
        data = init(shape, dtype)
        p = Parameter(data, name=attr.name, trainable=attr.trainable)
        p.optimize_attr = {"learning_rate": attr.learning_rate}
        p.regularizer = attr.regularizer
        p.need_clip = attr.need_clip
        return p

    def add_parameter(self, name: str, parameter: Optional[Parameter]):
        if parameter is None:
            self._parameters.pop(name, None)
            object.__setattr__(self, name, None)
        else:
            setattr(self, name, parameter)
        return parameter

    def add_sublayer(self, name: str, sublayer: "Layer"):
        setattr(self, name, sublayer)
        return sublayer

    def register_buffer(self, name: str, tensor: Optional[Tensor],
                        persistable: bool = True):
        self._buffers[name] = tensor
        if not persistable:
            self._non_persistable_buffer_names.add(name)
        object.__setattr__(self, name, tensor)

    # ---- traversal ----
    def named_parameters(self, prefix="", include_sublayers=True
                         ) -> Iterator[Tuple[str, Parameter]]:
        seen = set()
        for name, layer in self.named_sublayers(prefix=prefix, include_self=True):
            for pname, p in layer._parameters.items():
                if p is None or id(p) in seen:
                    continue
                seen.add(id(p))
                yield (f"{name}.{pname}" if name else pname), p
            if not include_sublayers:
                break

    def parameters(self, include_sublayers=True) -> List[Parameter]:
        return [p for _, p in self.named_parameters(
            include_sublayers=include_sublayers)]

    def named_buffers(self, prefix="", include_sublayers=True):
        for name, layer in self.named_sublayers(prefix=prefix, include_self=True):
            for bname, b in layer._buffers.items():
                if b is None:
                    continue
                yield (f"{name}.{bname}" if name else bname), b

    def buffers(self, include_sublayers=True):
        return [b for _, b in self.named_buffers()]

    def named_sublayers(self, prefix="", include_self=False
                        ) -> Iterator[Tuple[str, "Layer"]]:
        if include_self:
            yield prefix, self
        for name, layer in self._sub_layers.items():
            if layer is None:
                continue
            sub_prefix = f"{prefix}.{name}" if prefix else name
            yield from layer.named_sublayers(prefix=sub_prefix, include_self=True)

    def sublayers(self, include_self=False) -> List["Layer"]:
        return [l for _, l in self.named_sublayers(include_self=include_self)]

    def named_children(self):
        yield from self._sub_layers.items()

    def children(self):
        return list(self._sub_layers.values())

    def apply(self, fn):
        for layer in self.sublayers(include_self=True):
            fn(layer)
        return self

    # ---- mode ----
    def train(self):
        for layer in self.sublayers(include_self=True):
            layer.training = True
        return self

    def eval(self):
        for layer in self.sublayers(include_self=True):
            layer.training = False
        return self

    # ---- dtype / device movement ----
    def to(self, device=None, dtype=None, blocking=None):
        if dtype is not None:
            d = dtypes.convert_dtype(dtype)
            for p in self.parameters():
                if dtypes.is_floating_point(p.dtype):
                    p.data = p.data.astype(d)
            for _, b in self.named_buffers():
                if dtypes.is_floating_point(b.dtype):
                    b.data = b.data.astype(d)
            for layer in self.sublayers(include_self=True):
                layer._dtype = dtypes.dtype_name(d)
        return self

    def astype(self, dtype):
        return self.to(dtype=dtype)

    def float(self):
        return self.to(dtype="float32")

    def bfloat16(self):
        return self.to(dtype="bfloat16")

    # ---- state ----
    def state_dict(self, destination=None, include_sublayers=True,
                   structured_name_prefix="", use_hook=True) -> Dict[str, Tensor]:
        out = destination if destination is not None else OrderedDict()
        for name, p in self.named_parameters(prefix=structured_name_prefix):
            out[name] = p
        for name, layer in self.named_sublayers(
                prefix=structured_name_prefix, include_self=True):
            for bname, b in layer._buffers.items():
                if b is None or bname in layer._non_persistable_buffer_names:
                    continue
                out[f"{name}.{bname}" if name else bname] = b
        return out

    def set_state_dict(self, state_dict, use_structured_name=True):
        own = self.state_dict()
        missing, unexpected = [], []
        for k, v in state_dict.items():
            if k not in own:
                unexpected.append(k)
                continue
            arr = v.numpy() if hasattr(v, "numpy") else np.asarray(v)
            own[k].set_value(arr)
        for k in own:
            if k not in state_dict:
                missing.append(k)
        return missing, unexpected

    load_dict = set_state_dict
    set_dict = set_state_dict

    # ---- hooks ----
    def register_forward_pre_hook(self, hook):
        self._hook_id += 1
        self._forward_pre_hooks[self._hook_id] = hook
        return _HookRemoveHelper(self._forward_pre_hooks, self._hook_id)

    def register_forward_post_hook(self, hook):
        self._hook_id += 1
        self._forward_post_hooks[self._hook_id] = hook
        return _HookRemoveHelper(self._forward_post_hooks, self._hook_id)

    # ---- call ----
    def forward(self, *inputs, **kwargs):
        raise NotImplementedError

    def __call__(self, *inputs, **kwargs):
        for hook in self._forward_pre_hooks.values():
            result = hook(self, inputs)
            if result is not None:
                inputs = result if isinstance(result, tuple) else (result,)
        out = self.forward(*inputs, **kwargs)
        for hook in self._forward_post_hooks.values():
            result = hook(self, inputs, out)
            if result is not None:
                out = result
        return out

    # ---- functional bridge (the TPU fast path) ----
    def functional_state(self):
        """Return (param_arrays, buffer_arrays) pytrees keyed by structured name."""
        params = {k: p.data for k, p in self.named_parameters() if p.trainable}
        frozen = {k: p.data for k, p in self.named_parameters() if not p.trainable}
        bufs = {k: b.data for k, b in self.named_buffers()}
        bufs.update(frozen)
        return params, bufs

    @contextlib.contextmanager
    def _bound_state(self, params: Dict[str, Any], buffers: Dict[str, Any]):
        """Temporarily swap in arrays for parameters/buffers (by structured name)."""
        named_p = dict(self.named_parameters())
        named_b = dict(self.named_buffers())
        saved = []
        try:
            for k, arr in params.items():
                t = named_p.get(k)
                if t is None:
                    t = named_b.get(k)
                if t is None:
                    raise KeyError(f"unknown parameter {k}")
                saved.append((t, t.data))
                t.data = arr
            for k, arr in buffers.items():
                t = named_b.get(k)
                if t is None:
                    t = named_p.get(k)
                if t is None:
                    raise KeyError(f"unknown buffer {k}")
                saved.append((t, t.data))
                t.data = arr
            yield self
        finally:
            for t, old in saved:
                t.data = old

    def functional_call(self, params, buffers, *inputs, rng=None, **kwargs):
        """Pure call: forward() with given arrays bound, tape disabled.

        Differentiate with jax.grad over `params`; this is what jit/pjit trace.
        `rng` (a PRNG key, possibly a tracer) feeds dropout/random draws so
        they stay data-dependent under jit.
        """
        out, _ = self.functional_call_with_state(params, buffers, *inputs,
                                                 rng=rng, **kwargs)
        return out

    def functional_call_with_state(self, params, buffers, *inputs, rng=None,
                                   **kwargs):
        """Like functional_call but also returns the post-call buffer arrays
        (BatchNorm running stats etc.), which the caller must carry — inside a
        traced step the in-place buffer mutation is rolled back on exit."""
        import contextlib as _ctx
        from ...core.random import key_context
        named_b = dict(self.named_buffers())
        with self._bound_state(params, buffers):
            with no_grad():
                rng_ctx = key_context(rng) if rng is not None else \
                    _ctx.nullcontext()
                with rng_ctx:
                    wrapped = [Tensor(x) if not isinstance(x, Tensor) else x
                               for x in inputs]
                    out = self(*wrapped, **kwargs)
            new_buffers = {k: named_b[k].data if k in named_b
                           else buffers[k] for k in buffers}
        out = jax.tree_util.tree_map(
            lambda o: o.data if isinstance(o, Tensor) else o, out,
            is_leaf=lambda o: isinstance(o, Tensor))
        return out, new_buffers

    def clear_gradients(self):
        for p in self.parameters():
            p.clear_grad()

    def full_name(self):
        return self._name_scope

    def __repr__(self):
        extra = []
        for name, layer in self._sub_layers.items():
            extra.append(f"  ({name}): {layer.__class__.__name__}")
        body = "\n".join(extra)
        return f"{self.__class__.__name__}(\n{body}\n)" if body else \
            f"{self.__class__.__name__}()"


class _HookRemoveHelper:
    def __init__(self, hooks, hook_id):
        self._hooks = hooks
        self._id = hook_id

    def remove(self):
        self._hooks.pop(self._id, None)


class LayerList(Layer):
    def __init__(self, sublayers=None):
        super().__init__()
        if sublayers is not None:
            for i, l in enumerate(sublayers):
                self.add_sublayer(str(i), l)

    def __len__(self):
        return len(self._sub_layers)

    def __getitem__(self, idx):
        if isinstance(idx, slice):
            return LayerList(list(self._sub_layers.values())[idx])
        keys = list(self._sub_layers.keys())
        return self._sub_layers[keys[idx]]

    def __setitem__(self, idx, layer):
        keys = list(self._sub_layers.keys())
        self.add_sublayer(keys[idx], layer)

    def __iter__(self):
        return iter(self._sub_layers.values())

    def append(self, layer):
        self.add_sublayer(str(len(self._sub_layers)), layer)
        return self

    def insert(self, index, layer):
        layers = list(self._sub_layers.values())
        layers.insert(index, layer)
        self._sub_layers.clear()
        for i, l in enumerate(layers):
            self._sub_layers[str(i)] = l

    def extend(self, layers):
        for l in layers:
            self.append(l)
        return self


class Sequential(Layer):
    def __init__(self, *layers):
        super().__init__()
        if len(layers) == 1 and isinstance(layers[0], (list, tuple)) and \
                layers[0] and isinstance(layers[0][0], tuple):
            for name, layer in layers[0]:
                self.add_sublayer(name, layer)
        else:
            for i, layer in enumerate(layers):
                if isinstance(layer, tuple):
                    self.add_sublayer(layer[0], layer[1])
                else:
                    self.add_sublayer(str(i), layer)

    def __getitem__(self, idx):
        keys = list(self._sub_layers.keys())
        return self._sub_layers[keys[idx]]

    def __len__(self):
        return len(self._sub_layers)

    def __iter__(self):
        return iter(self._sub_layers.values())

    def forward(self, x):
        for layer in self._sub_layers.values():
            x = layer(x)
        return x


class ParameterList(Layer):
    def __init__(self, parameters=None):
        super().__init__()
        if parameters is not None:
            for i, p in enumerate(parameters):
                self.add_parameter(str(i), p)

    def __len__(self):
        return len(self._parameters)

    def __getitem__(self, idx):
        keys = list(self._parameters.keys())
        return self._parameters[keys[idx]]

    def __iter__(self):
        return iter(self._parameters.values())

    def append(self, parameter):
        self.add_parameter(str(len(self._parameters)), parameter)
        return self


class LayerDict(Layer):
    """Ordered dict of sublayers (reference: nn/layer/container.py
    LayerDict)."""

    def __init__(self, sublayers=None):
        super().__init__()
        if sublayers is not None:
            self.update(sublayers)

    def __getitem__(self, key):
        return self._sub_layers[key]

    def __setitem__(self, key, sublayer):
        self.add_sublayer(key, sublayer)

    def __delitem__(self, key):
        del self._sub_layers[key]

    def __len__(self):
        return len(self._sub_layers)

    def __iter__(self):
        return iter(self._sub_layers)

    def __contains__(self, key):
        return key in self._sub_layers

    def clear(self):
        self._sub_layers.clear()

    def pop(self, key):
        v = self._sub_layers[key]
        del self._sub_layers[key]
        return v

    def keys(self):
        return self._sub_layers.keys()

    def items(self):
        return self._sub_layers.items()

    def values(self):
        return self._sub_layers.values()

    def update(self, sublayers):
        pairs = (sublayers.items() if hasattr(sublayers, "items")
                 else sublayers)
        for k, v in pairs:
            self.add_sublayer(k, v)
        return self
