"""Normalization layers (reference: python/paddle/nn/layer/norm.py)."""
from __future__ import annotations

from ...core.tensor import Tensor
from .. import functional as F
from .. import initializer as I
from .layers import Layer


class LayerNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-05, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = [normalized_shape]
        self._normalized_shape = list(normalized_shape)
        self._epsilon = epsilon
        if weight_attr is not False:
            self.weight = self.create_parameter(
                self._normalized_shape, attr=weight_attr,
                default_initializer=I.Constant(1.0))
        else:
            self.weight = None
        if bias_attr is not False:
            self.bias = self.create_parameter(
                self._normalized_shape, attr=bias_attr, is_bias=True)
        else:
            self.bias = None

    def forward(self, x):
        return F.layer_norm(x, self._normalized_shape, self.weight, self.bias,
                            self._epsilon)


class _BatchNormBase(Layer):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-05,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 use_global_stats=None, name=None):
        super().__init__()
        self._num_features = num_features
        self._momentum = momentum
        self._epsilon = epsilon
        self._data_format = data_format
        self._use_global_stats = use_global_stats
        if weight_attr is not False:
            self.weight = self.create_parameter(
                [num_features], attr=weight_attr,
                default_initializer=I.Constant(1.0))
        else:
            self.weight = None
        if bias_attr is not False:
            self.bias = self.create_parameter([num_features], attr=bias_attr,
                                              is_bias=True)
        else:
            self.bias = None
        from ...tensor.creation import zeros, ones
        self.register_buffer("_mean", zeros([num_features]))
        self.register_buffer("_variance", ones([num_features]))

    def forward(self, x):
        return F.batch_norm(x, self._mean, self._variance, self.weight,
                            self.bias, training=self.training,
                            momentum=self._momentum, epsilon=self._epsilon,
                            data_format=self._data_format,
                            use_global_stats=self._use_global_stats)


class BatchNorm(_BatchNormBase):
    """fluid-style BatchNorm (acts like BatchNorm2D but accepts act)."""

    def __init__(self, num_channels, act=None, momentum=0.9, epsilon=1e-05,
                 data_layout="NCHW", **kwargs):
        super().__init__(num_channels, momentum, epsilon,
                         data_format=data_layout)
        self._act = act

    def forward(self, x):
        out = super().forward(x)
        if self._act:
            out = getattr(F, self._act)(out)
        return out


class BatchNorm1D(_BatchNormBase):
    pass


class BatchNorm2D(_BatchNormBase):
    pass


class BatchNorm3D(_BatchNormBase):
    pass


class SyncBatchNorm(_BatchNormBase):
    """Cross-replica batch norm. Under pjit/SPMD the batch axis is sharded and
    XLA computes global statistics automatically when the reduction spans the
    mesh; in eager single-process mode this equals BatchNorm.
    (reference: python/paddle/nn/layer/norm.py SyncBatchNorm + c_sync ops)"""

    def __init__(self, num_features, momentum=0.9, epsilon=1e-05,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr,
                         bias_attr, data_format, None, name)

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        if isinstance(layer, _BatchNormBase) and not isinstance(
                layer, SyncBatchNorm):
            new = SyncBatchNorm(layer._num_features, layer._momentum,
                                layer._epsilon,
                                data_format=layer._data_format)
            if layer.weight is not None:
                new.weight.set_value(layer.weight.data)
            if layer.bias is not None:
                new.bias.set_value(layer.bias.data)
            new._mean.set_value(layer._mean.data)
            new._variance.set_value(layer._variance.data)
            return new
        for name, sub in list(layer._sub_layers.items()):
            layer.add_sublayer(name, cls.convert_sync_batchnorm(sub))
        return layer


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-05,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 name=None):
        super().__init__()
        self._num_groups = num_groups
        self._epsilon = epsilon
        self._data_format = data_format
        if weight_attr is not False:
            self.weight = self.create_parameter(
                [num_channels], attr=weight_attr,
                default_initializer=I.Constant(1.0))
        else:
            self.weight = None
        if bias_attr is not False:
            self.bias = self.create_parameter([num_channels], attr=bias_attr,
                                              is_bias=True)
        else:
            self.bias = None

    def forward(self, x):
        return F.group_norm(x, self._num_groups, self._epsilon, self.weight,
                            self.bias, self._data_format)


class InstanceNorm1D(Layer):
    def __init__(self, num_features, epsilon=1e-05, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format="NCL",
                 name=None):
        super().__init__()
        self._epsilon = epsilon
        if weight_attr is not False:
            self.scale = self.create_parameter(
                [num_features], attr=weight_attr,
                default_initializer=I.Constant(1.0))
        else:
            self.scale = None
        if bias_attr is not False:
            self.bias = self.create_parameter([num_features], attr=bias_attr,
                                              is_bias=True)
        else:
            self.bias = None

    def forward(self, x):
        return F.instance_norm(x, weight=self.scale, bias=self.bias,
                               eps=self._epsilon)


class InstanceNorm2D(InstanceNorm1D):
    def __init__(self, num_features, epsilon=1e-05, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 name=None):
        super().__init__(num_features, epsilon, momentum, weight_attr,
                         bias_attr, data_format)


class InstanceNorm3D(InstanceNorm1D):
    def __init__(self, num_features, epsilon=1e-05, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format="NCDHW",
                 name=None):
        super().__init__(num_features, epsilon, momentum, weight_attr,
                         bias_attr, data_format)


class LocalResponseNorm(Layer):
    def __init__(self, size, alpha=1e-4, beta=0.75, k=1.0,
                 data_format="NCHW", name=None):
        super().__init__()
        self.size = size
        self.alpha = alpha
        self.beta = beta
        self.k = k
        self.data_format = data_format

    def forward(self, x):
        return F.local_response_norm(x, self.size, self.alpha, self.beta,
                                     self.k, self.data_format)


class SpectralNorm(Layer):
    def __init__(self, weight_shape, dim=0, power_iters=1, epsilon=1e-12,
                 name=None):
        super().__init__()
        self._dim = dim
        self._power_iters = power_iters
        self._epsilon = epsilon
        import numpy as np
        h = weight_shape[dim]
        w = int(np.prod(weight_shape)) // h
        from ...tensor.random import randn
        self.register_buffer("weight_u", randn([h]))
        self.register_buffer("weight_v", randn([w]))

    def forward(self, weight):
        import jax.numpy as jnp
        from ...core.tensor import apply

        u, v = self.weight_u.data, self.weight_v.data
        dim, eps, iters = self._dim, self._epsilon, self._power_iters

        def f(w):
            wm = jnp.moveaxis(w, dim, 0).reshape(w.shape[dim], -1)
            uu, vv = u, v
            for _ in range(iters):
                vv = wm.T @ uu
                vv = vv / (jnp.linalg.norm(vv) + eps)
                uu = wm @ vv
                uu = uu / (jnp.linalg.norm(uu) + eps)
            sigma = uu @ wm @ vv
            return w / sigma

        return apply(f, weight)
