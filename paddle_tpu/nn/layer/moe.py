"""Mixture-of-Experts layer with expert parallelism.

Reference anchor: the reference ships ONLY the alltoall primitive
(python/paddle/distributed/collective.py:1456) and no MoE layer (SURVEY header) —
this is parity-plus, designed GSPMD-first (Switch/GLaM pattern):

- experts are stacked [E, ...] weight tensors whose leading dim carries
  partition_spec over the `ep` mesh axis;
- routing builds static-shaped dispatch/combine tensors (capacity-based top-k,
  einsum dispatch) so XLA sees fixed shapes and inserts the all_to_all when the
  token→expert einsum crosses the ep sharding;
- the load-balancing auxiliary loss (Switch eq. 4) is returned alongside.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ...core.tensor import apply
from .. import initializer as I
from .layers import Layer

EXPERT_AXIS = "ep"


def _top_k_dispatch(gates, capacity, top_k):
    """gates [T, E] → dispatch [T, E, C] bool-ish, combine [T, E, C] float,
    aux loss. Static shapes; Switch-Transformer routing."""
    T, E = gates.shape
    dispatch = jnp.zeros((T, E, capacity), jnp.float32)
    combine = jnp.zeros((T, E, capacity), jnp.float32)
    remaining = gates
    # aux loss uses the FULL softmax and the top-1 assignment fractions
    mask1_for_aux = None
    fill = jnp.zeros((E,), jnp.float32)  # slots used per expert so far
    for rank in range(top_k):
        idx = jnp.argmax(remaining, axis=-1)                 # [T]
        mask = jax.nn.one_hot(idx, E, dtype=jnp.float32)     # [T, E]
        if rank == 0:
            mask1_for_aux = mask
        # position of each token within its expert queue (respecting slots
        # already consumed by earlier ranks)
        pos = jnp.cumsum(mask, axis=0) - 1 + fill[None, :]   # [T, E]
        keep = (pos < capacity).astype(jnp.float32) * mask
        pos_kept = jnp.where(mask > 0, pos, 0).astype(jnp.int32)
        onehot_pos = jax.nn.one_hot(pos_kept, capacity,
                                    dtype=jnp.float32)       # [T, E, C]
        d = keep[..., None] * onehot_pos
        gate_vals = jnp.sum(gates * mask, axis=-1, keepdims=True)  # [T,1]
        dispatch = dispatch + d
        combine = combine + d * gate_vals[..., None]
        fill = fill + jnp.sum(keep, axis=0)
        remaining = remaining * (1.0 - mask)
    # normalize combine weights over selected experts
    denom = jnp.sum(combine, axis=(1, 2), keepdims=True)
    combine = combine / jnp.maximum(denom, 1e-9)
    # load-balance loss: E * sum_e f_e * p_e
    density = jnp.mean(mask1_for_aux, axis=0)        # fraction routed
    density_proxy = jnp.mean(gates, axis=0)          # mean gate prob
    aux = E * jnp.sum(density * density_proxy)
    return dispatch, combine, aux


def _moe_core(x, gate_w, w1, b1, w2, b2, top_k, capacity_factor, activation,
              n_experts, exchange_in=None, exchange_out=None):
    """Shared MoE math: routing over `n_experts`, dispatch to [E, C, H]
    buffers, expert FFN, combine. The optional exchange hooks wrap the
    expert compute — identity for the GSPMD path, all_to_all pairs for the
    explicit expert-parallel path — so the routing/capacity math can never
    diverge between the two."""
    B, S, H = x.shape
    T = B * S
    xt = x.reshape(T, H)
    logits = (xt.astype(jnp.float32) @ gate_w.astype(jnp.float32))
    gates = jax.nn.softmax(logits, axis=-1)
    capacity = max(int(capacity_factor * T * top_k / n_experts), top_k)
    dispatch, combine, aux = _top_k_dispatch(gates, capacity, top_k)
    # token → expert buffers [E, C, H]; on the GSPMD path, crossing the ep
    # sharding here makes XLA emit the all_to_all
    expert_in = jnp.einsum("tec,th->ech", dispatch.astype(x.dtype), xt)
    if exchange_in is not None:
        expert_in = exchange_in(expert_in)
    h = activation(jnp.einsum("ech,ehf->ecf", expert_in, w1)
                   + b1[:, None, :].astype(x.dtype))
    expert_out = jnp.einsum("ecf,efh->ech", h, w2) \
        + b2[:, None, :].astype(x.dtype)
    if exchange_out is not None:
        expert_out = exchange_out(expert_out)
    out = jnp.einsum("tec,ech->th", combine.astype(x.dtype), expert_out)
    return out.reshape(B, S, H), aux.astype(jnp.float32)


def moe_forward(x, gate_w, w1, b1, w2, b2, top_k, capacity_factor,
                activation=jax.nn.gelu):
    """Pure MoE math over arrays. x: [B, S, H]; w1: [E, H, F]; w2: [E, F, H]."""
    return _moe_core(x, gate_w, w1, b1, w2, b2, top_k, capacity_factor,
                     activation, n_experts=w1.shape[0])


def moe_forward_ep(x, gate_w, w1, b1, w2, b2, top_k, capacity_factor,
                   activation=jax.nn.gelu, axis=EXPERT_AXIS):
    """Explicit expert-parallel MoE for MAPPED mesh axes (inside shard_map,
    where GSPMD cannot insert the all_to_all): the GShard dispatch done by
    hand. Each ep rank holds its local tokens [B_local, S, H] and its local
    experts w1 [E_local, H, F]; routing runs over the full E, then a tiled
    lax.all_to_all exchanges token buffers so every rank computes exactly
    its own experts over everyone's tokens, and the inverse all_to_all
    brings the results home (all_to_all is a permutation collective — its
    AD transpose is the inverse permutation, so grads are exact; expert-
    weight grads already sum over ALL ranks' tokens locally and need no
    cross-ep reduction; aux is a local-token statistic the caller averages
    over the ep (data) axis).

    Reference anchor: collective.py:1456 alltoall is the one MoE primitive
    the reference ships; this is its production use, Switch/GShard-style.
    """
    ep_n = jax.lax.psum(1, axis)  # static axis size
    E = w1.shape[0] * ep_n

    def exchange_in(expert_in):
        # split E into ep groups, concat on capacity → each rank now holds
        # [E_local, ep_n*C, H]: its experts, everyone's tokens
        return jax.lax.all_to_all(expert_in, axis, split_axis=0,
                                  concat_axis=1, tiled=True)

    def exchange_out(expert_out):
        # inverse: results home to the token-owning ranks [E, C, H]
        return jax.lax.all_to_all(expert_out, axis, split_axis=1,
                                  concat_axis=0, tiled=True)

    return _moe_core(x, gate_w, w1, b1, w2, b2, top_k, capacity_factor,
                     activation, n_experts=E, exchange_in=exchange_in,
                     exchange_out=exchange_out)


class MoELayer(Layer):
    """paddle.incubate-style MoE FFN (gate + stacked experts).

    usage:
        moe = MoELayer(d_model=512, d_hidden=2048, num_experts=8, top_k=2)
        out = moe(x)               # x: [B, S, d_model]
        aux = moe.aux_loss         # add to the training loss (scaled)
    """

    def __init__(self, d_model, d_hidden, num_experts, top_k=2,
                 capacity_factor=1.25, activation="gelu", gate=None,
                 name=None):
        super().__init__()
        self.num_experts = num_experts
        self.top_k = top_k
        self.capacity_factor = capacity_factor
        self._act = {"gelu": jax.nn.gelu, "relu": jax.nn.relu,
                     "silu": jax.nn.silu}[activation]
        self.gate_weight = self.create_parameter(
            [d_model, num_experts], default_initializer=I.XavierUniform())
        self.w1 = self.create_parameter(
            [num_experts, d_model, d_hidden],
            default_initializer=I.XavierUniform())
        self.b1 = self.create_parameter([num_experts, d_hidden], is_bias=True)
        self.w2 = self.create_parameter(
            [num_experts, d_hidden, d_model],
            default_initializer=I.XavierUniform())
        self.b2 = self.create_parameter([num_experts, d_model], is_bias=True)
        for p in (self.w1, self.b1, self.w2, self.b2):
            p.partition_spec = P(EXPERT_AXIS)
        self.aux_loss = None

    def forward(self, x):
        top_k, cf, act = self.top_k, self.capacity_factor, self._act
        # inside a shard_map with the ep axis mapped (pipeline stage fns),
        # GSPMD can't insert the all_to_all — take the explicit path on the
        # rank-local expert shards (mp_layers' axis_context pattern)
        from ...distributed.collective import current_axes, in_axis_context
        explicit_ep = in_axis_context() and EXPERT_AXIS in current_axes()
        fwd = moe_forward_ep if explicit_ep else moe_forward

        def f(xa, gw, w1, b1, w2, b2):
            return fwd(xa, gw, w1, b1, w2, b2, top_k, cf, act)

        out, aux = apply(f, x, self.gate_weight, self.w1, self.b1, self.w2,
                         self.b2)
        self.aux_loss = aux
        return out
