"""paddle.distributed.utils (reference:
python/paddle/distributed/utils.py — cluster/pod plumbing helpers shared by
the launchers)."""
from __future__ import annotations

import logging
import socket
from contextlib import closing

from .launch import Pod, get_cluster  # noqa: F401  (reference re-exports)

__all__ = ["get_logger", "get_host_name_ip", "find_free_ports",
           "terminate_local_procs", "add_arguments", "Pod", "get_cluster"]


def get_logger(log_level=20, name="root"):
    logger = logging.getLogger(name)
    logger.setLevel(log_level)
    if not logger.handlers:
        h = logging.StreamHandler()
        h.setFormatter(logging.Formatter(
            "%(asctime)s-%(levelname)s: %(message)s"))
        logger.addHandler(h)
    return logger


def get_host_name_ip():
    try:
        host = socket.gethostname()
        return host, socket.gethostbyname(socket.getfqdn(host))
    except OSError:
        return None


def find_free_ports(num):
    """Reserve `num` currently-free TCP ports (launch rendezvous)."""
    ports = set()
    for _ in range(num * 10):
        if len(ports) >= num:
            break
        with closing(socket.socket(socket.AF_INET,
                                   socket.SOCK_STREAM)) as s:
            s.bind(("", 0))
            ports.add(s.getsockname()[1])
    return ports if len(ports) >= num else None


def terminate_local_procs(procs):
    """Terminate launcher children (launch watch-loop failure path)."""
    for p in procs:
        proc = getattr(p, "proc", p)
        if proc is not None and proc.poll() is None:
            proc.terminate()
    for p in procs:
        proc = getattr(p, "proc", p)
        if proc is not None:
            try:
                proc.wait(timeout=10)
            except Exception:
                proc.kill()


def add_arguments(argname, type, default, help, argparser, **kwargs):  # noqa: A002
    argparser.add_argument("--" + argname, default=default, type=type,
                           help=help + " Default: %(default)s.", **kwargs)
