"""Collective communication API.

Reference: python/paddle/distributed/collective.py (all_reduce:415, all_gather:589,
reduce_scatter, alltoall:1456, send:1528/recv:1578, broadcast:348, new_group:208) —
each emitting a `c_*` op bound to a ring_id → NCCLCommContext.

TPU-native contract (SURVEY §2.4): c_allreduce_sum ↔ lax.psum, c_allgather ↔
lax.all_gather, c_reducescatter ↔ lax.psum_scatter, alltoall ↔ lax.all_to_all,
send_v2/recv_v2 ↔ lax.ppermute — *axis names on a jax Mesh replace ring ids*, and
XLA schedules the ICI transfers (no streams/events).

Execution contexts:
1. Inside shard_map (the real multi-chip path): ops lower to lax collectives over
   the ambient mesh axis. `axis_ctx` tracks which axes the enclosing runner mapped.
2. Eager, single process: groups of size 1 → identity (matching the reference's
   behavior when world_size == 1). This keeps user scripts runnable on one chip.
"""
from __future__ import annotations

import contextlib
import threading
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
from jax import lax

from ..core.tensor import Tensor, apply
from ..tensor.creation import _t
from .parallel_env import ParallelEnv, get_rank, get_world_size


class ReduceOp:
    SUM = 0
    MAX = 1
    MIN = 2
    PROD = 3
    AVG = 4


class Group:
    """A communication group. Under SPMD a group is a mesh-axis name; `ranks`
    kept for API parity/introspection."""

    def __init__(self, rank: int, nranks: int, id: int = 0,
                 ranks: Optional[List[int]] = None,
                 axis_name: Optional[str] = None):
        self.rank = rank
        self.nranks = nranks
        self.id = id
        self.ranks = ranks or list(range(nranks))
        self.axis_name = axis_name

    @property
    def world_size(self):
        return self.nranks

    def get_group_rank(self, rank):
        return self.ranks.index(rank) if rank in self.ranks else -1

    def is_member(self):
        return True

    def __repr__(self):
        return (f"Group(rank={self.rank}, nranks={self.nranks}, "
                f"axis={self.axis_name})")


_GROUP_COUNTER = [0]
_DEFAULT_GROUP: List[Optional[Group]] = [None]


class _AxisCtx(threading.local):
    def __init__(self):
        self.axes: tuple = ()       # axis names mapped by the enclosing shard_map
        self.primary: Optional[str] = None


_CTX = _AxisCtx()


@contextlib.contextmanager
def axis_context(axes: Sequence[str], primary: Optional[str] = None):
    """Entered by parallel runners (shard_map wrappers) so collective calls in
    model code know which mesh axes are live."""
    prev = (_CTX.axes, _CTX.primary)
    _CTX.axes = tuple(axes)
    _CTX.primary = primary or (axes[0] if axes else None)
    try:
        yield
    finally:
        _CTX.axes, _CTX.primary = prev


def in_axis_context() -> bool:
    return bool(_CTX.axes)


def current_axes():
    return _CTX.axes


def _resolve_axis(group) -> Optional[str]:
    if isinstance(group, str):
        return group
    if group is not None and getattr(group, "axis_name", None):
        if _CTX.axes and group.axis_name in _CTX.axes:
            return group.axis_name
        if _CTX.axes:
            return None  # axis not mapped here → treat as trivial group
        return group.axis_name if _CTX.axes else None
    return _CTX.primary


def get_group(id=0):
    return _DEFAULT_GROUP[0]


def new_group(ranks=None, backend=None, axis_name=None):
    """Reference collective.py:208. Under SPMD the meaningful handle is the mesh
    axis; arbitrary rank lists are retained for bookkeeping only."""
    _GROUP_COUNTER[0] += 1
    gid = _GROUP_COUNTER[0]
    rank = get_rank()
    if ranks is None:
        ranks = list(range(get_world_size()))
    grp_rank = ranks.index(rank) if rank in ranks else -1
    return Group(grp_rank, len(ranks), gid, list(ranks), axis_name)


def _group_size(group) -> int:
    axis = _resolve_axis(group)
    if axis is not None and _CTX.axes:
        return -1  # dynamic (resolved by lax at trace time)
    if group is not None and not isinstance(group, str):
        return group.nranks
    return get_world_size()


# ---- core collectives ----

def _psum_prod(a, axis):
    """Sign-correct product reduction. Integers take an exact gather-and-
    multiply path; floats use psum-of-logs for magnitude (zeros handled by a
    zero-count psum, sign via parity of the negative count)."""
    if not jnp.issubdtype(a.dtype, jnp.floating):
        return jnp.prod(lax.all_gather(a, axis), axis=0)
    zero = a == 0
    any_zero = lax.psum(zero.astype(jnp.int32), axis) > 0
    neg = lax.psum((a < 0).astype(jnp.int32), axis)
    sign = jnp.where(neg % 2 == 0, 1.0, -1.0).astype(a.dtype)
    safe = jnp.where(zero, 1.0, jnp.abs(a))
    mag = jnp.exp(lax.psum(jnp.log(safe.astype(jnp.float32)), axis))
    return jnp.where(any_zero, jnp.zeros_like(a), sign * mag.astype(a.dtype))


def all_reduce(tensor, op=ReduceOp.SUM, group=None, use_calc_stream=True,
               sync_op=True):
    axis = _resolve_axis(group)
    if axis is not None and _CTX.axes:
        fns = {ReduceOp.SUM: lambda a: lax.psum(a, axis),
               ReduceOp.MAX: lambda a: lax.pmax(a, axis),
               ReduceOp.MIN: lambda a: lax.pmin(a, axis),
               ReduceOp.AVG: lambda a: lax.pmean(a, axis),
               ReduceOp.PROD: lambda a: _psum_prod(a, axis)}
        out = apply(fns[op], _t(tensor))
        tensor.data = out.data
        return tensor
    # trivial group (size 1): identity
    return tensor


def reduce(tensor, dst, op=ReduceOp.SUM, group=None, use_calc_stream=True):
    # On TPU a reduce-to-one is a psum; non-dst ranks simply ignore the value.
    return all_reduce(tensor, op, group, use_calc_stream)


def all_gather(tensor_list, tensor, group=None, use_calc_stream=True,
               axis=0):
    ax = _resolve_axis(group)
    t = _t(tensor)
    if ax is not None and _CTX.axes:
        out = apply(lambda a: lax.all_gather(a, ax), t)
        n = out.shape[0]
        if isinstance(tensor_list, list):
            tensor_list.clear()
            tensor_list.extend(out[i] for i in range(n))
        return out
    if isinstance(tensor_list, list):
        tensor_list.clear()
        tensor_list.append(t)
    return t


def all_gather_concat(tensor, group=None, concat_axis=0):
    """Helper returning the concatenated gather (common TP use)."""
    ax = _resolve_axis(group)
    t = _t(tensor)
    if ax is not None and _CTX.axes:
        return apply(lambda a: lax.all_gather(a, ax, axis=concat_axis,
                                              tiled=True), t)
    return t


def reduce_scatter(tensor, tensor_or_tensor_list, op=ReduceOp.SUM, group=None,
                   sync_op=True):
    ax = _resolve_axis(group)
    src = tensor_or_tensor_list
    if isinstance(src, (list, tuple)):
        from ..tensor.manipulation import concat
        src = concat([_t(s) for s in src], axis=0)
    src = _t(src)
    if ax is not None and _CTX.axes:
        out = apply(lambda a: lax.psum_scatter(a, ax, scatter_dimension=0,
                                               tiled=True), src)
        tensor.data = out.data
        return tensor
    tensor.data = src.data
    return tensor


def alltoall(in_tensor_list, out_tensor_list=None, group=None,
             use_calc_stream=True):
    """Reference collective.py:1456. Under shard_map: lax.all_to_all over the
    axis; list-of-tensors form maps to stacking on a new leading dim."""
    ax = _resolve_axis(group)
    if isinstance(in_tensor_list, (list, tuple)):
        from ..tensor.manipulation import stack
        stacked = stack([_t(t) for t in in_tensor_list], axis=0)
    else:
        stacked = _t(in_tensor_list)
    if ax is not None and _CTX.axes:
        out = apply(lambda a: lax.all_to_all(a, ax, split_axis=0,
                                             concat_axis=0, tiled=True),
                    stacked)
    else:
        out = stacked
    if isinstance(out_tensor_list, list):
        n = (len(in_tensor_list) if isinstance(in_tensor_list, (list, tuple))
             else out.shape[0])
        out_tensor_list.clear()
        from ..tensor.manipulation import split as _split
        pieces = _split(out, n, axis=0)
        out_tensor_list.extend(pieces)
    return out


def all_to_all_single(tensor, group=None, split_axis=0, concat_axis=0):
    ax = _resolve_axis(group)
    t = _t(tensor)
    if ax is not None and _CTX.axes:
        return apply(lambda a: lax.all_to_all(a, ax, split_axis=split_axis,
                                              concat_axis=concat_axis,
                                              tiled=True), t)
    return t


def broadcast(tensor, src, group=None, use_calc_stream=True):
    ax = _resolve_axis(group)
    if ax is not None and _CTX.axes:
        src_local = (group.get_group_rank(src)
                     if group is not None and not isinstance(group, str)
                     and src in getattr(group, "ranks", []) else src)

        def f(a):
            idx = lax.axis_index(ax)
            masked = jnp.where(idx == src_local, a, jnp.zeros_like(a))
            return lax.psum(masked, ax)

        out = apply(f, _t(tensor))
        tensor.data = out.data
        return tensor
    return tensor


def scatter(tensor, tensor_list=None, src=0, group=None, use_calc_stream=True):
    ax = _resolve_axis(group)
    if ax is not None and _CTX.axes and tensor_list is not None:
        from ..tensor.manipulation import stack
        stacked = stack([_t(t) for t in tensor_list], axis=0)

        def f(a):
            idx = lax.axis_index(ax)
            # broadcast full stack from src then select own slice
            src_stack = lax.psum(
                jnp.where(idx == src, a, jnp.zeros_like(a)), ax)
            return src_stack[idx]

        out = apply(f, stacked)
        tensor.data = out.data
        return tensor
    if tensor_list:
        tensor.data = _t(tensor_list[src]).data
    return tensor


def send(tensor, dst=0, group=None, use_calc_stream=True):
    """Point-to-point send (send_v2 analog). SPMD has no one-sided p2p: a
    send/recv pair is one lax.ppermute. The pipeline layer calls ppermute_to
    directly; a bare `send` under shard_map permutes to the absolute dst index
    on the group axis and the matching `recv` is the identity on that value."""
    ax = _resolve_axis(group)
    if ax is not None and _CTX.axes:
        return ppermute_to(tensor, dst, ax, mode="to")
    return tensor


def recv(tensor, src=0, group=None, use_calc_stream=True):
    return tensor


def ppermute_to(tensor, shift_or_dst, axis, mode="shift"):
    """lax.ppermute helper: mode='shift' rotates by `shift`; the pipeline layer
    uses this for stage-to-stage activation transfer."""
    t = _t(tensor)

    def f(a):
        n = lax.psum(1, axis)
        if mode == "shift":
            perm = [(i, (i + shift_or_dst) % n) for i in range(n)]
        else:
            perm = [(i, shift_or_dst) for i in range(n)]
        return lax.ppermute(a, axis, perm)

    return apply(f, t)


def barrier(group=None):
    if _CTX.axes:
        return
    # host-level barrier across processes
    try:
        from jax.experimental import multihost_utils
        if get_world_size() > 1:
            multihost_utils.sync_global_devices("paddle_tpu_barrier")
    except Exception:
        pass


def wait(tensor, group=None, use_calc_stream=True):
    if isinstance(tensor, Tensor):
        jax.block_until_ready(tensor.data)
    return tensor


# ---- TP internals (reference collective.py:748-990) ----

def _c_identity(tensor, group=None):
    """Forward no-op, backward all-reduce (column-parallel input)."""
    ax = _resolve_axis(group)
    t = _t(tensor)
    if ax is None or not _CTX.axes:
        return t

    @jax.custom_vjp
    def f(a):
        return a

    def fwd(a):
        return a, None

    def bwd(_, g):
        return (lax.psum(g, ax),)

    f.defvjp(fwd, bwd)
    return apply(f, t)


def _mp_allreduce(tensor, op=ReduceOp.SUM, group=None):
    """Forward all-reduce, backward no-op (row-parallel output)."""
    ax = _resolve_axis(group)
    t = _t(tensor)
    if ax is None or not _CTX.axes:
        return t

    @jax.custom_vjp
    def f(a):
        return lax.psum(a, ax)

    def fwd(a):
        return lax.psum(a, ax), None

    def bwd(_, g):
        return (g,)

    f.defvjp(fwd, bwd)
    return apply(f, t)


def _c_concat(tensor, group=None):
    """all-gather along last dim (gather_output of column-parallel linear)."""
    ax = _resolve_axis(group)
    t = _t(tensor)
    if ax is None or not _CTX.axes:
        return t
    return apply(lambda a: lax.all_gather(a, ax, axis=a.ndim - 1, tiled=True),
                 t)


def _c_split(tensor, group=None):
    """keep own shard of last dim (input of row-parallel linear)."""
    ax = _resolve_axis(group)
    t = _t(tensor)
    if ax is None or not _CTX.axes:
        return t

    def f(a):
        n = lax.psum(1, ax)
        idx = lax.axis_index(ax)
        piece = a.shape[-1] // n
        return lax.dynamic_slice_in_dim(a, idx * piece, piece, axis=a.ndim - 1)

    return apply(f, t)


def _c_softmax_with_cross_entropy(logits, label, group=None,
                                  ignore_index=-100):
    """Vocab-sharded softmax-CE (reference
    c_softmax_with_cross_entropy_op.cu): logits sharded on the class dim over
    the mp axis; computes global logsumexp via psum without materializing the
    full vocab."""
    ax = _resolve_axis(group)
    lg, lb = _t(logits), _t(label)
    if ax is None or not _CTX.axes:
        from ..nn.functional.loss import softmax_with_cross_entropy
        return softmax_with_cross_entropy(lg, lb, ignore_index=ignore_index)

    def _fwd_math(a, y):
        n_shard = a.shape[-1]
        idx = lax.axis_index(ax)
        vocab_start = idx * n_shard
        a32 = a.astype(jnp.float32)
        local_max = jnp.max(a32, -1, keepdims=True)
        gmax = lax.pmax(local_max, ax)
        sumexp = jnp.sum(jnp.exp(a32 - gmax), -1, keepdims=True)
        gsum = lax.psum(sumexp, ax)
        logz = jnp.log(gsum) + gmax
        y = y.astype(jnp.int32)
        squeeze = (y.ndim == a.ndim and y.shape[-1] == 1)
        yy = y[..., 0] if squeeze else y
        local_label = yy - vocab_start
        in_range = (local_label >= 0) & (local_label < n_shard)
        safe = jnp.clip(local_label, 0, n_shard - 1)
        picked = jnp.take_along_axis(a32, safe[..., None], axis=-1)[..., 0]
        local_logit = jnp.where(in_range, picked, 0.0)
        target_logit = lax.psum(local_logit, ax)
        loss = logz[..., 0] - target_logit
        loss = jnp.where(yy == ignore_index, 0.0, loss)
        out = loss[..., None] if squeeze else loss
        return out, (a, logz, safe, in_range, yy)

    # Analytic gradient (c_softmax_with_cross_entropy_op.cu bwd):
    # d a_local = (softmax_local - onehot_local) * g. Hand-written because
    # under shard_map(check_vma=False) AD transposes raw psum to psum,
    # double-counting already-replicated cotangents.
    @jax.custom_vjp
    def f(a, y):
        return _fwd_math(a, y)[0]

    def f_fwd(a, y):
        out, res = _fwd_math(a, y)
        return out, res

    def f_bwd(res, g):
        a, logz, safe, in_range, yy = res
        squeeze = g.ndim == a.ndim  # out was loss[..., None]
        gg = g[..., 0] if squeeze else g
        gg = jnp.where(yy == ignore_index, 0.0, gg).astype(jnp.float32)
        a32 = a.astype(jnp.float32)
        p = jnp.exp(a32 - logz)  # local softmax shard
        da = p * gg[..., None]
        sub = jnp.where(in_range, gg, 0.0)
        da = da - jax.nn.one_hot(safe, a32.shape[-1],
                                 dtype=jnp.float32) * sub[..., None]
        return da.astype(a.dtype), None

    f.defvjp(f_fwd, f_bwd)
    return apply(f, lg, lb)


def get_default_group():
    if _DEFAULT_GROUP[0] is None:
        _DEFAULT_GROUP[0] = Group(get_rank(), get_world_size(), 0)
    return _DEFAULT_GROUP[0]


def destroy_process_group(group=None):
    pass
