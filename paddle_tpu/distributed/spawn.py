"""paddle.distributed.spawn analog (reference: distributed/spawn.py).

On TPU the normal model is one process per host (jax handles all local chips), so
spawn is mainly used by CPU-mesh tests; it forks `nprocs` processes with the
reference's PADDLE_* env contract.
"""
from __future__ import annotations

import multiprocessing as mp
import os


def _wrapper(func, rank, nprocs, base_port, args):
    os.environ["PADDLE_TRAINER_ID"] = str(rank)
    os.environ["PADDLE_TRAINERS_NUM"] = str(nprocs)
    endpoints = ",".join(f"127.0.0.1:{base_port + i}" for i in range(nprocs))
    os.environ["PADDLE_TRAINER_ENDPOINTS"] = endpoints
    os.environ["PADDLE_CURRENT_ENDPOINT"] = f"127.0.0.1:{base_port + rank}"
    func(*args)


def spawn(func, args=(), nprocs=1, join=True, daemon=False, **options):
    base_port = int(options.get("started_port", 35000))
    ctx = mp.get_context("spawn")
    procs = []
    for rank in range(nprocs):
        p = ctx.Process(target=_wrapper,
                        args=(func, rank, nprocs, base_port, args),
                        daemon=daemon)
        p.start()
        procs.append(p)
    if join:
        for p in procs:
            p.join()
        for p in procs:
            if p.exitcode != 0:
                raise RuntimeError(f"spawned process exited with {p.exitcode}")
    return procs
