"""paddle.distributed.cloud_utils (reference: distributed/cloud_utils.py —
derive the cluster layout from PaddleCloud environment variables)."""
from __future__ import annotations

import os

__all__ = ["get_cloud_cluster", "use_paddlecloud"]


def use_paddlecloud() -> bool:
    for k in ("PADDLE_TRAINERS_NUM", "POD_IP", "PADDLE_TRAINERS",
              "PADDLE_TRAINER_ID", "PADDLE_PORT"):
        if os.environ.get(k) is None:
            return False
    return True


def get_cloud_cluster(args_node_ips=None, device_mode=None,
                      devices_per_proc=None, args_port=None):
    """Cluster endpoints from the PaddleCloud env contract. Returns
    (node_ips, current_ip, trainer_endpoints)."""
    node_ips = (os.environ.get("PADDLE_TRAINERS", "") or
                args_node_ips or "127.0.0.1")
    if isinstance(node_ips, str):
        node_ips = [ip for ip in node_ips.split(",") if ip]
    node_ip = os.environ.get("POD_IP", node_ips[0])
    port = int(os.environ.get("PADDLE_PORT", args_port or 6170))
    n_proc = max(int(os.environ.get("PADDLE_TRAINERS_NUM", "1")), 1)
    n_nodes = max(len(node_ips), 1)
    per_node = -(-n_proc // n_nodes)  # ceil: never drop a trainer
    endpoints = [f"{ip}:{port + i}" for ip in node_ips
                 for i in range(per_node)][:n_proc]
    return node_ips, node_ip, endpoints
