"""TensorParallel model wrapper (reference: fleet/meta_parallel/tensor_parallel.py:40
— broadcasts params+inputs across the mp group at wrap time).

Under GSPMD the "broadcast" is the sharding declaration itself: replicated params
stay replicated, mp-sharded params (partition_spec on the model axis) are laid out
by parallelize(). Eager wrap is a passthrough."""
from __future__ import annotations

from ...nn.layer.layers import Layer


class TensorParallel(Layer):
    def __init__(self, layers, hcg=None, strategy=None):
        super().__init__()
        self._layers = layers
        self._hcg = hcg

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    def state_dict(self, *args, **kwargs):
        return self._layers.state_dict(*args, **kwargs)

    def set_state_dict(self, *args, **kwargs):
        return self._layers.set_state_dict(*args, **kwargs)

    def parameters(self, include_sublayers=True):
        return self._layers.parameters(include_sublayers)

    def named_parameters(self, prefix="", include_sublayers=True):
        return self._layers.named_parameters(prefix, include_sublayers)
