"""Megatron-style tensor-parallel layers.

Reference: fleet/meta_parallel/parallel_layers/mp_layers.py — VocabParallelEmbedding
:30, ColumnParallelLinear:97, RowParallelLinear:170, ParallelCrossEntropy:249.

TPU-native dual mode:
- GSPMD path (primary): parameters carry a PartitionSpec over the `model` axis
  (weight sharding declared, XLA inserts the collectives). `parallelize()` reads
  `param.partition_spec` when laying out the mesh. Layer math is written as plain
  dense ops — under pjit the sharded weights make XLA emit exactly the Megatron
  collectives (allreduce after row-parallel matmul, etc).
- shard_map path (explicit parity): when running under a shard_map runner with the
  `model` axis mapped and `explicit_tp=True`, the layers keep only their weight
  shard and call the _c_identity/_mp_allreduce custom-vjp collectives, matching the
  reference op-for-op (useful for tests asserting collective placement).
"""
from __future__ import annotations

import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ...core.tensor import Tensor, apply
from ...nn import functional as F
from ...nn import initializer as I
from ...nn.layer.layers import Layer
from ..collective import (_c_identity, _c_split, _mp_allreduce,
                          _c_softmax_with_cross_entropy, in_axis_context,
                          current_axes)
from ..topology import get_hybrid_communicate_group

MODEL_AXIS = "model"


def _mp_degree():
    hcg = get_hybrid_communicate_group()
    return hcg.get_model_parallel_world_size() if hcg else 1


def _explicit_tp() -> bool:
    return in_axis_context() and MODEL_AXIS in current_axes()


class VocabParallelEmbedding(Layer):
    """Embedding with the vocab dim sharded over `model` (mp_layers.py:30)."""

    def __init__(self, num_embeddings, embedding_dim, weight_attr=None,
                 mp_group=None, name=None):
        super().__init__()
        self.num_embeddings = num_embeddings
        self.embedding_dim = embedding_dim
        self.world_size = _mp_degree()
        assert num_embeddings % max(self.world_size, 1) == 0, (
            "vocab size must divide mp degree")
        # full logical weight; sharded on axis 0 by GSPMD
        self.weight = self.create_parameter(
            [num_embeddings, embedding_dim], attr=weight_attr,
            default_initializer=I.XavierNormal())
        self.weight.partition_spec = P(MODEL_AXIS, None)

    def forward(self, x):
        if _explicit_tp():
            # explicit mode: weight tensor holds the local shard inside
            # shard_map. The reduce goes through _mp_allreduce (custom-vjp:
            # fwd psum, bwd identity) — a raw lax.psum here would transpose
            # to another psum under check_vma=False and double-count the
            # replicated cotangent.
            def f(ids, w):
                from jax import lax
                n_shard = w.shape[0]
                idx = lax.axis_index(MODEL_AXIS)
                start = idx * n_shard
                local = ids.astype(jnp.int32) - start
                in_range = (local >= 0) & (local < n_shard)
                safe = jnp.clip(local, 0, n_shard - 1)
                out = jnp.take(w, safe, axis=0)
                return jnp.where(in_range[..., None], out, 0.0)

            return _mp_allreduce(apply(f, x, self.weight), group=MODEL_AXIS)
        return F.embedding(x, self.weight)


class ColumnParallelLinear(Layer):
    """W sharded on output dim (mp_layers.py:97): Y_local = X @ W_local."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=None, gather_output=True, mp_group=None, name=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.gather_output = gather_output
        self.world_size = _mp_degree()
        assert out_features % max(self.world_size, 1) == 0
        self.weight = self.create_parameter(
            [in_features, out_features], attr=weight_attr,
            default_initializer=I.XavierNormal())
        self.weight.partition_spec = P(None, MODEL_AXIS)
        if has_bias is None or has_bias:
            self.bias = self.create_parameter([out_features], is_bias=True)
            self.bias.partition_spec = P(MODEL_AXIS)
        else:
            self.bias = None

    def forward(self, x):
        if _explicit_tp():
            x = _c_identity(x, MODEL_AXIS)
            out = F.linear(x, self.weight, self.bias)
            if self.gather_output:
                from ..collective import _c_concat
                out = _c_concat(out, MODEL_AXIS)
            return out
        return F.linear(x, self.weight, self.bias)


class RowParallelLinear(Layer):
    """W sharded on input dim (mp_layers.py:170): Y = allreduce(X_local @ W_local)."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, input_is_parallel=False, mp_group=None,
                 name=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.input_is_parallel = input_is_parallel
        self.world_size = _mp_degree()
        assert in_features % max(self.world_size, 1) == 0
        self.weight = self.create_parameter(
            [in_features, out_features], attr=weight_attr,
            default_initializer=I.XavierNormal())
        self.weight.partition_spec = P(MODEL_AXIS, None)
        if has_bias:
            self.bias = self.create_parameter([out_features], is_bias=True)
            self.bias.partition_spec = P(None)
        else:
            self.bias = None

    def forward(self, x):
        if _explicit_tp():
            if not self.input_is_parallel:
                x = _c_split(x, MODEL_AXIS)
            out = F.linear(x, self.weight)  # bias added after reduce
            out = _mp_allreduce(out, group=MODEL_AXIS)
            if self.bias is not None:
                from ...tensor.math import add
                out = add(out, self.bias)
            return out
        return F.linear(x, self.weight, self.bias)


class ParallelCrossEntropy(Layer):
    """Vocab-sharded softmax CE (mp_layers.py:249 →
    c_softmax_with_cross_entropy_op.cu analog)."""

    def __init__(self, mp_group=None, name=None, ignore_index=-100):
        super().__init__()
        self.ignore_index = ignore_index

    def forward(self, input, label):
        if _explicit_tp():
            return _c_softmax_with_cross_entropy(input, label, MODEL_AXIS,
                                                 self.ignore_index)
        from ...nn.functional.loss import softmax_with_cross_entropy
        return softmax_with_cross_entropy(input, label)
