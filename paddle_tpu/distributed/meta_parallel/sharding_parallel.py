"""ShardingParallel wrapper (reference: fleet/meta_parallel/sharding_parallel.py:33).

ZeRO sharding on TPU is a sharding declaration on optimizer state / grads / params
over the `sharding` mesh axis (see paddle_tpu.parallel.sharding); the model wrapper
itself is a passthrough."""
from __future__ import annotations

from ...nn.layer.layers import Layer


class ShardingParallel(Layer):
    def __init__(self, layers, hcg=None, strategy=None):
        super().__init__()
        self._layers = layers
        self._hcg = hcg

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    def state_dict(self, *args, **kwargs):
        return self._layers.state_dict(*args, **kwargs)

    def set_state_dict(self, *args, **kwargs):
        return self._layers.set_state_dict(*args, **kwargs)

    def parameters(self, include_sublayers=True):
        return self._layers.parameters(include_sublayers)

    def named_parameters(self, prefix="", include_sublayers=True):
        return self._layers.named_parameters(prefix, include_sublayers)
