"""Pipeline layer description & segmentation.

Reference: fleet/meta_parallel/parallel_layers/pp_layers.py — LayerDesc:44,
SharedLayerDesc:62 (tied embeddings), SegmentLayers:23, PipelineLayer:76 with
allreduce_shared_weight_gradients:188.

TPU-native: PipelineLayer keeps the full layer list plus the stage segmentation;
the SPMD pipeline runner (pipeline_parallel.py) turns the stages into a
lax.scan-over-microbatches with ppermute stage transfer, or — on a single host —
runs stages sequentially (degenerate pp=1 case).
"""
from __future__ import annotations

from typing import Callable, List, Optional, Union

from ...nn.layer.layers import Layer, LayerList
from ..topology import get_hybrid_communicate_group


class LayerDesc:
    def __init__(self, layer_func, *inputs, **kwargs):
        self.layer_func = layer_func
        self.inputs = inputs
        self.kwargs = kwargs
        if not issubclass(layer_func, Layer):
            raise TypeError("LayerDesc expects a Layer subclass")

    def build_layer(self):
        return self.layer_func(*self.inputs, **self.kwargs)

    def __repr__(self):
        return f"LayerDesc({self.layer_func.__name__})"


class SharedLayerDesc(LayerDesc):
    """Tied-weight layer shared across stages (e.g. embedding/logits)."""

    def __init__(self, key, layer_func, forward_func=None, shared_weight_attr="weight",
                 *inputs, **kwargs):
        super().__init__(layer_func, *inputs, **kwargs)
        self.layer_name = key
        self.forward_func = forward_func
        self.shared_weight_attr = shared_weight_attr


class SegmentLayers:
    """Split N layers into `num_parts` contiguous stages (pp_layers.py:23)."""

    def __init__(self, layers_desc, num_parts, method="uniform"):
        self.layers_desc = layers_desc
        self.num_parts = num_parts
        self.method = method
        assert len(layers_desc) >= num_parts

    def do_segment(self) -> List[int]:
        if self.method == "uniform":
            return self.uniform(len(self.layers_desc), self.num_parts)
        if self.method.startswith("layer:"):
            # segment so each stage holds an equal count of the named layer
            name = self.method.split(":", 1)[1]
            weights = [1 if getattr(d, "layer_func", None) is not None
                       and getattr(d.layer_func, "__name__", "") == name else 0
                       for d in self.layers_desc]
            total = sum(weights)
            per = total // self.num_parts
            result = [0]
            acc = 0
            for i, w in enumerate(weights):
                acc += w
                if len(result) < self.num_parts and acc >= per * len(result):
                    result.append(i + 1)
            while len(result) <= self.num_parts:
                result.append(len(self.layers_desc))
            result[-1] = len(self.layers_desc)
            return result
        raise ValueError(f"unknown segment method {self.method}")

    @staticmethod
    def uniform(num_items, num_parts):
        result = [0] * (num_parts + 1)
        part_size = num_items // num_parts
        extra = num_items % num_parts
        for i in range(1, num_parts + 1):
            result[i] = result[i - 1] + part_size + (1 if i <= extra else 0)
        return result


class PipelineLayer(Layer):
    def __init__(self, layers, num_stages=None, topology=None,
                 loss_fn=None, seg_method="uniform", recompute_interval=0,
                 recompute_ctx=None):
        super().__init__()
        self._loss_fn = loss_fn
        self._topo = topology
        hcg = get_hybrid_communicate_group()
        if num_stages is None:
            num_stages = (hcg.get_pipe_parallel_world_size() if hcg else 1)
        self._num_stages = num_stages
        self._stage_id = hcg.get_stage_id() if hcg else 0
        self._recompute_interval = recompute_interval

        self._layers_desc = list(layers)
        seg = SegmentLayers(self._layers_desc, num_stages, seg_method)
        self.segment_parts = seg.do_segment()

        # Build ALL layers (SPMD: every host traces the whole program; XLA
        # places stages by sharding. The per-stage view is kept for the
        # explicit pipeline runner and for parity introspection.)
        self._shared_layers = {}
        built = []
        for i, d in enumerate(self._layers_desc):
            if isinstance(d, SharedLayerDesc):
                if d.layer_name not in self._shared_layers:
                    self._shared_layers[d.layer_name] = d.build_layer()
                built.append((self._shared_layers[d.layer_name],
                              d.forward_func))
            elif isinstance(d, LayerDesc):
                built.append((d.build_layer(), None))
            elif isinstance(d, Layer):
                built.append((d, None))
            elif callable(d):
                built.append((d, None))
            else:
                raise TypeError(f"bad pipeline entry {d!r}")
        self.run_function = built
        for i, (l, _) in enumerate(built):
            if isinstance(l, Layer):
                self.add_sublayer(str(i), l)

    def get_stage_from_index(self, layer_idx):
        for stage in range(self._num_stages):
            if (self.segment_parts[stage] <= layer_idx
                    < self.segment_parts[stage + 1]):
                return stage
        return self._num_stages - 1

    def stage_layers(self, stage_id=None):
        s = self._stage_id if stage_id is None else stage_id
        lo, hi = self.segment_parts[s], self.segment_parts[s + 1]
        return self.run_function[lo:hi]

    def forward(self, x, stage_id=None):
        """Run all stages (full model) or one stage's segment."""
        entries = (self.run_function if stage_id is None
                   else self.stage_layers(stage_id))
        for layer, fwd in entries:
            if fwd is not None:
                x = fwd(layer, x)
            elif isinstance(layer, Layer) or callable(layer):
                x = layer(x)
        return x

    def allreduce_shared_weight_gradients(self):
        """pp_layers.py:188 — tied-weight grads are reduced across the stages
        that share them. Under full-program SPMD the shared layer object is one
        parameter, so grads already accumulate; explicit mode handles it in the
        runner."""
        return

    @property
    def parameters_by_stage(self):
        out = []
        for s in range(self._num_stages):
            ps = []
            for layer, _ in self.stage_layers(s):
                if isinstance(layer, Layer):
                    ps.extend(layer.parameters())
            out.append(ps)
        return out
