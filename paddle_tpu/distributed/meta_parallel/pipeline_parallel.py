"""Pipeline-parallel execution.

Reference: fleet/meta_parallel/pipeline_parallel.py:32 (PipelineParallel,
train_batch:109 — F-then-B over micro-batches with p2p send/recv) and the static
1F1B schedule in framework/section_worker.cc:149-183.

TPU-native redesign: this dygraph wrapper runs micro-batches through the full
layer stack (gradient accumulation, no stage distribution) and exists for the
eager-API parity surface only. The real pipeline — a 1F1B ppermute schedule
with stage-sharded weights (section_worker.cc parity) — lives in
paddle_tpu.parallel.pipeline (run_1f1b / PipelinedTrainStep) and is what
parallelize() dispatches to when the mesh's pipe axis is > 1.
"""
from __future__ import annotations

from typing import Optional

from ...core.tensor import Tensor
from ...nn.layer.layers import Layer
from ...tensor.manipulation import split as tensor_split
from ..topology import get_hybrid_communicate_group
from .pp_layers import PipelineLayer


class PipelineParallel(Layer):
    def __init__(self, layers, hcg=None, strategy=None):
        super().__init__()
        if not isinstance(layers, PipelineLayer):
            raise TypeError("PipelineParallel expects a PipelineLayer")
        self._layers = layers
        self._hcg = hcg or get_hybrid_communicate_group()
        self._strategy = strategy
        cfg = getattr(strategy, "pipeline_configs", None)
        self.micro_batch_size = getattr(cfg, "micro_batch_size", 1) if cfg else 1
        self.accumulate_steps = getattr(cfg, "accumulate_steps", 1) if cfg else 1
        self.total_loss = None

    def forward(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def _load_micro_batch(self, data, idx):
        inputs, labels = data
        begin = idx * self.micro_batch_size
        end = begin + self.micro_batch_size
        return inputs[begin:end], labels[begin:end]

    def train_batch(self, data, optimizer, lr_scheduler=None, scaler=None):
        """F-then-B over micro-batches with grad accumulation
        (pipeline_parallel.py:109 semantics; loss averaged over micro-batches).
        """
        inputs, labels = data
        total = inputs.shape[0]
        n_micro = max(total // self.micro_batch_size, 1)
        self.total_loss = None
        loss_fn = self._layers._loss_fn
        for i in range(n_micro):
            x, y = self._load_micro_batch(data, i)
            out = self._layers(x)
            loss = loss_fn(out, y) if loss_fn is not None else out
            from ...tensor.math import divide
            scaled = divide(loss, float(n_micro))
            if scaler is not None:
                scaler.scale(scaled).backward()
            else:
                scaled.backward()
            if self.total_loss is None:
                self.total_loss = loss.detach()
            else:
                from ...tensor.math import add
                self.total_loss = add(self.total_loss, loss.detach())
        self._layers.allreduce_shared_weight_gradients()
        if scaler is not None:
            scaler.step(optimizer)
        else:
            optimizer.step()
        optimizer.clear_grad()
        if lr_scheduler is not None:
            lr_scheduler.step()
        from ...tensor.math import divide
        return divide(self.total_loss, float(n_micro))

    def eval_batch(self, data, compute_loss=True):
        inputs, labels = data
        out = self._layers(inputs)
        if compute_loss and self._layers._loss_fn is not None:
            return self._layers._loss_fn(out, labels)
        return out

    def state_dict(self, *args, **kwargs):
        return self._layers.state_dict(*args, **kwargs)

    def set_state_dict(self, *args, **kwargs):
        return self._layers.set_state_dict(*args, **kwargs)

    def parameters(self, include_sublayers=True):
        return self._layers.parameters(include_sublayers)

    def named_parameters(self, prefix="", include_sublayers=True):
        return self._layers.named_parameters(prefix, include_sublayers)
