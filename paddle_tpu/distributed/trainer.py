"""Dataset-driven trainer run loops.

Reference: paddle/fluid/framework/trainer.h:57 (MultiTrainer — one
device-worker thread per device pulling from DataFeed) and
device_worker.h:150 (HogwildWorker run loop), driven by
Executor.train_from_dataset (executor.py:1802). The pipeline counterpart
(SectionWorker, trainer.h:292) lives in paddle_tpu.parallel.pipeline as the
1F1B schedule.

TPU-native: one PROCESS drives all local chips (jax owns dispatch), so the
reference's thread-per-device fan-out collapses to a single host loop that
keeps the device fed: the C++ datafeed (csrc/datafeed) prefetches records on
reader threads, the host decodes ahead of dispatch, and the jit-compiled
train step runs async on device — the same producer/consumer structure with
XLA doing the device-side scheduling.
"""
from __future__ import annotations

from typing import Callable, Iterable, Optional

from ..core.tensor import Tensor


class DeviceWorker:
    """HogwildWorker analog: runs the train fn over a batch stream."""

    def __init__(self, train_fn: Callable, print_period: int = 100):
        self.train_fn = train_fn
        self.print_period = print_period
        self.steps = 0
        self.last_loss = None
        # a scan-fused step (parallel.ScanTrainStep) eats [K, ...] chunks
        # and returns the per-step loss vector; the run loop then advances
        # K steps per call and reports losses step-by-step
        self.scan_steps = int(getattr(train_fn, "scan_steps", 1) or 1)
        from ..profiler import ThroughputTracker
        self.throughput = ThroughputTracker()
        # goodput ledger (obs.goodput.GoodputLedger) — None keeps every
        # hook below at exactly one predicate. `ledger_phase` is what the
        # NEXT dispatch's device time books as: the resilient trainer
        # flips it to "rollback_waste" while re-running rolled-back steps
        self.ledger = None
        self.ledger_phase = "compute"
        # compile observatory (obs.compile_observatory) — None keeps the
        # hook at one predicate; when armed, every dispatch's abstract
        # signature is fingerprinted/registered before the train fn runs
        # (before, because sharded steps donate their arguments)
        self.observatory = None

    def run_step(self, batch):
        """One step: unpack the batch, run the train fn, track the loss.
        Step-level drivers (ResilientTrainer) call this directly so they
        can checkpoint/retry/rollback between steps. Over a scan-fused
        step this is one CHUNK: K steps advance and K losses report."""
        import sys
        args = batch if isinstance(batch, (tuple, list)) else (batch,)
        if self.scan_steps > 1:
            return self._run_chunk(args)
        if self.observatory is not None:
            import time
            self.observatory.observe_call(
                "train/device_worker", self.train_fn, args)
            t0 = time.perf_counter()
        if self.ledger is not None:
            with self.ledger.measure(self.ledger_phase):
                loss = self.train_fn(*args)
            self.ledger.add_steps(
                1, productive=(self.ledger_phase == "compute"))
        else:
            loss = self.train_fn(*args)
        if self.observatory is not None:
            # async dispatch: this span is launch (+ any blocking the fn
            # itself does), a floor on device execution for the registry
            import time
            self.observatory.note_device_seconds(
                "train/device_worker", time.perf_counter() - t0)
        self.steps += 1
        self.last_loss = loss
        if self.print_period and self.steps % self.print_period == 0:
            if isinstance(loss, Tensor):
                val = f"{float(loss.item()):.5f}"
            elif isinstance(loss, (int, float)):
                val = f"{float(loss):.5f}"
            else:  # train fns may return None or (loss, metrics) tuples
                val = repr(loss)
            print(f"[trainer] step {self.steps} loss {val}",
                  file=sys.stderr)
        return loss

    def _run_chunk(self, args):
        """One fused dispatch: K steps on device, per-step loss reporting
        and throughput accounting on the host."""
        import sys
        import time

        import numpy as np
        if self.observatory is not None:
            self.observatory.observe_call(
                "train/device_worker", self.train_fn, args)
        t0 = time.perf_counter()
        if self.ledger is not None:
            with self.ledger.measure(self.ledger_phase):
                loss = self.train_fn(*args)
                # materializing the loss vector blocks on the chunk, so
                # the booked span covers device compute, not dispatch
                losses = np.atleast_1d(np.asarray(
                    loss.data if isinstance(loss, Tensor) else loss))
            self.ledger.add_steps(
                losses.size, productive=(self.ledger_phase == "compute"))
        else:
            loss = self.train_fn(*args)
            losses = np.atleast_1d(np.asarray(
                loss.data if isinstance(loss, Tensor) else loss))
        dt = time.perf_counter() - t0
        self.throughput.update(steps=losses.size, seconds=dt,
                               tokens=self._chunk_tokens(args))
        if self.observatory is not None:
            # the loss vector was materialized above, so dt covers the
            # device execution of this chunk's executable
            self.observatory.note_device_seconds("train/device_worker", dt)
        for v in losses:
            self.steps += 1
            if self.print_period and self.steps % self.print_period == 0:
                print(f"[trainer] step {self.steps} loss {float(v):.5f}",
                      file=sys.stderr)
        self.last_loss = loss
        return loss

    @staticmethod
    def _chunk_tokens(args):
        """Tokens per chunk = element count of the first [K, batch, seq]
        array (the token ids); 0 when no such array is found."""
        for a in args:
            d = a.data if isinstance(a, Tensor) else a
            if getattr(d, "ndim", 0) >= 2 and hasattr(d, "size"):
                return int(d.size)
        return 0

    def run(self, batch_iter: Iterable):
        for batch in batch_iter:
            self.run_step(batch)
        return self.last_loss


class MultiTrainer:
    """trainer.h:57 analog: a dataset-driven run loop.

    usage:
        trainer = MultiTrainer(step_fn)         # e.g. a jit TrainStep
        trainer.train_from_dataset(dataset, epochs=2, batch_decoder=fn)
    dataset: an iterable (io.DataLoader, io.RecordFileDataset, generator);
    batch_decoder maps a raw record/batch to the step's arguments.
    """

    def __init__(self, train_fn: Callable, print_period: int = 100):
        self.worker = DeviceWorker(train_fn, print_period)

    def train_from_dataset(self, dataset: Iterable, epochs: int = 1,
                           batch_decoder: Optional[Callable] = None,
                           prefetch: Optional[int] = None):
        """prefetch: when the train fn is scan-fused (scan_steps > 1), wrap
        the per-step batch stream in an io.ChunkPrefetcher of this depth —
        a background thread stacks the next K batches and starts their
        sharded device_put while the current chunk computes. None/0 means
        the dataset already yields whatever the step consumes."""
        if prefetch and self.worker.scan_steps <= 1:
            raise ValueError(
                "prefetch requires a scan-fused train fn (scan_steps > 1); "
                "this train fn dispatches one step per batch")
        last = None
        for epoch in range(epochs):
            before = self.worker.steps
            it = iter(dataset)
            if batch_decoder is not None:
                it = (batch_decoder(b) for b in it)
            if prefetch:
                from ..io.prefetch import ChunkPrefetcher
                pf = ChunkPrefetcher(
                    it, scan_steps=self.worker.scan_steps,
                    put_fn=getattr(self.worker.train_fn,
                                   "device_put_chunk", None),
                    depth=int(prefetch))
                try:
                    last = self.worker.run(pf)
                finally:
                    pf.close()
            else:
                last = self.worker.run(it)
            if epochs > 1 and epoch > 0 and self.worker.steps == before:
                raise ValueError(
                    f"dataset yielded no batches in epoch {epoch + 1}: "
                    "one-shot iterators (generators) exhaust after the first "
                    "epoch — pass a re-iterable (list, DataLoader, "
                    "RecordFileDataset) for epochs > 1")
        return last

    @property
    def steps(self):
        return self.worker.steps


def train_from_dataset(train_fn, dataset, epochs=1, batch_decoder=None,
                       print_period=100, prefetch=None):
    """Executor.train_from_dataset parity entry."""
    return MultiTrainer(train_fn, print_period).train_from_dataset(
        dataset, epochs, batch_decoder, prefetch=prefetch)
