"""Dataset-driven trainer run loops.

Reference: paddle/fluid/framework/trainer.h:57 (MultiTrainer — one
device-worker thread per device pulling from DataFeed) and
device_worker.h:150 (HogwildWorker run loop), driven by
Executor.train_from_dataset (executor.py:1802). The pipeline counterpart
(SectionWorker, trainer.h:292) lives in paddle_tpu.parallel.pipeline as the
1F1B schedule.

TPU-native: one PROCESS drives all local chips (jax owns dispatch), so the
reference's thread-per-device fan-out collapses to a single host loop that
keeps the device fed: the C++ datafeed (csrc/datafeed) prefetches records on
reader threads, the host decodes ahead of dispatch, and the jit-compiled
train step runs async on device — the same producer/consumer structure with
XLA doing the device-side scheduling.
"""
from __future__ import annotations

from typing import Callable, Iterable, Optional

from ..core.tensor import Tensor


class DeviceWorker:
    """HogwildWorker analog: runs the train fn over a batch stream."""

    def __init__(self, train_fn: Callable, print_period: int = 100):
        self.train_fn = train_fn
        self.print_period = print_period
        self.steps = 0
        self.last_loss = None

    def run_step(self, batch):
        """One step: unpack the batch, run the train fn, track the loss.
        Step-level drivers (ResilientTrainer) call this directly so they
        can checkpoint/retry/rollback between steps."""
        import sys
        args = batch if isinstance(batch, (tuple, list)) else (batch,)
        loss = self.train_fn(*args)
        self.steps += 1
        self.last_loss = loss
        if self.print_period and self.steps % self.print_period == 0:
            if isinstance(loss, Tensor):
                val = f"{float(loss.item()):.5f}"
            elif isinstance(loss, (int, float)):
                val = f"{float(loss):.5f}"
            else:  # train fns may return None or (loss, metrics) tuples
                val = repr(loss)
            print(f"[trainer] step {self.steps} loss {val}",
                  file=sys.stderr)
        return loss

    def run(self, batch_iter: Iterable):
        for batch in batch_iter:
            self.run_step(batch)
        return self.last_loss


class MultiTrainer:
    """trainer.h:57 analog: a dataset-driven run loop.

    usage:
        trainer = MultiTrainer(step_fn)         # e.g. a jit TrainStep
        trainer.train_from_dataset(dataset, epochs=2, batch_decoder=fn)
    dataset: an iterable (io.DataLoader, io.RecordFileDataset, generator);
    batch_decoder maps a raw record/batch to the step's arguments.
    """

    def __init__(self, train_fn: Callable, print_period: int = 100):
        self.worker = DeviceWorker(train_fn, print_period)

    def train_from_dataset(self, dataset: Iterable, epochs: int = 1,
                           batch_decoder: Optional[Callable] = None):
        last = None
        for epoch in range(epochs):
            before = self.worker.steps
            it = iter(dataset)
            if batch_decoder is not None:
                it = (batch_decoder(b) for b in it)
            last = self.worker.run(it)
            if epochs > 1 and epoch > 0 and self.worker.steps == before:
                raise ValueError(
                    f"dataset yielded no batches in epoch {epoch + 1}: "
                    "one-shot iterators (generators) exhaust after the first "
                    "epoch — pass a re-iterable (list, DataLoader, "
                    "RecordFileDataset) for epochs > 1")
        return last

    @property
    def steps(self):
        return self.worker.steps


def train_from_dataset(train_fn, dataset, epochs=1, batch_decoder=None,
                       print_period=100):
    """Executor.train_from_dataset parity entry."""
    return MultiTrainer(train_fn, print_period).train_from_dataset(
        dataset, epochs, batch_decoder)
