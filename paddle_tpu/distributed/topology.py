"""Hybrid-parallel topology — the mesh abstraction.

Reference: python/paddle/distributed/fleet/base/topology.py:36 (CommunicateTopology,
N-D cartesian rank mesh) and :117 (HybridCommunicateGroup building dp/mp/pp/sharding
groups). The API is kept verbatim; TPU-natively the topology *is* a
jax.sharding.Mesh — `build_mesh()` returns one with axes named after the topology
dims, and every "communication group" is just an axis name for psum/ppermute under
shard_map (no comm objects, no ring ids).
"""
from __future__ import annotations

import itertools
from functools import reduce
from typing import Dict, List, Optional, Sequence

import numpy as np

import jax
from jax.sharding import Mesh


class ParallelMode:
    DATA_PARALLEL = 0
    TENSOR_PARALLEL = 1
    PIPELINE_PARALLEL = 2
    SHARDING_PARALLEL = 3


class CommunicateTopology:
    def __init__(self, hybrid_group_names=("data", "pipe", "sharding", "model"),
                 dims=(1, 1, 1, 1)):
        self._parallel_names = list(hybrid_group_names)
        self._dims = list(dims)
        self.coordinate = collections_namedtuple(self._parallel_names)
        self._world = np.arange(int(np.prod(self._dims))).reshape(self._dims)
        ranks = list(itertools.product(*(range(d) for d in self._dims)))
        self._coord2rank = {c: int(self._world[c]) for c in ranks}
        self._rank2coord = {v: k for k, v in self._coord2rank.items()}

    def get_hybrid_group_names(self):
        return self._parallel_names

    def get_dim(self, axis_name):
        return self._dims[self._parallel_names.index(axis_name)]

    get_dim_size = get_dim

    def world_size(self):
        return int(self._world.size)

    def get_rank(self, **kwargs):
        coord = tuple(kwargs[name] for name in self._parallel_names)
        return self._coord2rank[coord]

    def get_coord(self, rank):
        return self.coordinate(*self._rank2coord[rank])

    def get_axis_list(self, axis_name, index):
        """All ranks whose coordinate on `axis_name` equals index."""
        axis = self._parallel_names.index(axis_name)
        return sorted(int(r) for c, r in self._coord2rank.items()
                      if c[axis] == index)

    def get_comm_list(self, axis_name):
        """List of rank-groups along `axis_name` (reference topology.py:86)."""
        axis = self._parallel_names.index(axis_name)
        other_dims = [d for i, d in enumerate(self._dims) if i != axis]
        groups = []
        for other in itertools.product(*(range(d) for d in other_dims)):
            group = []
            for i in range(self._dims[axis]):
                coord = list(other)
                coord.insert(axis, i)
                group.append(self._coord2rank[tuple(coord)])
            groups.append(group)
        return groups

    def get_rank_from_stage(self, global_rank, **kwargs):
        coord = self.get_coord(global_rank)
        tf = coord._replace(**kwargs)._asdict()
        return self.get_rank(**tf)


def collections_namedtuple(names):
    import collections
    return collections.namedtuple("Coordinate", names)


class HybridCommunicateGroup:
    """Reference topology.py:117. Holds per-axis "groups" — here lightweight
    _AxisGroup handles naming a mesh axis — plus the rank bookkeeping models use
    (degree/rank per parallelism kind)."""

    def __init__(self, topology: CommunicateTopology, global_rank: int = None):
        from .parallel_env import ParallelEnv
        self._topo = topology
        self.global_rank = (global_rank if global_rank is not None
                            else ParallelEnv().rank)
        self.nranks = topology.world_size()

        self._dp_degree = topology.get_dim("data")
        self._pp_degree = topology.get_dim("pipe")
        self._sharding_degree = topology.get_dim("sharding")
        self._mp_degree = topology.get_dim("model")

        coord = topology.get_coord(self.global_rank % max(self.nranks, 1))
        self._dp_rank = coord.data
        self._pp_rank = coord.pipe
        self._sharding_rank = coord.sharding
        self._mp_rank = coord.model

        self._dp_group = _AxisGroup("data", topology, self.global_rank)
        self._pp_group = _AxisGroup("pipe", topology, self.global_rank)
        self._sharding_group = _AxisGroup("sharding", topology,
                                          self.global_rank)
        self._mp_group = _AxisGroup("model", topology, self.global_rank)
        # parity-plus axes (absent from the reference topology.py:36): expert
        # parallel (alltoall primitive, reference collective.py:1456) and
        # sequence parallel
        names = topology.get_hybrid_group_names()
        self._ep_degree = topology.get_dim("ep") if "ep" in names else 1
        self._ep_rank = getattr(coord, "ep", 0) if "ep" in names else 0
        self._ep_group = (_AxisGroup("ep", topology, self.global_rank)
                          if "ep" in names else None)
        self._sep_degree = topology.get_dim("sep") if "sep" in names else 1

    # parallel mode dispatch (fleet_base distributed_model uses this)
    def get_parallel_mode(self):
        if (self._mp_degree == 1 and self._pp_degree == 1
                and self._sharding_degree == 1):
            return ParallelMode.DATA_PARALLEL
        if self._pp_degree > 1:
            return ParallelMode.PIPELINE_PARALLEL
        if self._mp_degree > 1:
            return ParallelMode.TENSOR_PARALLEL
        return ParallelMode.SHARDING_PARALLEL

    def topology(self):
        return self._topo

    def get_global_rank(self):
        return self.global_rank

    # data parallel
    def get_data_parallel_rank(self):
        return self._dp_rank

    def get_data_parallel_world_size(self):
        return self._dp_degree

    def get_data_parallel_group(self):
        return self._dp_group

    def get_data_parallel_group_src_rank(self):
        return self._dp_group.ranks[0]

    # model (tensor) parallel
    def get_model_parallel_rank(self):
        return self._mp_rank

    def get_model_parallel_world_size(self):
        return self._mp_degree

    def get_model_parallel_group(self):
        return self._mp_group

    def get_model_parallel_group_src_rank(self):
        return self._mp_group.ranks[0]

    # pipeline parallel
    def get_stage_id(self):
        return self._pp_rank

    def get_pipe_parallel_rank(self):
        return self._pp_rank

    def get_pipe_parallel_world_size(self):
        return self._pp_degree

    def get_pipe_parallel_group(self):
        return self._pp_group

    def is_first_stage(self):
        return self._pp_rank == 0

    def is_last_stage(self):
        return self._pp_rank == self._pp_degree - 1

    # sharding
    def get_sharding_parallel_rank(self):
        return self._sharding_rank

    def get_sharding_parallel_world_size(self):
        return self._sharding_degree

    def get_sharding_parallel_group(self):
        return self._sharding_group

    def get_sharding_parallel_group_src_rank(self):
        return self._sharding_group.ranks[0]

    # expert parallel (parity-plus)
    def get_expert_parallel_rank(self):
        return self._ep_rank

    def get_expert_parallel_world_size(self):
        return self._ep_degree

    def get_expert_parallel_group(self):
        return self._ep_group

    # p2p neighbours (reference _build_p2p_lists:173)
    def get_p2p_groups(self):
        prev_stage = (self._pp_rank - 1) % self._pp_degree
        next_stage = (self._pp_rank + 1) % self._pp_degree
        return prev_stage, next_stage

    # mesh factory — the TPU-native heart of the topology
    def build_mesh(self, devices=None) -> Mesh:
        return build_mesh_from_dims(
            dict(zip(self._topo.get_hybrid_group_names(), self._topo._dims)),
            devices)


class _AxisGroup:
    """A "communication group" = a named mesh axis + its rank list."""

    def __init__(self, axis_name: str, topo: CommunicateTopology,
                 global_rank: int):
        self.axis_name = axis_name
        self._topo = topo
        coord = topo.get_coord(global_rank % max(topo.world_size(), 1))
        idx = topo.get_hybrid_group_names().index(axis_name)
        # the group containing global_rank along this axis
        fixed = {n: getattr(coord, n) for n in topo.get_hybrid_group_names()
                 if n != axis_name}
        self.ranks = [topo.get_rank(**{**fixed, axis_name: i})
                      for i in range(topo.get_dim(axis_name))]
        self.nranks = len(self.ranks)
        self.rank = self.ranks.index(global_rank) if global_rank in self.ranks \
            else -1
        self.id = idx + 1  # ring-id analog; 0 is the global group

    @property
    def world_size(self):
        return self.nranks

    def get_group_rank(self, rank):
        return self.ranks.index(rank) if rank in self.ranks else -1

    def __repr__(self):
        return f"Group(axis={self.axis_name}, ranks={self.ranks})"


def build_mesh_from_dims(dims: Dict[str, int], devices=None) -> Mesh:
    """Create a jax Mesh with the given {axis: size} layout.

    Axis order follows the dict (reference order: data, pipe, sharding, model).
    Axes of size 1 are kept so PartitionSpecs can always name them. On real TPU
    slices the default device order already follows the physical torus; the
    innermost axis (model) gets the fastest-varying devices → TP collectives ride
    the shortest ICI hops.
    """
    devs = list(devices) if devices is not None else jax.devices()
    total = reduce(lambda a, b: a * b, dims.values(), 1)
    if total > len(devs):
        raise ValueError(
            f"topology {dims} needs {total} devices, have {len(devs)}")
    arr = np.array(devs[:total]).reshape(tuple(dims.values()))
    return Mesh(arr, tuple(dims.keys()))


_GLOBAL_HCG: List[Optional[HybridCommunicateGroup]] = [None]
_GLOBAL_MESH: List[Optional[Mesh]] = [None]


def set_hybrid_communicate_group(hcg: HybridCommunicateGroup):
    _GLOBAL_HCG[0] = hcg
    _GLOBAL_MESH[0] = hcg.build_mesh()


def get_hybrid_communicate_group() -> Optional[HybridCommunicateGroup]:
    return _GLOBAL_HCG[0]


def get_mesh() -> Optional[Mesh]:
    return _GLOBAL_MESH[0]


def set_mesh(mesh: Mesh):
    _GLOBAL_MESH[0] = mesh
