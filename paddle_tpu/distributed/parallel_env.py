"""Process/cluster environment.

Reference: python/paddle/distributed/parallel.py:58 (init_parallel_env) +
imperative/nccl_context.cc:53 (TCP bootstrap of nccl ids) +
fleet/base/role_maker.py:794 (PADDLE_TRAINER_* env discovery).

TPU-native: jax.distributed.initialize replaces the whole unique-id TCP dance; one
process per *host* (not per device), with jax.process_index() as the node rank and
all local TPU chips visible. The reference env vars are still honored so launch
scripts port unchanged.
"""
from __future__ import annotations

import os

import jax


_INITIALIZED = [False]


class ParallelEnv:
    """paddle.distributed.ParallelEnv parity."""

    def __init__(self):
        self._rank = int(os.getenv("PADDLE_TRAINER_ID", "0"))
        self._world_size = int(os.getenv("PADDLE_TRAINERS_NUM", "1"))
        self._device_id = int(os.getenv("FLAGS_selected_tpus",
                                        os.getenv("FLAGS_selected_gpus", "0")))
        endpoints = os.getenv("PADDLE_TRAINER_ENDPOINTS", "")
        self._trainer_endpoints = endpoints.split(",") if endpoints else []
        self._current_endpoint = os.getenv("PADDLE_CURRENT_ENDPOINT", "")

    @property
    def rank(self):
        if _INITIALIZED[0]:
            return jax.process_index()
        return self._rank

    @property
    def world_size(self):
        if _INITIALIZED[0]:
            return jax.process_count()
        return self._world_size

    @property
    def device_id(self):
        return self._device_id

    @property
    def trainer_endpoints(self):
        return self._trainer_endpoints

    @property
    def current_endpoint(self):
        return self._current_endpoint

    # legacy aliases
    local_rank = rank
    nranks = world_size


def init_parallel_env():
    """Bootstrap multi-host jax. Single-host (or already-initialized) is a no-op.

    Honors PADDLE_TRAINER_ENDPOINTS (rank-0 endpoint = coordinator) so
    `paddle.distributed.launch`-style scripts work unchanged.
    """
    if _INITIALIZED[0]:
        return ParallelEnv()
    env = ParallelEnv()
    n_procs = int(os.getenv("PADDLE_TRAINERS_NUM", "1"))
    if n_procs > 1 and env.trainer_endpoints:
        coordinator = env.trainer_endpoints[0]
        jax.distributed.initialize(
            coordinator_address=coordinator,
            num_processes=n_procs,
            process_id=int(os.getenv("PADDLE_TRAINER_ID", "0")))
        _INITIALIZED[0] = True
    return env


def get_rank(group=None):
    if group is not None:
        return group.get_group_rank(ParallelEnv().rank)
    return ParallelEnv().rank


def get_world_size(group=None):
    if group is not None:
        return group.nranks
    if _INITIALIZED[0]:
        return jax.process_count()
    return int(os.getenv("PADDLE_TRAINERS_NUM", "1"))


def is_initialized():
    return _INITIALIZED[0]
