"""DataParallel (reference: python/paddle/fluid/dygraph/parallel.py:382 +
imperative/reducer.cc:289).

The reference buckets grads into comm_buffer_size-MB groups and overlaps NCCL
allreduce with backward via hooks. TPU-native: under pjit with the batch axis
sharded on `data`, the gradient psum is inserted by XLA and fused/overlapped by the
scheduler — bucketing is subsumed. This wrapper therefore:
  - eager single-process: transparent passthrough (grad sync is a no-op at size 1);
  - functional path: `sync_gradients_fn` gives the explicit psum/pmean used by the
    shard_map-based runners for reducer-parity semantics (scale 1/N like
    parallel.py:588 scale_loss).
"""
from __future__ import annotations

import jax
from jax import lax

from ..core.tensor import Tensor
from ..nn.layer.layers import Layer
from .collective import in_axis_context, current_axes
from .parallel_env import get_world_size


class DataParallel(Layer):
    def __init__(self, layers, strategy=None, comm_buffer_size=25,
                 last_comm_buffer_size=1, find_unused_parameters=False,
                 group=None):
        super().__init__()
        self._layers = layers
        self.comm_buffer_size = comm_buffer_size
        self.find_unused_parameters = find_unused_parameters

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    def scale_loss(self, loss):
        # parallel.py:588 — with SPMD pmean the 1/N scale is inside the psum
        return loss

    def apply_collective_grads(self):
        """reducer.cc FusedAllReduceSchedule analog for the eager multi-process
        path: average grads across jax processes. No-op at world 1; under the
        functional runners gradient sync happens inside the step (pmean).

        Like the reference's fused buckets, all grads go through ONE
        collective: flatten-concat, single allgather, mean, unflatten."""
        import jax
        if in_axis_context() or jax.process_count() <= 1:
            return
        import jax.numpy as jnp
        with_grad = [p for p in self._layers.parameters()
                     if p.grad is not None]
        if not with_grad:
            return
        # comm_buffer_size-MB buckets (reference default 25MB): bounds the
        # transient (P, bucket) gather to bucket_bytes x process_count
        buckets = _bucket_grads(with_grad, self.comm_buffer_size)
        # one all-REDUCE per bucket (reducer.cc ncclAllReduce parity): a
        # [n_dev, n] array sharded over a device mesh, mean over the device
        # dim with a replicated output — GSPMD lowers this to all-reduce,
        # n bytes on the wire instead of process_allgather's P x n
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
        mesh, reduce_fn = _device_mean_reducer()
        devs = jax.devices()
        for group in buckets:
            flat = jnp.concatenate(
                [p.grad.data.astype(jnp.float32).reshape(-1) for p in group])
            row = flat[None]
            shards = [jax.device_put(row, d) for d in jax.local_devices()]
            garr = jax.make_array_from_single_device_arrays(
                (len(devs),) + flat.shape,
                NamedSharding(mesh, P("p")), shards)
            mean_arr = reduce_fn(garr)
            mean = jnp.asarray(mean_arr.addressable_data(0))
            offset = 0
            for p in group:
                n = p.grad.data.size
                p.grad.data = mean[offset:offset + n].reshape(
                    p.grad.data.shape).astype(p.grad.data.dtype)
                offset += n

    # passthrough conveniences
    def state_dict(self, *args, **kwargs):
        return self._layers.state_dict(*args, **kwargs)

    def set_state_dict(self, *args, **kwargs):
        return self._layers.set_state_dict(*args, **kwargs)

    def parameters(self, include_sublayers=True):
        return self._layers.parameters(include_sublayers)

    def named_parameters(self, prefix="", include_sublayers=True):
        return self._layers.named_parameters(prefix, include_sublayers)


def _bucket_grads(params, comm_buffer_size_mb):
    """Group params-with-grads into ~comm_buffer_size-MB buckets sized by
    the grads' ACTUAL bytes (size * dtype.itemsize). The old rule divided
    the MB cap by a hard-coded 4 bytes/element, so bf16/fp16 grads filled
    buckets to 2x the configured transient-memory bound."""
    import numpy as np
    cap_bytes = max(int(comm_buffer_size_mb * 1024 * 1024), 1)
    buckets, bucket, bucket_bytes = [], [], 0
    for p in params:
        bucket.append(p)
        g = p.grad.data
        bucket_bytes += int(g.size) * int(np.dtype(g.dtype).itemsize)
        if bucket_bytes >= cap_bytes:
            buckets.append(bucket)
            bucket, bucket_bytes = [], 0
    if bucket:
        buckets.append(bucket)
    return buckets


_REDUCER_CACHE = []


def _device_mean_reducer():
    """Module-cached (mesh, jitted mean-over-devices): rebuilt only if the
    device set changes, so per-step grad sync hits the jit cache."""
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    devs = tuple(jax.devices())
    if _REDUCER_CACHE and _REDUCER_CACHE[0][0] == devs:
        return _REDUCER_CACHE[0][1], _REDUCER_CACHE[0][2]
    mesh = Mesh(np.array(devs), ("p",))
    import jax.numpy as jnp
    fn = jax.jit(lambda x: jnp.mean(x, axis=0),
                 out_shardings=NamedSharding(mesh, P()))
    _REDUCER_CACHE.clear()
    _REDUCER_CACHE.append((devs, mesh, fn))
    return mesh, fn


def sync_gradients_fn(axis: str = "data", average: bool = True,
                      comm_dtype: str | None = None):
    """Pure fn(grads_pytree) -> synced grads; used inside shard_map steps.

    comm_dtype (strategy.fp16_allreduce, fp16_allreduce_optimizer.py:148):
    fp32 grads are cast to the reduced dtype BEFORE the collective and back
    after — here the collective is explicit, so the cast genuinely halves the
    bytes on the wire."""
    import jax.numpy as jnp
    cd = jnp.dtype(comm_dtype) if comm_dtype else None

    def sync(grads):
        op = lax.pmean if average else lax.psum

        def one(g):
            if cd is not None and g.dtype == jnp.float32:
                return op(g.astype(cd), axis).astype(g.dtype)
            return op(g, axis)

        return jax.tree_util.tree_map(one, grads)

    return sync
