"""DataParallel (reference: python/paddle/fluid/dygraph/parallel.py:382 +
imperative/reducer.cc:289).

The reference buckets grads into comm_buffer_size-MB groups and overlaps NCCL
allreduce with backward via hooks. TPU-native: under pjit with the batch axis
sharded on `data`, the gradient psum is inserted by XLA and fused/overlapped by the
scheduler — bucketing is subsumed. This wrapper therefore:
  - eager single-process: transparent passthrough (grad sync is a no-op at size 1);
  - functional path: `sync_gradients_fn` gives the explicit psum/pmean used by the
    shard_map-based runners for reducer-parity semantics (scale 1/N like
    parallel.py:588 scale_loss).
"""
from __future__ import annotations

import jax
from jax import lax

from ..core.tensor import Tensor
from ..nn.layer.layers import Layer
from .collective import in_axis_context, current_axes
from .parallel_env import get_world_size


class DataParallel(Layer):
    def __init__(self, layers, strategy=None, comm_buffer_size=25,
                 last_comm_buffer_size=1, find_unused_parameters=False,
                 group=None):
        super().__init__()
        self._layers = layers
        self.comm_buffer_size = comm_buffer_size
        self.find_unused_parameters = find_unused_parameters
        # quantized bucket reduce (strategy.quant_allreduce /
        # FLAGS_quant_allreduce; distributed/compression.py)
        from .strategy import QuantAllreduceConfig
        quant_on = bool(strategy is not None
                        and getattr(strategy, "quant_allreduce", False))
        if not quant_on:
            from ..flags import get_flags
            quant_on = bool(
                get_flags("FLAGS_quant_allreduce")["FLAGS_quant_allreduce"])
        self._comm_quant = None
        if quant_on:
            cfg = getattr(strategy, "quant_allreduce_configs", None)
            self._comm_quant = (
                cfg if isinstance(cfg, QuantAllreduceConfig)
                else QuantAllreduceConfig()).validate()
        self._sync_calls = 0

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    def scale_loss(self, loss):
        # parallel.py:588 — with SPMD pmean the 1/N scale is inside the psum
        return loss

    def apply_collective_grads(self):
        """reducer.cc FusedAllReduceSchedule analog for the eager multi-process
        path: average grads across jax processes. No-op at world 1; under the
        functional runners gradient sync happens inside the step (pmean).

        Like the reference's fused buckets, all grads go through ONE
        collective: flatten-concat, single allgather, mean, unflatten."""
        import jax
        if in_axis_context() or jax.process_count() <= 1:
            return
        import jax.numpy as jnp
        with_grad = [p for p in self._layers.parameters()
                     if p.grad is not None]
        if not with_grad:
            return
        # comm_buffer_size-MB buckets (reference default 25MB): bounds the
        # transient (P, bucket) gather to bucket_bytes x process_count.
        # Buckets are grouped by grad dtype so each concat/reduce runs in the
        # bucket's NATIVE dtype — the old fp32 up-cast doubled bf16/fp16 wire
        # bytes and defeated _bucket_grads' dtype-aware byte accounting
        buckets = _bucket_grads(with_grad, self.comm_buffer_size)
        self._sync_calls += 1
        for group in buckets:
            flat = jnp.concatenate(
                [p.grad.data.reshape(-1) for p in group])
            if (self._comm_quant is not None
                    and jnp.issubdtype(flat.dtype, jnp.floating)
                    and flat.size >= self._comm_quant.min_quant_numel):
                mean = _quantized_bucket_mean(
                    flat, self._comm_quant, self._sync_calls)
            else:
                mean = _bucket_mean(flat)
            offset = 0
            for p in group:
                n = p.grad.data.size
                p.grad.data = mean[offset:offset + n].reshape(
                    p.grad.data.shape).astype(p.grad.data.dtype)
                offset += n

    # passthrough conveniences
    def state_dict(self, *args, **kwargs):
        return self._layers.state_dict(*args, **kwargs)

    def set_state_dict(self, *args, **kwargs):
        return self._layers.set_state_dict(*args, **kwargs)

    def parameters(self, include_sublayers=True):
        return self._layers.parameters(include_sublayers)

    def named_parameters(self, prefix="", include_sublayers=True):
        return self._layers.named_parameters(prefix, include_sublayers)


def _bucket_grads(params, comm_buffer_size_mb):
    """Group params-with-grads into ~comm_buffer_size-MB buckets sized by
    the grads' ACTUAL bytes (size * dtype.itemsize). The old rule divided
    the MB cap by a hard-coded 4 bytes/element, so bf16/fp16 grads filled
    buckets to 2x the configured transient-memory bound.

    Buckets never mix dtypes (reducer.cc groups by dtype for the same
    reason): a mixed bucket would force a common-dtype concat — in practice
    an fp32 up-cast that doubles half-precision wire bytes."""
    import numpy as np
    cap_bytes = max(int(comm_buffer_size_mb * 1024 * 1024), 1)
    by_dtype = {}
    order = []
    for p in params:
        dt = np.dtype(p.grad.data.dtype)
        if dt not in by_dtype:
            by_dtype[dt] = []
            order.append(dt)
        by_dtype[dt].append(p)
    buckets = []
    for dt in order:
        bucket, bucket_bytes = [], 0
        for p in by_dtype[dt]:
            bucket.append(p)
            bucket_bytes += int(p.grad.data.size) * int(dt.itemsize)
            if bucket_bytes >= cap_bytes:
                buckets.append(bucket)
                bucket, bucket_bytes = [], 0
        if bucket:
            buckets.append(bucket)
    return buckets


_REDUCER_CACHE = []
_QREDUCER_CACHE = []


def _device_mean_reducer():
    """Module-cached (mesh, jitted mean-over-devices): rebuilt only if the
    device set changes, so per-step grad sync hits the jit cache. The mean
    accumulates in fp32 but the rows keep their native dtype, so the
    cross-device gather the out_sharding forces moves native-width bytes."""
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    devs = tuple(jax.devices())
    if _REDUCER_CACHE and _REDUCER_CACHE[0][0] == devs:
        return _REDUCER_CACHE[0][1], _REDUCER_CACHE[0][2]
    mesh = Mesh(np.array(devs), ("p",))
    import jax.numpy as jnp
    fn = jax.jit(
        lambda x: jnp.mean(x, axis=0, dtype=jnp.float32).astype(x.dtype),
        out_shardings=NamedSharding(mesh, P()))
    _REDUCER_CACHE.clear()
    _REDUCER_CACHE.append((devs, mesh, fn))
    return mesh, fn


def _device_quant_reducer():
    """Like _device_mean_reducer but over (int8 payload, bf16 scales) rows:
    dequant + mean happens AFTER the replicating gather, so the wire moves
    quantized bytes."""
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    devs = tuple(jax.devices())
    if _QREDUCER_CACHE and _QREDUCER_CACHE[0][0] == devs:
        return _QREDUCER_CACHE[0][1], _QREDUCER_CACHE[0][2]
    mesh = Mesh(np.array(devs), ("p",))
    import jax.numpy as jnp
    from .compression import dequantize_blockwise
    fn = jax.jit(
        lambda p, s: jnp.mean(dequantize_blockwise(p, s), axis=0),
        out_shardings=NamedSharding(mesh, P()))
    _QREDUCER_CACHE.clear()
    _QREDUCER_CACHE.append((devs, mesh, fn))
    return mesh, fn


def _rows_to_global(row, mesh):
    """[1, ...] local row -> [n_dev, ...] process-sharded global array."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    devs = jax.devices()
    shards = [jax.device_put(row, d) for d in jax.local_devices()]
    return jax.make_array_from_single_device_arrays(
        (len(devs),) + row.shape[1:], NamedSharding(mesh, P("p")), shards)


def _bucket_mean(flat):
    """One all-REDUCE per bucket (reducer.cc ncclAllReduce parity): a
    [n_dev, n] array sharded over a device mesh, mean over the device dim
    with a replicated output — GSPMD lowers this to all-reduce, n bytes on
    the wire instead of process_allgather's P x n."""
    import jax.numpy as jnp
    mesh, reduce_fn = _device_mean_reducer()
    return jnp.asarray(reduce_fn(_rows_to_global(flat[None], mesh))
                       .addressable_data(0))


def _quantized_bucket_mean(flat, cfg, call_count):
    """Quantized bucket reduce (the plain quantized-pmean fallback for the
    eager path — shard_map runners get the true RS+AG in
    compression.quantized_allreduce): each process quantizes its OWN
    flattened bucket before the collective, so the wire moves int8 payload
    + bf16 blockwise scales (~4x fewer bytes); dequant + mean runs after."""
    import jax.numpy as jnp
    from .compression import quantize_bucket_host
    n = flat.shape[0]
    key = jax.random.fold_in(jax.random.PRNGKey(call_count),
                             jax.process_index())
    payload, scales, _ = quantize_bucket_host(
        flat.astype(jnp.float32), cfg, key)
    mesh, reduce_fn = _device_quant_reducer()
    mean = reduce_fn(_rows_to_global(payload[None], mesh),
                     _rows_to_global(scales[None], mesh))
    return jnp.asarray(mean.addressable_data(0))[:n]


def sync_gradients_fn(axis: str = "data", average: bool = True,
                      comm_dtype: str | None = None, comm_quant=None):
    """Pure fn(grads_pytree) -> synced grads; used inside shard_map steps.

    comm_dtype (strategy.fp16_allreduce, fp16_allreduce_optimizer.py:148):
    fp32 grads are cast to the reduced dtype BEFORE the collective and back
    after — here the collective is explicit, so the cast genuinely halves the
    bytes on the wire.

    comm_quant (strategy.quant_allreduce): a QuantAllreduceConfig routes
    every large-enough leaf through compression.quantized_allreduce — the
    blockwise int8 reduce-scatter + all-gather (~4x fewer wire bytes than
    fp32, ~2x fewer than comm_dtype). Supersedes comm_dtype when both are
    set. `key=` on the returned sync fn seeds the stochastic rounding."""
    import jax.numpy as jnp
    cd = jnp.dtype(comm_dtype) if comm_dtype else None

    if comm_quant is not None:
        from .compression import quantized_pmean

        def sync_q(grads, key=None):
            return quantized_pmean(grads, axis, comm_quant, key,
                                   average=average)

        return sync_q

    def sync(grads):
        op = lax.pmean if average else lax.psum

        def one(g):
            if cd is not None and g.dtype == jnp.float32:
                return op(g.astype(cd), axis).astype(g.dtype)
            return op(g, axis)

        return jax.tree_util.tree_map(one, grads)

    return sync
