"""Elastic membership management (reference: fleet/elastic.py:90 —
ElasticManager registers hosts in etcd, watches for scale-in/out, rewrites
PADDLE_TRAINER_ENDPOINTS and relaunches the local trainers).

etcd-free TPU redesign: membership lives in the launcher's own KV server
(fleet/utils/http_server.py) hosted by node 0. Every node heartbeats its
endpoint under /elastic/node/<idx>; the manager polls the full membership,
and a change (join, leave, heartbeat expiry) triggers an endpoint rewrite +
relaunch. Training state survives through checkpoint auto-resume
(paddle_tpu.checkpoint), which is the same recovery contract as the
reference's auto_checkpoint + relaunch."""
from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional


def _text(v):
    """KV values arrive as bytes (_LocalKV) or str (HTTP KVClient)."""
    return v.decode() if isinstance(v, bytes) else v


class ElasticStatus:
    COMPLETED = "completed"
    ERROR = "error"
    HOLD = "hold"
    RESTART = "restart"
    EXIT = "exit"


class _LocalKV:
    """In-process KV with the KVClient interface (tests / single host)."""

    def __init__(self):
        self._kv: Dict[str, bytes] = {}
        self._lock = threading.Lock()

    def put(self, key, value):
        with self._lock:
            self._kv[key] = value if isinstance(value, bytes) else \
                value.encode()

    def get(self, key):
        with self._lock:
            v = self._kv.get(key)
        return v

    def delete(self, key):
        with self._lock:
            self._kv.pop(key, None)

    def keys(self, prefix):
        with self._lock:
            return [k for k in self._kv if k.startswith(prefix)]


class ElasticManager:
    """Membership watcher (elastic.py:90 analog).

    kv: a KVClient-like object (put/get/delete); node 0 usually runs the
    KVServer. heartbeat entries carry a timestamp; entries older than
    `timeout` count as dead (etcd lease-TTL analog).
    """

    PREFIX = "/elastic/node/"

    def __init__(self, host_endpoint: str, kv=None, np_range=(1, None),
                 timeout: float = 10.0,
                 on_restart: Optional[Callable[[List[str]], None]] = None,
                 kv_retries: int = 3, kv_backoff: float = 0.1,
                 expiry_grace: Optional[int] = None):
        if expiry_grace is None:
            from ..flags import get_flags
            expiry_grace = get_flags("FLAGS_elastic_expiry_grace")[
                "FLAGS_elastic_expiry_grace"]
        self.endpoint = host_endpoint
        self.kv = kv if kv is not None else _LocalKV()
        self.min_np, self.max_np = np_range
        self.timeout = timeout
        self.on_restart = on_restart
        self.hosts: List[str] = []
        self._beat_stop = threading.Event()
        self._beat_thread: Optional[threading.Thread] = None
        # hardening: transient KV hiccups must not look like mass death.
        # KV ops retry with bounded backoff, and a previously-alive host is
        # only declared dead after `expiry_grace` consecutive stale polls.
        self._kv_retries = kv_retries
        self._kv_backoff = kv_backoff
        self.expiry_grace = max(1, int(expiry_grace))
        self._miss_counts: Dict[str, int] = {}

    def _kv_call(self, fn, *args):
        """Run a KV op with bounded exponential-backoff retry; transient
        server hiccups (connection reset, restart) self-heal instead of
        bubbling up as membership events."""
        attempt = 0
        while True:
            try:
                return fn(*args)
            except Exception:
                attempt += 1
                if attempt > self._kv_retries:
                    raise
                time.sleep(self._kv_backoff * (2 ** (attempt - 1)))

    # ---- membership registry ----
    def register(self, retry_window: float = 30.0):
        """First contact retries while the KV host (node 0) is still coming
        up — peers race the server's start."""
        deadline = time.time() + retry_window
        while True:
            try:
                self._heartbeat_once()
                self._merge_roster()
                break
            except Exception:
                if time.time() >= deadline:
                    raise
                time.sleep(0.5)
        self._beat_thread = threading.Thread(target=self._beat_loop,
                                             daemon=True)
        self._beat_thread.start()

    def _merge_roster(self):
        """HTTP KV has no key listing: nodes co-maintain a roster key
        (read-merge-write; last-writer-wins races self-heal on the next
        heartbeat since every node re-merges itself)."""
        if hasattr(self.kv, "keys"):
            return
        raw = self._kv_call(self.kv.get, self.PREFIX + "_roster")
        hosts = set(_text(raw).split(",")) - {""} if raw else set()
        if self.endpoint not in hosts:
            hosts.add(self.endpoint)
            self._kv_call(self.kv.put, self.PREFIX + "_roster",
                          ",".join(sorted(hosts)).encode())

    def _heartbeat_once(self):
        self._kv_call(self.kv.put, self.PREFIX + self.endpoint,
                      f"{time.time()}".encode())

    def _beat_loop(self):
        while not self._beat_stop.wait(self.timeout / 3):
            try:
                self._heartbeat_once()
                self._merge_roster()
            except Exception:
                pass  # transient KV outage; next beat retries

    def deregister(self):
        self._beat_stop.set()
        try:
            self.kv.delete(self.PREFIX + self.endpoint)
            if not hasattr(self.kv, "keys"):
                # drop ourselves from the co-maintained roster so polls don't
                # probe dead entries forever
                raw = self.kv.get(self.PREFIX + "_roster")
                hosts = set(_text(raw).split(",")) - {"", self.endpoint} \
                    if raw else set()
                self.kv.put(self.PREFIX + "_roster",
                            ",".join(sorted(hosts)).encode())
        except Exception:
            pass  # the KV host may already be gone during teardown

    def _host_ages(self) -> Dict[str, float]:
        """Heartbeat age in seconds per registered endpoint."""
        now = time.time()
        ages = {}
        for key in self._keys():
            raw = self.kv.get(key)
            if raw is None:
                continue
            try:
                ts = float(_text(raw))
            except ValueError:
                continue
            ages[key[len(self.PREFIX):]] = now - ts
        return ages

    def alive_hosts(self) -> List[str]:
        """Endpoints with a fresh heartbeat, sorted for stable rank order."""
        return sorted(h for h, age in self._host_ages().items()
                      if age <= self.timeout)

    def _keys(self):
        if hasattr(self.kv, "keys"):
            return self.kv.keys(self.PREFIX)
        # HTTP KVClient has no listing; nodes mirror the roster under a
        # well-known key maintained by node 0
        raw = self.kv.get(self.PREFIX + "_roster")
        if not raw:
            return []
        return [self.PREFIX + h for h in _text(raw).split(",") if h]

    # ---- watch loop (elastic.py watch + _update_hosts analog) ----
    def watch_once(self) -> str:
        """One poll: compare live membership to the last seen roster.

        Expiry hardening: a host that was in the roster keeps its seat for
        up to `expiry_grace` consecutive *slightly*-stale polls before its
        absence triggers a relaunch — one missed heartbeat (GC pause, KV
        restart, packet loss) is not a membership event. A heartbeat older
        than `timeout * expiry_grace` is past any transient hiccup and
        evicts immediately. A KV outage during the poll itself HOLDs with
        the old roster instead of reading as everyone-died."""
        try:
            ages = self._host_ages()
        except Exception:
            return ElasticStatus.HOLD  # KV unreachable: keep the old world
        alive = sorted(h for h, a in ages.items() if a <= self.timeout)
        # grace: re-add known hosts whose heartbeat is stale but young
        for h in self.hosts:
            if h in alive:
                self._miss_counts.pop(h, None)
            else:
                misses = self._miss_counts.get(h, 0) + 1
                self._miss_counts[h] = misses
                hard_dead = ages.get(h, float("inf")) \
                    > self.timeout * self.expiry_grace
                if misses < self.expiry_grace and not hard_dead:
                    alive = sorted(set(alive) | {h})
        if not alive:
            return ElasticStatus.HOLD
        if self.max_np and len(alive) > self.max_np:
            alive = alive[:self.max_np]
        if len(alive) < self.min_np:
            return ElasticStatus.HOLD  # wait for enough nodes to join
        if not self.hosts:
            self.hosts = alive
            self._update_env(alive)  # pod must start with the real world
            return ElasticStatus.COMPLETED
        if alive != self.hosts:
            old = self.hosts
            self.hosts = alive
            self._update_env(alive)
            if self.on_restart is not None:
                self.on_restart(alive)
            import sys
            print(f"[elastic] membership changed {old} -> {alive}; "
                  "relaunching", file=sys.stderr)
            return ElasticStatus.RESTART
        return ElasticStatus.COMPLETED

    def _update_env(self, hosts: List[str]):
        """Rewrite the reference env contract for the new world
        (elastic.py _update_hosts:246)."""
        import os
        os.environ["PADDLE_TRAINER_ENDPOINTS"] = ",".join(hosts)
        os.environ["PADDLE_TRAINERS_NUM"] = str(len(hosts))
        if self.endpoint in hosts:
            os.environ["PADDLE_TRAINER_ID"] = str(hosts.index(self.endpoint))

    def rank(self) -> int:
        return self.hosts.index(self.endpoint) if self.endpoint in self.hosts \
            else -1
